#!/usr/bin/env bash
# CI gate: bytecode-compile the whole package, then run the storage-tier
# test subset — including the vacuum-leak assertion (after drop + vacuum,
# ObjectStore.list() shows no orphaned SSTs) so object-store growth stays
# bounded in tests — plus the robustness subset (retry layer, sink
# decoupling, chaos) and the boundary-IO lint. Usage:
# scripts/check.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
unset PALLAS_AXON_POOL_IPS TPU_LIBRARY_PATH 2>/dev/null || true

echo "== compileall =="
python -m compileall -q risingwave_tpu

echo "== storage-tier tests =="
python -m pytest -q -p no:cacheprovider \
    tests/test_object_store.py \
    tests/test_sstable.py \
    tests/test_hummock.py \
    tests/test_compactor.py \
    tests/test_durability.py \
    tests/test_failpoints.py \
    tests/test_backup_restore.py \
    "$@"

echo "== robustness tests (retry / sink decouple / chaos) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_retry.py \
    tests/test_fault_injection.py \
    tests/test_sink_decouple.py \
    tests/test_broker.py \
    "$@"

echo "== pallas compile proxy (StableHLO/Mosaic lowering, no chip) =="
# Both TPU kernels (ops/pallas_rank.py, ops/interval_join.py) AND every
# fused-epoch surface — q8 session windows, TPC-H q3, the co-scheduled
# multi-job epoch — are lowered for platform "tpu" WITHOUT executing:
# kernel tracing errors, Mosaic-unsupported ops, block-spec mismatches
# and fused-core lowering breakage fail here even while the chip tunnel
# is down.
python -m pytest -q -p no:cacheprovider \
    tests/test_pallas_compile.py \
    "$@"

echo "== fused-epoch / interval-join / co-schedule / sharded subset =="
python -m pytest -q -p no:cacheprovider \
    tests/test_fused_epoch.py \
    tests/test_fused_q8_q3.py \
    tests/test_coschedule.py \
    tests/test_tick_compiler.py \
    tests/test_fused_sharded.py \
    tests/test_fused_sharded_ladder.py \
    tests/test_registry_coverage.py \
    tests/test_interval_join.py \
    tests/test_batched_ingest.py \
    tests/test_cli_fragments.py \
    tests/test_bench_hardening.py -m 'not slow' \
    "$@"

echo "== sharded-ladder heavy parity (slow-marked out of tier-1) =="
# the K×S group / q8 / q3 sharded checkpoint + re-shard parity runs,
# the every-builder dispatch/profiler cross-check, and the tick
# compiler's 200-small-MVs ≤8-dispatch acceptance case compile large
# programs — tier-2 per the 870s tier-1 wall budget
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_fused_sharded_ladder.py \
    tests/test_registry_coverage.py \
    tests/test_tick_compiler.py \
    "$@"

echo "== pipelined tick (async epoch pipeline, fast tier) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_pipeline.py -m 'not slow' \
    "$@"

echo "== pipelined tick heavy (kill -9 recovery + netsplit composition) =="
# real process death with a deferred flush + un-joined checkpoint
# encode, and the q5 netsplit scenario run with pipeline_depth=2 —
# slow-marked out of tier-1 per the 870s wall budget
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_pipeline.py \
    "$@"

echo "== serving-plane tests (two-phase agg + plan cache + reads) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_serving.py \
    tests/test_batch.py \
    "$@"

echo "== tier-2 heavy parity tests (slow-marked out of the tier-1 wall budget) =="
# these files are not in any other subset; their slow-marked tests
# (multi-process kills, full NEXmark replays, sharded-mesh workloads)
# would push the tier-1 run past its timeout, so they run HERE instead
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_parallel.py \
    tests/test_meta_sim.py \
    tests/test_nexmark_queries.py \
    tests/test_nexmark_extended.py \
    tests/test_ch_bench.py \
    "$@"

echo "== observability tests (profiling plane + federation + HTTP) =="
# no 'not slow' filter: the profiler-lifecycle + worker-federation +
# ctl-CLI tests are marked slow (real jax.profiler captures and
# subprocesses — too heavy for tier-1) but MUST run here
python -m pytest -q -p no:cacheprovider \
    tests/test_observability.py \
    tests/test_profiling.py \
    tests/test_dashboard.py \
    "$@"

echo "== barrier observatory (ledger + blame + telemetry catalog) =="
# no 'not slow' filter: the 2-worker federated waterfall and the
# chaos-partitioned blame acceptance run (barrier_blame + ctl
# --inflight + rw_catalog.rw_barrier_inflight over pgwire, all before
# the epoch deadline) are slow-marked but MUST run here
python -m pytest -q -p no:cacheprovider \
    tests/test_barrier_observatory.py \
    "$@"

echo "== ctl trace barrier smoke (history + --inflight + --json) =="
# end-to-end over a real durable dir: the ctl session recovers the
# catalog, serves the waterfall tables, names in-flight suspects, and
# emits machine-parseable JSON with the ledger's three sections
obs_dir=$(mktemp -d)
python - "$obs_dir" <<'EOF'
import sys
from risingwave_tpu.frontend import Session
s = Session(data_dir=sys.argv[1], checkpoint_frequency=2)
s.run_sql("CREATE TABLE obs_t (k BIGINT PRIMARY KEY, v BIGINT)")
s.run_sql("INSERT INTO obs_t VALUES (1, 10), (2, 20)")
s.flush()
assert s._barrier_ledger.history(), "ledger empty after flush"
s.close()
EOF
python -m risingwave_tpu ctl trace barrier --data-dir "$obs_dir"
python -m risingwave_tpu ctl trace barrier --data-dir "$obs_dir" --inflight
python -m risingwave_tpu ctl trace barrier --data-dir "$obs_dir" --json \
    | python -c 'import json,sys; o=json.load(sys.stdin); \
assert set(o) >= {"history","stages","summary"}, sorted(o); \
print("ctl trace barrier --json: OK")'
rm -rf "$obs_dir"

echo "== profiler-overhead smoke (0 added dispatches, bounded wall cost) =="
# The profiling plane is ON by default: assert that a profiled fused q5
# epoch still takes EXACTLY one dispatch per epoch (dispatch_count
# guards it through the profiler's wrapper) and that per-epoch wall
# overhead vs profiling-off stays within budget (<= 2ms or 50% of the
# unprofiled epoch, whichever is larger — pure host bookkeeping).
python - <<'EOF'
import time
import jax, jax.numpy as jnp
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.profiling import GLOBAL_PROFILER
from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.connector import NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
from risingwave_tpu.ops.grouped_agg import AggCore

CAP, K, EPOCHS = 128, 4, 40
gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
exprs = [call("tumble_start", col(5, TIMESTAMP),
              Literal(10_000_000, INT64)), col(0, INT64)]
core = AggCore((INT64, INT64), (0, 1), [count_star()],
               table_capacity=1 << 12, out_capacity=CAP)

def run(enabled):
    GLOBAL_PROFILER.enabled = enabled
    with count_dispatches() as c:
        fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, core, CAP)
        st = fused(core.init_state(), jnp.int64(0),
                   jax.random.PRNGKey(0), K)   # compile
        jax.block_until_ready(st.lanes)
        c.reset()
        t0 = time.perf_counter()
        for i in range(EPOCHS):
            st = fused(st, jnp.int64((i + 1) * K * CAP),
                       jax.random.PRNGKey(i + 1), K)
        jax.block_until_ready(st.lanes)
        dt = time.perf_counter() - t0
        n = c.counts["fused_source_agg_epoch.<locals>.epoch"]
    return n, dt / EPOCHS

GLOBAL_PROFILER.reset()
n_off, per_off = run(False)
n_on, per_on = run(True)
GLOBAL_PROFILER.enabled = True
assert n_off == EPOCHS and n_on == EPOCHS, \
    f"profiling changed the dispatch count: off={n_off} on={n_on}"
assert GLOBAL_PROFILER.counts()[
    "fused_source_agg_epoch.<locals>.epoch"] >= EPOCHS
budget = max(0.002, per_off * 0.5)
overhead = per_on - per_off
assert overhead <= budget, (
    f"profiler overhead {overhead*1e3:.3f}ms/epoch exceeds budget "
    f"{budget*1e3:.3f}ms (off={per_off*1e3:.3f}ms on={per_on*1e3:.3f}ms)")
print(f"profiler overhead OK: {max(overhead,0)*1e3:.3f}ms/epoch "
      f"(epoch {per_off*1e3:.3f}ms, {EPOCHS} epochs, 0 added dispatches)")
EOF

echo "== bench smoke (single tiny phase, 1-dispatch invariants) =="
# seconds, not minutes: fused q5/q8/q3 epochs + a 4-job co-scheduled
# group run end to end on the CPU backend with the
# one-dispatch-per-epoch invariant asserted (bench.py --smoke) — plus
# the serving-cache invariant: a repeated identical SELECT creates 0
# new jit wrappers, and a version-bump re-execution creates 0 too
python bench.py --smoke

echo "== distribution tests (cross-worker fragment graphs) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_distributed.py \
    tests/test_multiprocess.py \
    "$@"

echo "== scaling tests (live vnode migration + autoscaler) =="
python -m pytest -q -p no:cacheprovider \
    tests/test_rescale_live.py -m 'not slow' \
    "$@"

echo "== network fault plane (chaos subset) =="
# Unit surface (schedules, seq dedup/reorder, keepalive eviction,
# auditor), then one FAST seeded netsplit scenario run twice to assert
# the identical-injection-trace replay property, then a bounded
# crash-point sweep (die at four failpoint sites, audit after each).
# The full acceptance surface — q5 partition, every registered site,
# the spanning 2PC sweep — is tests/test_chaos.py (slow-marked).
python -m pytest -q -p no:cacheprovider \
    tests/test_net_faults.py \
    "$@"
python -m risingwave_tpu.sim --netsplit exchange_dup_reorder \
    --seed 7 --replay
python -m risingwave_tpu.sim --sweep \
    --sites checkpoint.segment.write,checkpoint.commit,sink.deliver,meta.store.txn

echo "== UDF isolation plane (out-of-process user code, fast tier) =="
# wire codecs, function shipping, bit-exact parity inproc vs process,
# restart semantics (deadline trip, deterministic kill -9 mid-batch,
# reply-after-fence, typed errors, backpressure) — docs/robustness.md
python -m pytest -q -p no:cacheprovider \
    tests/test_udf_plane.py -m 'not slow' \
    "$@"

echo "== UDF chaos / soak (server kills + auditor + soak seed — tier-2) =="
# the seeded udf-link chaos scenario + replay determinism, the
# kill-mid-epoch acceptance run under pipeline_depth=2 with a
# co-scheduled group, the crash-point sweep over the udf.* sites,
# ctl udf serve external attach, and the ~60s soak composition (RPC
# chaos + UDF-server kills + serving readers, auditor green) whose
# record feeds `ctl bench trend` — slow-marked out of tier-1 per the
# 870s wall budget
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_udf_plane.py \
    "$@"

echo "== control plane (meta process + frontend fleet + admission) =="
# Fast tier: AdmissionController bounded-queue units, the [meta] config
# section, the ALTER SYSTEM parse, and a live MetaServer + MetaClient
# loopback roundtrip (store CAS, notifications, placements, lease).
# Slow tier (out of tier-1 per the 870s wall budget): the fleet
# acceptance surface — one writer + two serving sessions over one meta
# process + one Hummock dir, last-writer-wins fencing, meta kill -9 →
# restart → reconnect → auditor green, pgwire SSL/GSSENC probes, 4x
# admission overload with zero dropped connections, and the
# zero-added-dispatch parity guard at pipeline_depth 1 and 2.
python -m pytest -q -p no:cacheprovider \
    tests/test_control_plane.py -m 'not slow' \
    "$@"
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_control_plane.py \
    "$@"
# seeded meta-link delay chaos: a serving reader attaches over a slow
# meta link while the writer commits; auditor green + identical
# injection trace on replay (docs/control-plane.md)
python -m risingwave_tpu.sim --meta-chaos --seed 13 --replay

echo "== leader failover (TTL lease, term-fenced election) =="
# Fast tier (tier-1): the lease protocol on a bare MetaServer — the
# CAS race admits exactly one same-term candidate (typed LeaseLost for
# the loser), renew-after-supersede is refused, the client NEVER
# retries lease.acquire/lease.renew over a broken link, the TTL
# detector pushes exactly one leader_down per term, and seeded delay
# on the lease.renew chaos stream slows heartbeats WITHOUT a spurious
# failover.
python -m pytest -q -p no:cacheprovider \
    tests/test_failover.py -m 'not slow' \
    "$@"
# Slow tier (out of tier-1 per the 870s wall budget): the promotion
# lifecycle over real Sessions (standby auto-promotes, reader keeps
# pins across the handover, fenced ex-writer demotes to serving), the
# rw_leader_history catalog relation, the ctl smoke, and the kill -9
# acceptance scenario.
python -m pytest -q -p no:cacheprovider -m slow \
    tests/test_failover.py \
    "$@"
# the acceptance run itself under the chaos plane: SIGKILL the writer
# process mid-stream → standby promotes within the TTL, exactly-once
# audit green, identical meta-link injection trace on --replay
# (docs/control-plane.md "Leader failover")
python -m risingwave_tpu.sim --failover --seed 7 --replay
# ctl smoke: who holds the lease — live over the wire, then offline
# from the durable store (TTL remaining is server memory → "unknown")
fo_dir=$(mktemp -d)
python - "$fo_dir" <<'EOF'
import os, subprocess, sys
from risingwave_tpu.meta.server import MetaServer
from risingwave_tpu.meta.client import MetaClient
d = sys.argv[1]
srv = MetaServer(data_dir=os.path.join(d, "meta"), lease_ttl_s=30.0)
addr = srv.start()
c = MetaClient(addr, session_id="check-sh-writer")
c.acquire_leader(1)
out = subprocess.run(
    [sys.executable, "-m", "risingwave_tpu", "ctl", "meta", "leader",
     "--meta-addr", addr], capture_output=True, text=True, timeout=120)
assert out.returncode == 0, out.stderr
assert "check-sh-writer" in out.stdout, out.stdout
sys.stdout.write(out.stdout)
c.close()
srv.stop()
EOF
python -m risingwave_tpu ctl meta leader --data-dir "$fo_dir"
rm -rf "$fo_dir"

echo "== rwlint (AST invariant checker, docs/static-analysis.md) =="
# One AST-grounded pass replaces the five historical grep lints
# (exchange-boundary, wire-boundary, placement-mutation,
# serving-cache, boundary-IO — now alias-aware and docstring-proof)
# and adds the deep planes no grep could express: dispatch-discipline
# (no host transfer / nested jit reachable from the epoch-builder
# registries), trace-purity (no wall-clock/RNG/mutable-default capture
# under jit/vmap/shard_map), seqlock-discipline (Session data-version
# protocol), failpoint-honesty (declared == executed site registry).
# --ci keeps the per-rule "<rule> lint: OK" lines diffable against the
# old output. Timing budget: the full-package run must stay under 10s
# on the CPU CI host (asserted again, with margin, by the tier-1
# wiring test in tests/test_rwlint.py).
start_ns=$(date +%s%N)
python -m risingwave_tpu.analysis --ci
elapsed_ms=$(( ($(date +%s%N) - start_ns) / 1000000 ))
echo "rwlint: ${elapsed_ms} ms"
if [ "$elapsed_ms" -gt 10000 ]; then
    echo "rwlint exceeded the 10s CI timing budget: ${elapsed_ms} ms"
    exit 1
fi

echo "== vacuum-leak assertion =="
python - <<'EOF'
from risingwave_tpu.storage.hummock import SST_PREFIX, HummockStateStore
from risingwave_tpu.storage.object_store import MemObjectStore

st = HummockStateStore(object_store=MemObjectStore(),
                       inline_compaction=False)
for e in range(1, 10):
    st.ingest(5, e, {b"k%03d" % e: b"v"}, set())
    st.ingest(6, e, {b"k%03d" % e: b"v"}, set())
    st.commit(e)
st.drop_table(5)
st.compact()
st.vacuum()
listed = set(st.object_store.list(SST_PREFIX))
referenced = set(st.manager.version.all_runs())
assert listed == referenced, (
    f"orphaned SSTs after drop+vacuum: {sorted(listed - referenced)}")
_, tables = st.committed_epoch, dict(st.iter_table(6))
assert len(tables) == 9 and not dict(st.iter_table(5))
print(f"no orphans: {len(listed)} SSTs listed, all referenced")
EOF

echo "check.sh: OK"
