"""Sharded (multi-chip) streaming hash join: vnode shuffle of BOTH inputs +
per-shard JoinCore.

TPU-native counterpart of the reference's parallel HashJoin actors fed by two
hash dispatchers (reference: hash dispatch src/stream/src/executor/dispatch.rs:532,
vnode partitioning docs/consistent-hash.md, join executor
src/stream/src/executor/hash_join.rs:227-270): instead of
serialize→gRPC→deserialize on every exchange edge, each side's chunk is
shuffled to its owner shard with one ``lax.all_to_all`` over ICI *inside the
jitted step*, fused with the join probe/update itself.

Both sides shuffle by their join-key columns, so matching rows co-locate on
the same shard and each shard runs the UNCHANGED pure ``JoinCore`` step
(ops/join_state.py) on its slice — the whole multi-chip join is the
single-chip program under ``shard_map``.

Layout mirrors parallel/sharded_agg.py: every state array carries a leading
[n_shards] axis sharded over the mesh (``P('shard')``); a step consumes one
local chunk per shard and returns the per-shard emission grid (compact with
``gather_units_window`` per shard before sending downstream).

Hot-key skew (NEXmark's 90% hot-auction bids) overflows fixed bucket widths;
like the single-chip executor, a step that trips an overflow flag is
discarded and retried on the UNTOUCHED previous state after growing the
geometry — functional state makes the retry exact even under shard_map.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import StreamChunk, chunk_to_rows
from ..common.types import Schema
from ..ops.fused_sharded import sharded_equi_join_epoch
from ..ops.join_state import JoinCore, JoinType, import_state
from .sharded_agg import SHARD_AXIS, make_mesh


class ShardedHashJoin:
    """Data-parallel streaming hash join over a device mesh.

    One ``step(side, chunk_batch)`` shuffles + joins one local chunk per
    shard in a single XLA program; outputs keep the sharded leading axis."""

    def __init__(
        self,
        mesh: Mesh,
        left_schema: Schema,
        right_schema: Schema,
        left_keys: Sequence[int],
        right_keys: Sequence[int],
        join_type: JoinType = JoinType.INNER,
        condition=None,
        key_capacity: int = 1 << 10,
        bucket_width: int = 8,
        max_state_cells: int = 1 << 24,
    ):
        self.mesh = mesh
        self.n = mesh.devices.size
        self._schemas = (left_schema, right_schema)
        self._keys = (tuple(left_keys), tuple(right_keys))
        self._join_args = dict(join_type=join_type, condition=condition)
        self.max_state_cells = max_state_cells
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._build(key_capacity, bucket_width, state=None)

    def _build(self, key_capacity: int, bucket_width: int, state) -> None:
        ls, rs = self._schemas
        lk, rk = self._keys
        self.core = JoinCore(
            ls, rs, lk, rk, key_capacity=key_capacity,
            bucket_width=bucket_width, **self._join_args,
        )
        self.out_schema = self.core.out_schema
        if state is None:
            state = jax.vmap(lambda _: self.core.init_state())(
                jnp.arange(self.n))
        self.state = jax.device_put(
            state, jax.tree_util.tree_map(lambda _: self._sharding, state))

        # the generic sharded-fused equi-join surface
        # (ops/fused_sharded.SHARDED_EPOCH_BUILDERS["equi_join"]): one
        # dispatch covers k same-side chunks — shuffle + probe/update
        # for the whole mesh — where the old per-chunk step ladder paid
        # one dispatch each
        self._epoch = sharded_equi_join_epoch(self.core, self.mesh, lk, rk)

    # -- stepping with functional growth-on-overflow --------------------------

    def step(self, side: str, chunk_batch: StreamChunk) -> StreamChunk:
        """``chunk_batch``: arrays with a leading [n_shards] axis (one local
        chunk per shard). Returns the per-shard emission grids (leading
        [n_shards] axis, mostly-invisible rows). Grows state geometry and
        retries on overflow (single-chip analogue:
        stream/hash_join.py:_apply_growing)."""
        return self.step_epoch(side, [chunk_batch])[0]

    def step_epoch(self, side: str,
                   chunk_batches: Sequence[StreamChunk]) -> list:
        """``k`` same-side chunk batches (each with the leading
        [n_shards] axis) in ONE fused dispatch — the epoch analogue of
        ``step``, applied in order. Returns the k per-shard emission
        grids. Overflow handling is the same functional grow-retry:
        the epoch's outputs are discarded, geometry grows, and the
        whole batch replays from the UNTOUCHED previous state.

        The scan length is padded to the next power of two with
        all-invisible chunks (a no-op for the join body), so
        data-dependent run lengths from the executor's input batching
        compile O(log k) epoch variants, not one per distinct k."""
        k = len(chunk_batches)
        padded = 1 << (k - 1).bit_length() if k > 1 else 1
        if padded > k:
            pad = jax.tree_util.tree_map(jnp.zeros_like, chunk_batches[0])
            chunk_batches = list(chunk_batches) + [pad] * (padded - k)
        batch = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=1), *chunk_batches)
        batch = jax.device_put(
            batch, jax.tree_util.tree_map(lambda _: self._sharding, batch))
        while True:
            new_state, bigs = self._epoch(self.state, batch, side=side)
            flags = jax.device_get((
                new_state.left.lane_overflow, new_state.left.ht_overflow,
                new_state.right.lane_overflow, new_state.right.ht_overflow,
            ))
            lane_ovf = bool(np.any(flags[0]) | np.any(flags[2]))
            ht_ovf = bool(np.any(flags[1]) | np.any(flags[3]))
            if not lane_ovf and not ht_ovf:
                self.state = new_state
                return [jax.tree_util.tree_map(lambda x, i=i: x[:, i],
                                               bigs) for i in range(k)]
            new_W = self.core.W * 2 if lane_ovf else self.core.W
            new_cap = self.core.capacity * 2 if ht_ovf else self.core.capacity
            if new_W * new_cap > self.max_state_cells:
                raise RuntimeError(
                    f"ShardedHashJoin: per-shard state would exceed "
                    f"{self.max_state_cells} cells (cap={new_cap}, W={new_W})")
            self._grow(new_cap, new_W)

    def _grow(self, new_cap: int, new_W: int) -> None:
        """Re-layout every shard's state into the larger geometry on host
        (rare event; import_state's rehash path is not vmappable because it
        branches on a concrete overflow flag)."""
        old = jax.device_get(self.state)
        ls, rs = self._schemas
        lk, rk = self._keys
        new_core = JoinCore(
            ls, rs, lk, rk, key_capacity=new_cap, bucket_width=new_W,
            **self._join_args)
        shards = [
            import_state(new_core,
                         jax.tree_util.tree_map(lambda x: jnp.asarray(x[s]), old))
            for s in range(self.n)
        ]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        self._build(new_cap, new_W, state=stacked)

    # -- host-side helpers ----------------------------------------------------

    def batch_chunks(self, chunks: Sequence[StreamChunk]) -> StreamChunk:
        """Stack n single-shard chunks into one sharded batch."""
        assert len(chunks) == self.n
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *chunks)
        return jax.device_put(
            stacked, jax.tree_util.tree_map(lambda _: self._sharding, stacked))

    def collect_rows(self, big: StreamChunk) -> list:
        """Gather one step's output to host: [(op, row), ...] across shards.

        Test/debug surface — production egress compacts per shard with
        gather_units_window and keeps flowing on device."""
        host = jax.device_get(big)
        out = []
        for s in range(self.n):
            shard = jax.tree_util.tree_map(lambda x: x[s], host)
            out.extend(chunk_to_rows(shard, self.out_schema, with_ops=True,
                                     physical=True))
        return out


def build_sharded_q7_step(n_devices: int) -> None:
    """Driver dry-run: full sharded NEXmark q7/q8-shaped windowed join step
    over an n-device mesh — both sides vnode-shuffled by join key, per-shard
    JoinCore probe/update with a non-equi window condition — one real step
    executed on tiny shapes, cross-checked against a host join."""
    from ..common.chunk import Column
    from ..connector import NexmarkConfig, NexmarkGenerator
    from ..connector.nexmark import AUCTION_SCHEMA, BID_SCHEMA
    from ..expr import call, col

    mesh = make_mesh(n_devices)
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=64))

    # bid ⋈ auction ON bid.auction = auction.id AND bid.date_time <= auction.expires
    n_l = len(BID_SCHEMA)
    cond = call("less_than_or_equal",
                col(5, BID_SCHEMA[5].type),                 # bid.date_time
                col(n_l + 6, AUCTION_SCHEMA[6].type))       # auction.expires
    join = ShardedHashJoin(
        mesh, BID_SCHEMA, AUCTION_SCHEMA, [0], [0], JoinType.INNER,
        condition=cond, key_capacity=1 << 9, bucket_width=16,
    )

    def spread(bid_chunk: StreamChunk) -> StreamChunk:
        # NEXmark's 90%-hot-auction skew would force a giant bucket width on
        # tiny dryrun shapes; spread bid keys uniformly over the live auction
        # id range instead (the host cross-check uses the same spread rows,
        # so the check stays exact)
        a = bid_chunk.columns[0]
        rowpos = jnp.arange(a.data.shape[0], dtype=a.data.dtype)
        spread_ids = 1000 + (a.data + rowpos) % 64
        cols = (Column(spread_ids.astype(a.data.dtype), a.mask),
                ) + bid_chunk.columns[1:]
        return bid_chunk.with_columns(cols)

    auctions = [gen.next_auction_chunk() for _ in range(n_devices)]
    bids = [spread(gen.next_bid_chunk()) for _ in range(n_devices)]
    out_a = join.step("right", join.batch_chunks(auctions))
    out_b = join.step("left", join.batch_chunks(bids))
    jax.block_until_ready(out_b.ops)
    got = sorted(join.collect_rows(out_a) + join.collect_rows(out_b))

    # host-model inner join over the same rows
    a_rows = [r for c in auctions
              for r in chunk_to_rows(c, AUCTION_SCHEMA, physical=True)]
    b_rows = [r for c in bids
              for r in chunk_to_rows(c, BID_SCHEMA, physical=True)]
    expected = sorted(
        (0, br + ar)
        for br in b_rows for ar in a_rows
        if br[0] == ar[0] and br[5] <= ar[6]
    )
    assert got == expected, (
        f"sharded join mismatch: {len(got)} rows vs host {len(expected)}")
    print(f"dryrun_multichip({n_devices}): q7-core sharded join OK, "
          f"{len(got)} joined rows")
