"""Host drivers for the mesh-sharded fused epochs (ops/fused_sharded.py).

``ShardedFusedAgg`` / ``ShardedFusedJoin`` own the sharded stacked state
(leading ``[n_shards]`` axis, ``NamedSharding(mesh, P('shard'))``) and the
per-epoch control loop:

* ``run_epoch(start, key, k)`` — ONE jit dispatch for the whole mesh.
* ``flush()`` — ONE packed stats fetch covering every shard (the agg
  reuses ops/fused_multi.py's vmapped barrier steps: the shard axis is
  served by exactly the machinery the co-scheduler built for its job
  axis), then per-window output gathers via a traced shard index, so one
  compiled gather serves every shard.
* routing-overflow grow-retry: the compacted all-to-all receive width
  (``recv_width`` chunks) can overflow under hot-key skew; the epoch's
  sticky per-shard ``route_ovf`` flag surfaces in the SAME packed fetch,
  and the driver doubles the width and re-runs the epoch from the
  untouched previous state — the functional grow-retry of
  parallel/sharded_join.py, applied to the fused path (which is why the
  sharded epochs never donate their buffers).

Durability composes with the ordinary split-state tables: per-shard
states are solo-shaped (``shard_states()``), so the agg checkpoints
through ONE HashAggExecutor persistence engine (its own state-table
delta flush), and recovery re-shards committed rows onto ANY mesh size
by replaying the vnode mapping (``load_shard_states`` — the same
``vnode_to_shard`` in-dispatch routing uses). The join exports/imports
per-shard ``IntervalJoinCore`` payloads; ``reshard_join_payloads``
re-buckets them for a differently-sized mesh.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.chunk import Column, flatten_shards, gather_units_window
from ..common.hashing import shard_rows, vnode_of, vnode_to_shard
from ..common.profiling import profile_dispatch
from ..ops.fused_multi import (
    gather_job_flush_chunk, index_state, multi_agg_finish, stack_states,
    unstack_states,
)
from ..ops.fused_sharded import sharded_agg_epoch, sharded_join_epoch
from ..ops.grouped_agg import load_rows_into_state
from .sharded_agg import SHARD_AXIS

_NEG = np.iinfo(np.int64).min


def _sharded_agg_probe(core) -> Callable:
    """``probe(stacked, route_ovf[n]) -> (packed [n, 3], rank [n, cap])``
    — the whole mesh's barrier probe in one dispatch / one fetch; slot 2
    carries the epoch's routing-overflow flag so retry detection costs no
    extra sync."""

    def probe_one(st, rovf):
        rank = core.flush_rank(st)
        packed = jnp.stack([rank[-1], st.overflow.astype(jnp.int32),
                            rovf.astype(jnp.int32)])
        return packed, rank

    vm = jax.vmap(probe_one)

    def probe(stacked, rovf):
        return vm(stacked, rovf)

    return profile_dispatch(jax.jit(probe), probe.__qualname__)


class _ShardedFusedBase:
    """Shared mesh/state plumbing + the grow-retry bookkeeping."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        self.mesh = mesh
        self.n = mesh.devices.size
        self.core = core
        self.chunk_fn = chunk_fn
        self.exprs = tuple(exprs)
        self.rows_per_chunk = int(rows_per_chunk)
        self.recv_width = min(int(recv_width), self.n)
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        if states is None:
            states = [core.init_state() for _ in range(self.n)]
        if len(states) != self.n:
            raise ValueError(
                f"{len(states)} shard states for a {self.n}-device mesh")
        self.stacked = self._put(stack_states(list(states)))
        self._epochs: dict[int, Callable] = {}   # recv_width -> jitted
        self._pending = None    # (prev_stacked, start, key, k) to retry
        self.epochs_run = 0
        self.route_grows = 0    # grow-retry events (observability)

    def _put(self, stacked):
        return jax.device_put(
            stacked,
            jax.tree_util.tree_map(lambda _: self._sharding, stacked))

    def _build_epoch(self, width: int) -> Callable:
        raise NotImplementedError

    def _epoch_fn(self) -> Callable:
        fn = self._epochs.get(self.recv_width)
        if fn is None:
            fn = self._build_epoch(self.recv_width)
            self._epochs[self.recv_width] = fn
        return fn

    def _grow_and_retry(self):
        """Routing overflow: the last epoch dropped rows on some shard.
        Double the receive width (capped at full n·C, where overflow is
        impossible) and replay the epoch from the untouched pre-epoch
        state — deterministic (start, key, k) makes the retry exact."""
        prev, start, key, k = self._pending
        self.recv_width = min(max(self.recv_width * 2, 2), self.n)
        self.route_grows += 1
        return self._epoch_fn()(prev, start, key, k)

    # -- per-shard state views (solo-shaped; checkpoint/test surface) ---------

    def shard_states(self) -> list:
        return unstack_states(self.stacked, self.n)

    def set_states(self, states: Sequence) -> None:
        self.stacked = self._put(stack_states(list(states)))


class ShardedFusedAgg(_ShardedFusedBase):
    """The q5 shape (source → project → AggCore) fused over a mesh."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, exprs, rows_per_chunk,
                         recv_width, states)
        self._rovf = jnp.zeros(self.n, jnp.bool_)
        self._probe = _sharded_agg_probe(core)
        self._finish = multi_agg_finish(core)
        self._gather = gather_job_flush_chunk(core)

    def _build_epoch(self, width: int) -> Callable:
        return sharded_agg_epoch(self.chunk_fn, self.exprs, self.core,
                                 self.rows_per_chunk, self.mesh, width)

    def _settle(self) -> None:
        """Validate a still-pending epoch (routing overflow → grow-retry)
        before piling another one on top of it. The usual driver cadence
        — run_epoch, flush, run_epoch, … — settles inside flush() for
        free; this extra fetch is paid only by epoch-chaining callers."""
        while self._pending is not None:
            if bool(np.any(np.asarray(jax.device_get(self._rovf)))):
                self.stacked, self._rovf = self._grow_and_retry()
            else:
                self._pending = None

    def run_epoch(self, start: int, key, k: int) -> None:
        """ONE dispatch: k chunks generated, routed and aggregated across
        the whole mesh. Validation (routing overflow) settles at the next
        ``flush()`` — same tick, zero extra host syncs."""
        self._settle()
        args = (jnp.int64(start), key, int(k))
        self._pending = (self.stacked, *args)
        self.stacked, self._rovf = self._epoch_fn()(self.stacked, *args)
        self.epochs_run += 1

    def flush(self) -> list:
        """Barrier flush: one packed [n, 3] fetch for every shard's dirty
        count / overflow / route flag, per-window churn gathers (traced
        shard index — one compiled gather for the mesh), one vmapped
        finish. Returns the flush StreamChunks in shard-major order."""
        while True:
            packed, ranks = self._probe(self.stacked, self._rovf)
            packed_h = np.asarray(jax.device_get(packed))
            if self._pending is not None and packed_h[:, 2].any():
                self.stacked, self._rovf = self._grow_and_retry()
                continue
            break
        self._pending = None
        self._rovf = jnp.zeros(self.n, jnp.bool_)
        chunks = []
        for s in range(self.n):
            n_dirty, overflow = int(packed_h[s, 0]), int(packed_h[s, 1])
            if overflow:
                raise RuntimeError(
                    f"sharded fused agg: shard {s} group table overflow "
                    f"(per-shard capacity {self.core.capacity}); increase "
                    "agg_table_capacity")
            lo = 0
            while lo < n_dirty:
                chunks.append(self._gather(self.stacked, ranks,
                                           jnp.int64(s), jnp.int64(lo)))
                lo += self.core.groups_per_chunk
        self.stacked = self._finish(self.stacked)
        return chunks

    def checkpoint(self, engine, epoch: int) -> None:
        """Write every shard's checkpoint delta through ONE
        HashAggExecutor persistence engine (its own state-table flush —
        hash partitioning keeps per-shard keys disjoint, so the deltas
        union cleanly in the shared table), then restack once."""
        states = []
        for s in range(self.n):
            engine.state = index_state(self.stacked, s)
            engine._checkpoint_to_state_table(epoch)
            states.append(engine.state)
        self.set_states(states)

    def merged_group_values(self) -> dict:
        """All shards' live groups → {key_tuple: (lanes...)}. Test/debug
        surface (production egress is the flush chunks)."""
        host = jax.device_get(self.stacked)
        out: dict = {}
        for s in range(self.n):
            st = jax.tree_util.tree_map(lambda x: x[s], host)
            occ = np.asarray(st.table.occupied)
            live = np.asarray(st.lanes[0]) > 0
            kd = [np.asarray(x) for x in st.table.key_data]
            km = [np.asarray(x) for x in st.table.key_mask]
            lanes = [np.asarray(x) for x in st.lanes]
            for slot in np.nonzero(occ & live)[0]:
                key = tuple(kd[c][slot].item() if km[c][slot] else None
                            for c in range(len(kd)))
                out[key] = tuple(l[slot].item() for l in lanes)
        return out


class ShardedFusedJoin(_ShardedFusedBase):
    """The q7 shape (source → project → bucketed interval join + max
    flush) fused over a mesh. ``core``: the PER-SHARD IntervalJoinCore —
    windows spread uniformly under the vnode hash, so its ring only
    needs ~1/n of the solo bucket count."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, exprs, rows_per_chunk,
                         recv_width, states)
        self._out = None        # last epoch's full output tuple

        def gather_flush(stacked, dels, inss, olds, s, lo,
                         out_capacity: int):
            st = index_state(stacked, s)
            return core.gather_flush(st, dels[s], inss[s], olds[s], lo,
                                     out_capacity)

        def gather_probe(probe_out, s, lo, out_capacity: int):
            pj = jax.tree_util.tree_map(lambda x: x[s], probe_out)
            return gather_units_window(flatten_shards(pj), lo,
                                       out_capacity)

        self._gather_flush = profile_dispatch(
            jax.jit(gather_flush, static_argnames=("out_capacity",)),
            gather_flush.__qualname__)
        self._gather_probe = profile_dispatch(
            jax.jit(gather_probe, static_argnames=("out_capacity",)),
            gather_probe.__qualname__)

    def _build_epoch(self, width: int) -> Callable:
        return sharded_join_epoch(self.chunk_fn, self.exprs, self.core,
                                  self.rows_per_chunk, self.mesh, width)

    def _settle(self) -> None:
        """Validate a still-pending epoch before running the next one
        (see ShardedFusedAgg._settle; the run/flush cadence never pays
        this fetch)."""
        while self._pending is not None:
            packed_h = np.asarray(jax.device_get(self._out[5]))
            if packed_h[:, 5].any():
                self._out = self._grow_and_retry()
                self.stacked = self._out[0]
            else:
                self._pending = None

    def run_epoch(self, start: int, key, k: int) -> None:
        """ONE dispatch: ingest + probe emission + the barrier flush plan
        for every shard (the join epoch body flushes in-dispatch)."""
        self._settle()
        args = (jnp.int64(start), key, int(k))
        self._pending = (self.stacked, *args)
        self._out = self._epoch_fn()(self.stacked, *args)
        self.stacked = self._out[0]
        self.epochs_run += 1

    def flush(self, out_capacity: int):
        """Drain the epoch's two emission surfaces. ONE [n, 6] packed
        fetch covers every shard's flags, counts and the route-overflow
        retry signal. Returns ``(probe_chunks, churn_chunks)``."""
        if self._out is None:
            return [], []
        while True:
            packed_h = np.asarray(jax.device_get(self._out[5]))
            if self._pending is not None and packed_h[:, 5].any():
                self._out = self._grow_and_retry()
                self.stacked = self._out[0]
                continue
            break
        self._pending = None
        _, probe_out, del_m, ins_m, old_emitted, _ = self._out
        probe_chunks, churn_chunks = [], []
        for s in range(self.n):
            n_flush, ovf, clobber, sawdel, n_probe, _ = (
                int(x) for x in packed_h[s])
            if ovf or clobber or sawdel:
                raise RuntimeError(
                    f"sharded fused join: shard {s} flags ovf={ovf} "
                    f"clobber={clobber} sawdel={sawdel}")
            lo = 0
            while lo < n_probe:
                probe_chunks.append(self._gather_probe(
                    probe_out, jnp.int64(s), jnp.int64(lo),
                    out_capacity=out_capacity))
                lo += out_capacity // 2
            lo = 0
            while lo < n_flush:
                churn_chunks.append(self._gather_flush(
                    self.stacked, del_m, ins_m, old_emitted,
                    jnp.int64(s), jnp.int64(lo),
                    out_capacity=out_capacity))
                lo += out_capacity
        self._out = None
        return probe_chunks, churn_chunks

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self) -> list:
        """Per-shard checkpoint payloads (IntervalJoinCore.export_host)."""
        return [self.core.export_host(index_state(self.stacked, s))
                for s in range(self.n)]

    def import_host(self, payloads: Sequence) -> None:
        self.set_states([self.core.import_host(p) for p in payloads])


# ---------------------------------------------------------------------------
# re-sharding: replay the vnode mapping over durable state so a job
# recovers onto a DIFFERENTLY-sized mesh
# ---------------------------------------------------------------------------


def load_agg_rows(core, rows: Sequence) -> object:
    """Fold state-table rows (keys ++ lanes) into a fresh AggState via
    the SAME bulk loader the executor recovery uses
    (ops/grouped_agg.load_rows_into_state). ``prev_lanes`` ends equal to
    ``lanes``: the recovered snapshot is the baseline downstream already
    saw."""
    state = load_rows_into_state(core, core.init_state(), rows)
    return state.replace(prev_lanes=state.lanes)


def load_shard_states(core, rows: Sequence, n_shards: int) -> list:
    """Partition committed agg rows onto ``n_shards`` by REPLAYING the
    vnode mapping (common/hashing.shard_rows — the same ``vnode_of →
    vnode_to_shard`` the in-dispatch all_to_all routes with), then load
    each shard's slice. This is the re-shard path: the durable table is
    shard-count-agnostic, so an 8-shard checkpoint reopens cleanly on a
    4-shard (or solo) mesh."""
    per_shard = shard_rows(core.key_types, rows, n_shards)
    return [load_agg_rows(core, rs) for rs in per_shard]


def _empty_join_payload(core) -> dict:
    nb, W = core.n_buckets, core.W
    return {
        "win_id": np.full(nb, -1, np.int64),
        "fill": np.zeros(nb, np.int32),
        "touched": np.zeros(nb, bool),
        "cur_max": np.full(nb, _NEG, np.int64),
        "cur_cnt": np.zeros(nb, np.int64),
        "emitted_max": np.full(nb, _NEG, np.int64),
        "emitted_live": np.zeros(nb, bool),
        "lane_overflow": np.zeros((), bool),
        "ring_clobber": np.zeros((), bool),
        "saw_delete": np.zeros((), bool),
        "row_data": [np.zeros((nb, W), f.type.np_dtype)
                     for f in core.probe_schema],
        "row_mask": [np.zeros((nb, W), bool) for _ in core.probe_schema],
    }


_JOIN_BUCKET_FIELDS = ("win_id", "fill", "touched", "cur_max", "cur_cnt",
                       "emitted_max", "emitted_live")
_JOIN_FLAG_FIELDS = ("lane_overflow", "ring_clobber", "saw_delete")


def reshard_join_payloads(old_core, payloads: Sequence, new_core,
                          new_n: int) -> list:
    """Re-bucket per-shard interval-join checkpoint payloads onto a
    ``new_n``-shard mesh: every resident window re-routes by replaying
    the vnode mapping over its window-start value — the exact hash the
    in-dispatch all_to_all applies to that window's rows — and lands at
    ``win_id % new_nb`` in its new owner's ring. Ring geometry may shrink
    with the mesh (windows spread ~uniformly); a destination collision
    (two live windows sharing a slot) raises instead of clobbering."""
    if old_core.W != new_core.W or \
            len(old_core.probe_schema) != len(new_core.probe_schema):
        raise ValueError("re-shard requires identical lane geometry "
                         "(lane_width / probe schema)")
    if old_core.window_us != new_core.window_us or \
            old_core.ts_col != new_core.ts_col or \
            old_core.probe_schema[old_core.ts_col].type.np_dtype != \
            new_core.probe_schema[new_core.ts_col].type.np_dtype:
        # win_id values are copied verbatim: a different window (or ts
        # layout) would relabel every resident window AND route it
        # differently than the live all_to_all — refuse, don't split-brain
        raise ValueError("re-shard requires identical window config "
                         "(window_us / ts_col)")
    nb_new = new_core.n_buckets
    ts_dtype = old_core.probe_schema[old_core.ts_col].type.np_dtype
    outs = [_empty_join_payload(new_core) for _ in range(new_n)]
    for p in payloads:
        for f in _JOIN_FLAG_FIELDS:
            flag = bool(np.asarray(p[f]))
            for o in outs:      # sticky flags stay visible on every shard
                o[f] = o[f] | flag
        win = np.asarray(p["win_id"])
        idx = np.nonzero(win >= 0)[0]
        if not len(idx):
            continue
        ws = (win[idx] * old_core.window_us).astype(np.dtype(ts_dtype))
        col = Column(jnp.asarray(ws), jnp.ones(len(idx), jnp.bool_))
        shard = np.asarray(vnode_to_shard(vnode_of([col]), new_n))
        slot = win[idx] % nb_new
        for j, b in enumerate(idx):
            s, t = int(shard[j]), int(slot[j])
            o = outs[s]
            if o["win_id"][t] != -1:
                raise RuntimeError(
                    f"re-shard bucket collision on shard {s} slot {t}; "
                    "increase the new core's n_buckets")
            for f in _JOIN_BUCKET_FIELDS:
                o[f][t] = p[f][b]
            for c in range(len(o["row_data"])):
                o["row_data"][c][t] = p["row_data"][c][b]
                o["row_mask"][c][t] = p["row_mask"][c][b]
    return outs
