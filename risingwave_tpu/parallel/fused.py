"""Host drivers for the mesh-sharded fused epochs (ops/fused_sharded.py).

``ShardedFusedAgg`` / ``ShardedFusedJoin`` / ``ShardedFusedSession`` /
``ShardedFusedQ3`` own one surface's sharded stacked state (leading
``[n_shards]`` axis, ``NamedSharding(mesh, P('shard'))``);
``ShardedCoGroup`` (+ the signature-keyed ``ShardedCoScheduler``) owns a
whole co-scheduled group's ``[n_shards, J]`` state — K signature-equal
MVs × S shards in ONE dispatch per tick (fusion surface 6). All share
the per-epoch control loop:

* ``run_epoch(start, key, k)`` — ONE jit dispatch for the whole mesh.
* ``flush()`` — ONE packed stats fetch covering every shard (the agg
  reuses ops/fused_multi.py's vmapped barrier steps: the shard axis is
  served by exactly the machinery the co-scheduler built for its job
  axis), then per-window output gathers via a traced shard index, so one
  compiled gather serves every shard.
* routing-overflow grow-retry: the compacted all-to-all receive width
  (``recv_width`` chunks) can overflow under hot-key skew; the epoch's
  sticky per-shard ``route_ovf`` flag surfaces in the SAME packed fetch,
  and the driver doubles the width and re-runs the epoch from the
  untouched previous state — the functional grow-retry of
  parallel/sharded_join.py, applied to the fused path (which is why the
  sharded epochs never donate their buffers).

Durability composes with the ordinary split-state tables: per-shard
states are solo-shaped (``shard_states()``), so the agg checkpoints
through ONE HashAggExecutor persistence engine (its own state-table
delta flush), and recovery re-shards committed rows onto ANY mesh size
by replaying the vnode mapping (``load_shard_states`` — the same
``vnode_to_shard`` in-dispatch routing uses). The join exports/imports
per-shard ``IntervalJoinCore`` payloads; ``reshard_join_payloads``
re-buckets them for a differently-sized mesh.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.chunk import Column, flatten_shards, gather_units_window
from ..common.fetch import PendingFlush, async_fetch, fetch
from ..common.hashing import (
    shard_rows, vnode_of, vnode_to_shard, vnodes_of_rows,
)
from ..common.profiling import GLOBAL_PROFILER, profile_dispatch
from ..ops.fused_multi import (
    gather_job_flush_chunk, index_state, multi_agg_finish, stack_states,
    unstack_states,
)
from ..ops.fused_sharded import (
    build_sharded_group_epoch, sharded_agg_epoch, sharded_join_epoch,
    sharded_q3_epoch, sharded_session_epoch,
)
from ..ops.grouped_agg import load_rows_into_state
from ..ops.hash_table import ht_lookup_or_insert
from .sharded_agg import SHARD_AXIS

_NEG = np.iinfo(np.int64).min


def _sharded_agg_probe(core, job_axis: bool = False) -> Callable:
    """``probe(stacked, route_ovf) -> (packed [..., 3], rank [..., cap])``
    — the whole mesh's barrier probe in one dispatch / one fetch; slot 2
    carries the epoch's routing-overflow flag so retry detection costs no
    extra sync. With ``job_axis`` the vmap nests over ``[n, J]`` (the
    K×S co-scheduled group's layout) instead of ``[n]``."""

    def probe_one(st, rovf):
        rank = core.flush_rank(st)
        packed = jnp.stack([rank[-1], st.overflow.astype(jnp.int32),
                            rovf.astype(jnp.int32)])
        return packed, rank

    vm = jax.vmap(jax.vmap(probe_one)) if job_axis \
        else jax.vmap(probe_one)

    def probe(stacked, rovf):
        return vm(stacked, rovf)

    return profile_dispatch(jax.jit(probe), probe.__qualname__)


class _GrowRetryMixin:
    """The routing-overflow grow-retry plumbing every sharded-fused
    driver shares: per-width epoch cache, sharded device_put, and the
    width-doubling replay. Requires ``_init_retry`` to have run and a
    ``_build_epoch(width)`` implementation."""

    def _init_retry(self, mesh, recv_width: int) -> None:
        self.mesh = mesh
        self.n = mesh.devices.size
        self.recv_width = min(int(recv_width), self.n)
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._epochs: dict[int, Callable] = {}   # recv_width -> jitted
        self._pending = None    # (prev_stacked, epoch_args) to retry
        self.epochs_run = 0
        self.route_grows = 0    # grow-retry events (observability)

    def _put(self, stacked):
        return jax.device_put(
            stacked,
            jax.tree_util.tree_map(lambda _: self._sharding, stacked))

    def _build_epoch(self, width: int) -> Callable:
        raise NotImplementedError

    def _epoch_fn(self) -> Callable:
        fn = self._epochs.get(self.recv_width)
        if fn is None:
            fn = self._build_epoch(self.recv_width)
            self._epochs[self.recv_width] = fn
        return fn

    def _grow_and_retry(self):
        """Routing overflow: the last epoch dropped rows on some shard.
        Double the receive width (capped at full n·C, where overflow is
        impossible) and replay the epoch from the untouched pre-epoch
        state — deterministic epoch args make the retry exact."""
        prev, args = self._pending
        self.recv_width = min(max(self.recv_width * 2, 2), self.n)
        self.route_grows += 1
        return self._epoch_fn()(prev, *args)

    # -- the shared retry loop for drivers that hold the epoch's full
    # output tuple in self._out (join / session / q3). Subclasses set
    # _PACKED_POS (index of the packed array in the tuple) and _OVF_COL
    # (packed column carrying the per-shard route-overflow flag).
    _PACKED_POS: int = -1
    _OVF_COL: int = -1

    def _settle(self) -> None:
        """Validate a still-pending epoch (routing overflow →
        grow-retry) before piling another one on top of it. The usual
        driver cadence — run_epoch, flush, run_epoch, … — settles
        inside flush() for free; this extra fetch is paid only by
        epoch-chaining callers."""
        while self._pending is not None:
            packed_h = np.asarray(fetch(self._out[self._PACKED_POS]))
            if packed_h[:, self._OVF_COL].any():
                self._out = self._grow_and_retry()
                self.stacked = self._out[0]
            else:
                self._pending = None

    def _settled_packed(self) -> np.ndarray:
        """The flush-side twin: retry until the packed flags are
        overflow-free, clear the pending marker, return the host copy
        (ONE fetch per attempt covers flags AND the retry signal)."""
        while True:
            packed_h = np.asarray(fetch(self._out[self._PACKED_POS]))
            if self._pending is not None and \
                    packed_h[:, self._OVF_COL].any():
                self._out = self._grow_and_retry()
                self.stacked = self._out[0]
                continue
            break
        self._pending = None
        return packed_h


class _ShardedFusedBase(_GrowRetryMixin):
    """Shared mesh/state plumbing for the single-job sharded drivers."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        self._init_retry(mesh, recv_width)
        self.core = core
        self.chunk_fn = chunk_fn
        self.exprs = tuple(exprs)
        self.rows_per_chunk = int(rows_per_chunk)
        if states is None:
            states = [core.init_state() for _ in range(self.n)]
        if len(states) != self.n:
            raise ValueError(
                f"{len(states)} shard states for a {self.n}-device mesh")
        self.stacked = self._put(stack_states(list(states)))

    # -- per-shard state views (solo-shaped; checkpoint/test surface) ---------

    def shard_states(self) -> list:
        return unstack_states(self.stacked, self.n)

    def set_states(self, states: Sequence) -> None:
        self.stacked = self._put(stack_states(list(states)))


class ShardedFusedAgg(_ShardedFusedBase):
    """The q5 shape (source → project → AggCore) fused over a mesh."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, exprs, rows_per_chunk,
                         recv_width, states)
        self._rovf = jnp.zeros(self.n, jnp.bool_)
        self._probe = _sharded_agg_probe(core)
        self._finish = multi_agg_finish(core)
        self._gather = gather_job_flush_chunk(core)

    def _build_epoch(self, width: int) -> Callable:
        return sharded_agg_epoch(self.chunk_fn, self.exprs, self.core,
                                 self.rows_per_chunk, self.mesh, width)

    def _settle(self) -> None:
        """Validate a still-pending epoch (routing overflow → grow-retry)
        before piling another one on top of it. The usual driver cadence
        — run_epoch, flush, run_epoch, … — settles inside flush() for
        free; this extra fetch is paid only by epoch-chaining callers."""
        while self._pending is not None:
            if bool(np.any(np.asarray(fetch(self._rovf)))):
                self.stacked, self._rovf = self._grow_and_retry()
            else:
                self._pending = None

    def run_epoch(self, start: int, key, k: int) -> None:
        """ONE dispatch: k chunks generated, routed and aggregated across
        the whole mesh. Validation (routing overflow) settles at the next
        ``flush()`` — same tick, zero extra host syncs."""
        self._settle()
        args = (jnp.int64(start), key, int(k))
        self._pending = (self.stacked, args)
        self.stacked, self._rovf = self._epoch_fn()(self.stacked, *args)
        self.epochs_run += 1

    def flush(self) -> list:
        """Barrier flush: one packed [n, 3] fetch for every shard's dirty
        count / overflow / route flag, per-window churn gathers (traced
        shard index — one compiled gather for the mesh), one vmapped
        finish. Returns the flush StreamChunks in shard-major order."""
        while True:
            packed, ranks = self._probe(self.stacked, self._rovf)
            packed_h = np.asarray(
                fetch(packed, dispatch=self._probe.__qualname__))
            if self._pending is not None and packed_h[:, 2].any():
                self.stacked, self._rovf = self._grow_and_retry()
                continue
            break
        self._pending = None
        self._rovf = jnp.zeros(self.n, jnp.bool_)
        chunks = []
        for s in range(self.n):
            n_dirty, overflow = int(packed_h[s, 0]), int(packed_h[s, 1])
            if overflow:
                raise RuntimeError(
                    f"sharded fused agg: shard {s} group table overflow "
                    f"(per-shard capacity {self.core.capacity}); increase "
                    "agg_table_capacity")
            lo = 0
            while lo < n_dirty:
                chunks.append(self._gather(self.stacked, ranks,
                                           jnp.int64(s), jnp.int64(lo)))
                lo += self.core.groups_per_chunk
        self.stacked = self._finish(self.stacked)
        return chunks

    def checkpoint(self, engine, epoch: int) -> None:
        """Write every shard's checkpoint delta through ONE
        HashAggExecutor persistence engine (its own state-table flush —
        hash partitioning keeps per-shard keys disjoint, so the deltas
        union cleanly in the shared table), then restack once."""
        states = []
        for s in range(self.n):
            engine.state = index_state(self.stacked, s)
            engine._checkpoint_to_state_table(epoch)
            states.append(engine.state)
        self.set_states(states)

    def merged_group_values(self) -> dict:
        """All shards' live groups → {key_tuple: (lanes...)}. Test/debug
        surface (production egress is the flush chunks)."""
        host = jax.device_get(self.stacked)
        out: dict = {}
        for s in range(self.n):
            st = jax.tree_util.tree_map(lambda x: x[s], host)
            occ = np.asarray(st.table.occupied)
            live = np.asarray(st.lanes[0]) > 0
            kd = [np.asarray(x) for x in st.table.key_data]
            km = [np.asarray(x) for x in st.table.key_mask]
            lanes = [np.asarray(x) for x in st.lanes]
            for slot in np.nonzero(occ & live)[0]:
                key = tuple(kd[c][slot].item() if km[c][slot] else None
                            for c in range(len(kd)))
                out[key] = tuple(l[slot].item() for l in lanes)
        return out


class ShardedFusedJoin(_ShardedFusedBase):
    """The q7 shape (source → project → bucketed interval join + max
    flush) fused over a mesh. ``core``: the PER-SHARD IntervalJoinCore —
    windows spread uniformly under the vnode hash, so its ring only
    needs ~1/n of the solo bucket count."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, exprs, rows_per_chunk,
                         recv_width, states)
        self._out = None        # last epoch's full output tuple

        def gather_flush(stacked, dels, inss, olds, s, lo,
                         out_capacity: int):
            st = index_state(stacked, s)
            return core.gather_flush(st, dels[s], inss[s], olds[s], lo,
                                     out_capacity)

        def gather_probe(probe_out, s, lo, out_capacity: int):
            pj = jax.tree_util.tree_map(lambda x: x[s], probe_out)
            return gather_units_window(flatten_shards(pj), lo,
                                       out_capacity)

        self._gather_flush = profile_dispatch(
            jax.jit(gather_flush, static_argnames=("out_capacity",)),
            gather_flush.__qualname__)
        self._gather_probe = profile_dispatch(
            jax.jit(gather_probe, static_argnames=("out_capacity",)),
            gather_probe.__qualname__)

    _PACKED_POS = 5
    _OVF_COL = 5

    def _build_epoch(self, width: int) -> Callable:
        return sharded_join_epoch(self.chunk_fn, self.exprs, self.core,
                                  self.rows_per_chunk, self.mesh, width)

    def run_epoch(self, start: int, key, k: int) -> None:
        """ONE dispatch: ingest + probe emission + the barrier flush plan
        for every shard (the join epoch body flushes in-dispatch)."""
        self._settle()
        args = (jnp.int64(start), key, int(k))
        self._pending = (self.stacked, args)
        self._out = self._epoch_fn()(self.stacked, *args)
        self.stacked = self._out[0]
        self.epochs_run += 1

    def flush(self, out_capacity: int):
        """Drain the epoch's two emission surfaces. ONE [n, 6] packed
        fetch covers every shard's flags, counts and the route-overflow
        retry signal. Returns ``(probe_chunks, churn_chunks)``."""
        if self._out is None:
            return [], []
        packed_h = self._settled_packed()
        _, probe_out, del_m, ins_m, old_emitted, _ = self._out
        probe_chunks, churn_chunks = [], []
        for s in range(self.n):
            n_flush, ovf, clobber, sawdel, n_probe, _ = (
                int(x) for x in packed_h[s])
            if ovf or clobber or sawdel:
                raise RuntimeError(
                    f"sharded fused join: shard {s} flags ovf={ovf} "
                    f"clobber={clobber} sawdel={sawdel}")
            lo = 0
            while lo < n_probe:
                probe_chunks.append(self._gather_probe(
                    probe_out, jnp.int64(s), jnp.int64(lo),
                    out_capacity=out_capacity))
                lo += out_capacity // 2
            lo = 0
            while lo < n_flush:
                churn_chunks.append(self._gather_flush(
                    self.stacked, del_m, ins_m, old_emitted,
                    jnp.int64(s), jnp.int64(lo),
                    out_capacity=out_capacity))
                lo += out_capacity
        self._out = None
        return probe_chunks, churn_chunks

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self) -> list:
        """Per-shard checkpoint payloads (IntervalJoinCore.export_host)."""
        return [self.core.export_host(index_state(self.stacked, s))
                for s in range(self.n)]

    def import_host(self, payloads: Sequence) -> None:
        self.set_states([self.core.import_host(p) for p in payloads])


class ShardedFusedSession(_ShardedFusedBase):
    """The q8 shape (source → project → session-gap windows, watermark
    close included) fused over a mesh. ``core``: the PER-SHARD
    SessionWindowCore — keys spread uniformly under the vnode hash, so
    its table and closed buffer only need ~1/n of the solo capacity."""

    def __init__(self, mesh, core, chunk_fn, exprs, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, exprs, rows_per_chunk,
                         recv_width, states)
        self._out = None        # last epoch's (stacked, snapshot, packed)

        def gather_closed(snap, s, n_closed, lo, out_capacity: int):
            sn = jax.tree_util.tree_map(lambda x: x[s], snap)
            return core.gather_closed(sn, n_closed, lo, out_capacity)

        self._gather = profile_dispatch(
            jax.jit(gather_closed, static_argnames=("out_capacity",)),
            gather_closed.__qualname__)

    _PACKED_POS = 2
    _OVF_COL = 5

    def _build_epoch(self, width: int) -> Callable:
        return sharded_session_epoch(self.chunk_fn, self.exprs, self.core,
                                     self.rows_per_chunk, self.mesh, width)

    def run_epoch(self, start: int, key, k: int, watermark: int) -> None:
        """ONE dispatch: k chunks generated, routed by session key and
        sessionized across the whole mesh, plus the watermark close."""
        self._settle()
        args = (jnp.int64(start), key, int(k), jnp.int64(watermark))
        self._pending = (self.stacked, args)
        self._out = self._epoch_fn()(self.stacked, *args)
        self.stacked = self._out[0]
        self.epochs_run += 1

    def flush(self, out_capacity: int) -> list:
        """Drain the epoch's closed sessions. ONE [n, 6] packed fetch
        covers every shard's emission count, sticky flags and the
        route-overflow retry signal; per-shard emission windows gather
        through one compiled gather with a traced shard index."""
        if self._out is None:
            return []
        packed_h = self._settled_packed()
        _, snap, _ = self._out
        chunks = []
        for s in range(self.n):
            n_closed, ovf, covf, sawdel, ooo, _ = (
                int(x) for x in packed_h[s])
            if ovf or covf or sawdel or ooo:
                raise RuntimeError(
                    f"sharded fused session: shard {s} flags "
                    f"table_ovf={ovf} closed_ovf={covf} sawdel={sawdel} "
                    f"out_of_order={ooo}")
            lo = 0
            while lo < n_closed:
                chunks.append(self._gather(
                    snap, jnp.int64(s), jnp.int64(n_closed),
                    jnp.int64(lo), out_capacity=out_capacity))
                lo += out_capacity
        self._out = None
        return chunks

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self) -> list:
        return [self.core.export_host(index_state(self.stacked, s))
                for s in range(self.n)]

    def import_host(self, payloads: Sequence) -> None:
        self.set_states([self.core.import_host(p) for p in payloads])


class ShardedFusedQ3(_ShardedFusedBase):
    """The TPC-H q3 shape (orders build + lineitem probe + revenue agg +
    global top-n churn) fused over a mesh. Orders, their lineitems and
    their revenue group co-locate under the orderkey vnode; the flush's
    global top-``limit`` runs in-dispatch over an all-gathered candidate
    union, so the churn chunk comes back replicated — the driver reads
    shard 0's copy, ONE extra fetch beyond the packed flags."""

    def __init__(self, mesh, core, chunk_fn, rows_per_chunk: int,
                 recv_width: int = 2, states: Optional[Sequence] = None):
        super().__init__(mesh, core, chunk_fn, (), rows_per_chunk,
                         recv_width, states)
        self._out = None        # last epoch's (stacked, churn, packed)

    _PACKED_POS = 2
    _OVF_COL = 4

    def _build_epoch(self, width: int) -> Callable:
        return sharded_q3_epoch(self.chunk_fn, self.core,
                                self.rows_per_chunk, self.mesh, width)

    def run_epoch(self, start: int, key, k: int) -> None:
        """ONE dispatch: build + probe + aggregate k event chunks across
        the mesh AND recompute the global top-n churn."""
        self._settle()
        args = (jnp.int64(start), key, int(k))
        self._pending = (self.stacked, args)
        self._out = self._epoch_fn()(self.stacked, *args)
        self.stacked = self._out[0]
        self.epochs_run += 1

    def flush(self) -> list:
        """ONE [n, 5] packed fetch (flags + retry signal); the churn
        chunk is the dispatch's own output, replicated per shard —
        shard 0's copy is returned (at top-n cardinality, no windowed
        drain is ever needed)."""
        if self._out is None:
            return []
        packed_h = self._settled_packed()
        for s in range(self.n):
            _n_out, o_ovf, a_ovf, sawdel, _ = (
                int(x) for x in packed_h[s])
            if o_ovf or a_ovf or sawdel:
                raise RuntimeError(
                    f"sharded fused q3: shard {s} flags orders_ovf={o_ovf} "
                    f"agg_ovf={a_ovf} sawdel={sawdel}")
        out = jax.tree_util.tree_map(lambda x: x[0], self._out[1])
        self._out = None
        return [out]

    # -- checkpoint / recovery -------------------------------------------------

    def export_host(self) -> list:
        return [self.core.export_host(index_state(self.stacked, s))
                for s in range(self.n)]

    def import_host(self, payloads: Sequence) -> None:
        self.set_states([self.core.import_host(p) for p in payloads])


# ---------------------------------------------------------------------------
# re-sharding: replay the vnode mapping over durable state so a job
# recovers onto a DIFFERENTLY-sized mesh
# ---------------------------------------------------------------------------


def load_agg_rows(core, rows: Sequence) -> object:
    """Fold state-table rows (keys ++ lanes) into a fresh AggState via
    the SAME bulk loader the executor recovery uses
    (ops/grouped_agg.load_rows_into_state). ``prev_lanes`` ends equal to
    ``lanes``: the recovered snapshot is the baseline downstream already
    saw."""
    state = load_rows_into_state(core, core.init_state(), rows)
    return state.replace(prev_lanes=state.lanes)


def load_shard_states(core, rows: Sequence, n_shards: int) -> list:
    """Partition committed agg rows onto ``n_shards`` by REPLAYING the
    vnode mapping (common/hashing.shard_rows — the same ``vnode_of →
    vnode_to_shard`` the in-dispatch all_to_all routes with), then load
    each shard's slice. This is the re-shard path: the durable table is
    shard-count-agnostic, so an 8-shard checkpoint reopens cleanly on a
    4-shard (or solo) mesh."""
    per_shard = shard_rows(core.key_types, rows, n_shards)
    return [load_agg_rows(core, rs) for rs in per_shard]


def _empty_join_payload(core) -> dict:
    nb, W = core.n_buckets, core.W
    return {
        "win_id": np.full(nb, -1, np.int64),
        "fill": np.zeros(nb, np.int32),
        "touched": np.zeros(nb, bool),
        "cur_max": np.full(nb, _NEG, np.int64),
        "cur_cnt": np.zeros(nb, np.int64),
        "emitted_max": np.full(nb, _NEG, np.int64),
        "emitted_live": np.zeros(nb, bool),
        "lane_overflow": np.zeros((), bool),
        "ring_clobber": np.zeros((), bool),
        "saw_delete": np.zeros((), bool),
        "row_data": [np.zeros((nb, W), f.type.np_dtype)
                     for f in core.probe_schema],
        "row_mask": [np.zeros((nb, W), bool) for _ in core.probe_schema],
    }


_JOIN_BUCKET_FIELDS = ("win_id", "fill", "touched", "cur_max", "cur_cnt",
                       "emitted_max", "emitted_live")
_JOIN_FLAG_FIELDS = ("lane_overflow", "ring_clobber", "saw_delete")


def reshard_join_payloads(old_core, payloads: Sequence, new_core,
                          new_n: int) -> list:
    """Re-bucket per-shard interval-join checkpoint payloads onto a
    ``new_n``-shard mesh: every resident window re-routes by replaying
    the vnode mapping over its window-start value — the exact hash the
    in-dispatch all_to_all applies to that window's rows — and lands at
    ``win_id % new_nb`` in its new owner's ring. Ring geometry may shrink
    with the mesh (windows spread ~uniformly); a destination collision
    (two live windows sharing a slot) raises instead of clobbering."""
    if old_core.W != new_core.W or \
            len(old_core.probe_schema) != len(new_core.probe_schema):
        raise ValueError("re-shard requires identical lane geometry "
                         "(lane_width / probe schema)")
    if old_core.window_us != new_core.window_us or \
            old_core.ts_col != new_core.ts_col or \
            old_core.probe_schema[old_core.ts_col].type.np_dtype != \
            new_core.probe_schema[new_core.ts_col].type.np_dtype:
        # win_id values are copied verbatim: a different window (or ts
        # layout) would relabel every resident window AND route it
        # differently than the live all_to_all — refuse, don't split-brain
        raise ValueError("re-shard requires identical window config "
                         "(window_us / ts_col)")
    nb_new = new_core.n_buckets
    ts_dtype = old_core.probe_schema[old_core.ts_col].type.np_dtype
    outs = [_empty_join_payload(new_core) for _ in range(new_n)]
    for p in payloads:
        for f in _JOIN_FLAG_FIELDS:
            flag = bool(np.asarray(p[f]))
            for o in outs:      # sticky flags stay visible on every shard
                o[f] = o[f] | flag
        win = np.asarray(p["win_id"])
        idx = np.nonzero(win >= 0)[0]
        if not len(idx):
            continue
        ws = (win[idx] * old_core.window_us).astype(np.dtype(ts_dtype))
        col = Column(jnp.asarray(ws), jnp.ones(len(idx), jnp.bool_))
        shard = np.asarray(vnode_to_shard(vnode_of([col]), new_n))
        slot = win[idx] % nb_new
        for j, b in enumerate(idx):
            s, t = int(shard[j]), int(slot[j])
            o = outs[s]
            if o["win_id"][t] != -1:
                raise RuntimeError(
                    f"re-shard bucket collision on shard {s} slot {t}; "
                    "increase the new core's n_buckets")
            for f in _JOIN_BUCKET_FIELDS:
                o[f][t] = p[f][b]
            for c in range(len(o["row_data"])):
                o["row_data"][c][t] = p["row_data"][c][b]
                o["row_mask"][c][t] = p["row_mask"][c][b]
    return outs


def _route_keys(key_type, keys: Sequence, new_n: int) -> np.ndarray:
    """Owner shard per key value — the host-side replay of the exact
    ``vnode_of → vnode_to_shard`` hash the in-dispatch all_to_all routes
    with, composed from the canonical helpers (never re-derived, so a
    future change to the vnode→shard mapping cannot strand durable
    rows)."""
    vns = vnodes_of_rows((key_type,), [(k,) for k in keys])
    return np.asarray(vnode_to_shard(jnp.asarray(vns, jnp.int32), new_n))


def reshard_session_payloads(core, payloads: Sequence, new_n: int) -> list:
    """Re-partition per-shard session-window checkpoint payloads
    (SessionWindowCore.export_host) onto a ``new_n``-shard mesh: every
    open session re-routes by replaying the vnode mapping over its key —
    the exact hash the in-dispatch all_to_all applies to that key's
    rows — and closed-but-undrained buffer rows follow their key. Sticky
    flags stay visible on every shard. An 8-shard checkpoint reopens
    cleanly on 4 shards (or solo)."""
    open_rows: list = []     # (key, sess_start, last_ts, count)
    closed_rows: list = []   # (key, start, end, cnt)
    flags = {f: False for f in ("overflow", "closed_overflow",
                                "saw_delete", "out_of_order")}
    for p in payloads:
        for f in flags:
            flags[f] = flags[f] or bool(np.asarray(p[f]))
        occ = np.asarray(p["table_occupied"])
        live = occ & (np.asarray(p["sess_start"]) >= 0)
        kd = np.asarray(p["table_key_data"][0])
        for slot in np.nonzero(live)[0]:
            open_rows.append((int(kd[slot]),
                              int(p["sess_start"][slot]),
                              int(p["last_ts"][slot]),
                              int(p["count"][slot])))
        fill = int(np.asarray(p["closed_fill"]))
        for r in range(fill):
            closed_rows.append((int(p["closed_key"][r]),
                                int(p["closed_start"][r]),
                                int(p["closed_end"][r]),
                                int(p["closed_cnt"][r])))
    open_shard = _route_keys(core.key_type, [r[0] for r in open_rows],
                             new_n)
    closed_shard = _route_keys(core.key_type,
                               [r[0] for r in closed_rows], new_n)
    states = []
    for s in range(new_n):
        st = core.init_state()
        mine = [open_rows[i] for i in np.nonzero(open_shard == s)[0]]
        if mine:
            data = np.array([r[0] for r in mine],
                            dtype=core.key_type.np_dtype)
            kcol = Column(jnp.asarray(data),
                          jnp.ones(len(mine), jnp.bool_))
            table, slots, _, ovf = ht_lookup_or_insert(
                st.table, [kcol], jnp.ones(len(mine), jnp.bool_))
            if bool(ovf):
                raise RuntimeError(
                    f"session re-shard: shard {s} key table overflow "
                    f"(capacity {core.capacity}); increase capacity")
            st = st.replace(
                table=table,
                sess_start=st.sess_start.at[slots].set(
                    jnp.asarray([r[1] for r in mine], jnp.int64)),
                last_ts=st.last_ts.at[slots].set(
                    jnp.asarray([r[2] for r in mine], jnp.int64)),
                count=st.count.at[slots].set(
                    jnp.asarray([r[3] for r in mine], jnp.int64)))
        cmine = [closed_rows[i] for i in np.nonzero(closed_shard == s)[0]]
        if cmine:
            if len(cmine) > core.closed_capacity:
                raise RuntimeError(
                    f"session re-shard: shard {s} closed buffer overflow")
            pos = jnp.arange(len(cmine))
            st = st.replace(
                closed_key=st.closed_key.at[pos].set(
                    jnp.asarray([r[0] for r in cmine], jnp.int64)),
                closed_start=st.closed_start.at[pos].set(
                    jnp.asarray([r[1] for r in cmine], jnp.int64)),
                closed_end=st.closed_end.at[pos].set(
                    jnp.asarray([r[2] for r in cmine], jnp.int64)),
                closed_cnt=st.closed_cnt.at[pos].set(
                    jnp.asarray([r[3] for r in cmine], jnp.int64)),
                closed_fill=jnp.asarray(len(cmine), jnp.int32))
        st = st.replace(**{
            f: jnp.asarray(v, jnp.bool_) for f, v in flags.items()})
        states.append(st)
    return states


def reshard_q3_payloads(core, payloads: Sequence, new_n: int) -> list:
    """Re-partition per-shard q3 checkpoint payloads
    (Q3Core.export_host) onto a ``new_n``-shard mesh: qualifying orders
    (key + odate/prio lanes) and their revenue groups re-route by the
    orderkey vnode — the same hash the in-dispatch all_to_all routes
    events with, so an order and its group always land together — and
    the replicated emitted top-n buffer copies to every shard. Requires
    the same core geometry (capacities / limit are mesh-independent)."""
    order_rows: list = []    # (okey, odate, prio)
    agg_rows: list = []      # (okey, *lanes)
    flags = {f: False for f in ("orders_overflow", "saw_delete")}
    agg_overflow = False
    for p in payloads:
        for f in flags:
            flags[f] = flags[f] or bool(np.asarray(p[f]))
        agg = p["agg"]
        agg_overflow = agg_overflow or bool(np.asarray(agg.overflow))
        occ = np.asarray(p["orders_occupied"])
        kd = np.asarray(p["orders_key_data"][0])
        for slot in np.nonzero(occ)[0]:
            order_rows.append((int(kd[slot]), int(p["odate"][slot]),
                               int(p["prio"][slot])))
        aocc = np.asarray(agg.table.occupied)
        akd = np.asarray(agg.table.key_data[0])
        lanes = [np.asarray(l) for l in agg.lanes]
        for slot in np.nonzero(aocc)[0]:
            agg_rows.append((int(akd[slot]),)
                            + tuple(int(l[slot]) for l in lanes))
    from ..common.types import INT64
    order_shard = _route_keys(INT64, [r[0] for r in order_rows], new_n)
    agg_by_shard = [[] for _ in range(new_n)]
    for r, s in zip(agg_rows,
                    _route_keys(INT64, [r[0] for r in agg_rows], new_n)):
        agg_by_shard[int(s)].append(r)
    emitted = payloads[0]       # replicated across shards by the flush
    states = []
    for s in range(new_n):
        st = core.init_state()
        mine = [order_rows[i] for i in np.nonzero(order_shard == s)[0]]
        if mine:
            data = np.array([r[0] for r in mine], dtype=np.int64)
            kcol = Column(jnp.asarray(data),
                          jnp.ones(len(mine), jnp.bool_))
            orders, slots, _, ovf = ht_lookup_or_insert(
                st.orders, [kcol], jnp.ones(len(mine), jnp.bool_))
            if bool(ovf):
                raise RuntimeError(
                    f"q3 re-shard: shard {s} orders table overflow "
                    f"(capacity {core.orders_capacity})")
            st = st.replace(
                orders=orders,
                odate=st.odate.at[slots].set(
                    jnp.asarray([r[1] for r in mine], jnp.int64)),
                prio=st.prio.at[slots].set(
                    jnp.asarray([r[2] for r in mine], jnp.int64)))
        agg_state = load_rows_into_state(core.agg, st.agg,
                                         agg_by_shard[s])
        st = st.replace(
            agg=agg_state.replace(
                prev_lanes=agg_state.lanes,
                overflow=jnp.asarray(agg_overflow, jnp.bool_)),
            emitted_key=jnp.asarray(emitted["emitted_key"]),
            emitted_rev=jnp.asarray(emitted["emitted_rev"]),
            emitted_odate=jnp.asarray(emitted["emitted_odate"]),
            emitted_prio=jnp.asarray(emitted["emitted_prio"]),
            emitted_valid=jnp.asarray(emitted["emitted_valid"]),
            **{f: jnp.asarray(v, jnp.bool_) for f, v in flags.items()})
        states.append(st)
    return states


# ---------------------------------------------------------------------------
# co-scheduled groups × the shard axis: the K-jobs × S-shards driver
# ---------------------------------------------------------------------------


class ShardedCoGroup(_GrowRetryMixin):
    """One signature's job set sharded over a mesh: K signature-equal
    source+agg MVs × S shards tick in ONE dispatch per epoch
    (ops/fused_sharded.build_sharded_group_epoch — the sixth fusion
    surface). State leaves carry ``[n_shards, J, ...]`` with the leading
    axis on the mesh; per-job identity (event cursor, PRNG seed, batch
    counter) rides as data exactly like the mesh-less CoGroup, and the
    routing-overflow grow-retry is the ShardedFusedAgg idiom applied
    group-wide (one overflowing job replays the whole group's epoch from
    the untouched previous state — deterministic, so the retry is
    exact for every member)."""

    def __init__(self, mesh, spec, recv_width: int = 2):
        if spec.kind != "agg":
            raise ValueError(
                "sharded co-scheduling covers the source+agg shape only")
        self._init_retry(mesh, recv_width)
        self.core = spec.core
        self.chunk_fn = spec.chunk_fn
        self.exprs = tuple(spec.exprs)
        self.rows_per_chunk = int(spec.rows_per_chunk)
        self.signature = spec.signature
        self.names: list[str] = []
        self.starts: list[int] = []
        self.batch_nos: list[int] = []
        self.seeds: list[int] = []
        self.stacked = None
        self._base_keys = None
        self._rovf = None
        self.pending: Optional[PendingFlush] = None
        self._probe = _sharded_agg_probe(self.core, job_axis=True)
        self._finish = profile_dispatch(
            jax.jit(jax.vmap(jax.vmap(self.core.finish_flush))),
            "sharded_group_finish")

        core = self.core

        def gather(stacked, ranks, s, j, lo):
            st = jax.tree_util.tree_map(lambda x: x[s, j], stacked)
            return core.gather_flush_chunk(st, ranks[s, j], lo)

        self._gather = profile_dispatch(jax.jit(gather),
                                        gather.__qualname__)

    def _build_epoch(self, width: int) -> Callable:
        return build_sharded_group_epoch(
            self.chunk_fn, self.exprs, self.core, self.rows_per_chunk,
            self.mesh, width)

    # -- membership -----------------------------------------------------------

    @property
    def n_jobs(self) -> int:
        return len(self.names)

    def add(self, name: str, shard_states: Optional[Sequence] = None,
            start: int = 0, seed: int = 0, batch_no: int = 0) -> None:
        """Join the group. ``shard_states``: the job's n solo-shaped
        per-shard states (recovery re-shard), or None for fresh."""
        if name in self.names:
            raise ValueError(f"job {name!r} already sharded-co-scheduled")
        assert self.pending is None, \
            "membership change with a flush in flight (drain first)"
        self._settle()
        self._rovf = None       # shaped [n, J_old]; J changes below
        if shard_states is None:
            shard_states = [self.core.init_state()
                            for _ in range(self.n)]
        if len(shard_states) != self.n:
            raise ValueError(
                f"{len(shard_states)} shard states for a "
                f"{self.n}-device mesh")
        ss = stack_states(list(shard_states))          # leaves [n, ...]
        if self.stacked is None:
            self.stacked = self._put(jax.tree_util.tree_map(
                lambda x: jnp.expand_dims(x, 1), ss))
        else:
            self.stacked = self._put(jax.tree_util.tree_map(
                lambda xs, x: jnp.concatenate(
                    [xs, jnp.expand_dims(x, 1)], axis=1),
                self.stacked, ss))
        self.names.append(name)
        self.starts.append(int(start))
        self.batch_nos.append(int(batch_no))
        self.seeds.append(int(seed))
        self._base_keys = None

    def remove(self, name: str) -> list:
        """Drop a job; returns its final n solo-shaped shard states."""
        assert self.pending is None, \
            "membership change with a flush in flight (drain first)"
        self._settle()
        self._rovf = None       # shaped [n, J_old]; J changes below
        j = self.names.index(name)
        states = self.shard_states_of(name)
        if self.n_jobs > 1:
            self.stacked = self._put(jax.tree_util.tree_map(
                lambda x: jnp.concatenate([x[:, :j], x[:, j + 1:]],
                                          axis=1), self.stacked))
        else:
            self.stacked = None
        for lst in (self.names, self.starts, self.batch_nos, self.seeds):
            lst.pop(j)
        self._base_keys = None
        return states

    def shard_states_of(self, name: str) -> list:
        j = self.names.index(name)
        return [jax.tree_util.tree_map(lambda x: x[s, j], self.stacked)
                for s in range(self.n)]

    # -- ticking --------------------------------------------------------------

    def _keys(self):
        if self._base_keys is None:
            self._base_keys = jnp.stack(
                [jax.random.PRNGKey(s) for s in self.seeds])
        return self._base_keys

    def _settle(self) -> None:
        while self._pending is not None:
            if bool(np.any(np.asarray(fetch(self._rovf)))):
                self.stacked, self._rovf = self._grow_and_retry()
            else:
                self._pending = None

    def run_epoch(self, k: int) -> None:
        """ONE dispatch: every member job advances k chunks across every
        shard of the mesh. Routing-overflow validation settles at the
        next flush — same tick, zero extra host syncs."""
        self._settle()
        starts = jnp.asarray(self.starts, jnp.int64)
        nos = jnp.asarray(self.batch_nos, jnp.int64)
        args = (starts, self._keys(), nos, int(k))
        self._pending = (self.stacked, args)
        self.stacked, self._rovf = self._epoch_fn()(self.stacked, *args)
        for j in range(self.n_jobs):
            self.starts[j] += k * self.rows_per_chunk
            self.batch_nos[j] += 1
        self.epochs_run += 1

    def begin_flush(self) -> PendingFlush:
        """Start the K×S barrier flush without resolving it: probe
        enqueued, packed [n, J, 3] stats streaming host-ward, vmapped
        finish enqueued eagerly so the next epoch can dispatch before
        the fetch resolves (pipeline_depth = 2). The route-overflow
        retry signal rides the same packed fetch, so validation is
        deferred with it — the grow-retry in ``finish_flush`` replays
        from the untouched pre-epoch state the ``_pending`` slot holds
        (sharded epochs never donate)."""
        assert self.pending is None, "flush already in flight"
        packed, ranks = self._probe(
            self.stacked,
            self._rovf if self._rovf is not None
            else jnp.zeros((self.n, self.n_jobs), jnp.bool_))
        self.pending = PendingFlush(
            self.stacked, packed, ranks,
            async_fetch(packed, dispatch=self._probe.__qualname__))
        self.stacked = self._finish(self.stacked)
        return self.pending

    def finish_flush(self) -> dict:
        """Resolve the in-flight K×S flush: ONE packed fetch covers
        every (shard, job) cell's dirty count / overflow / route flag;
        a set route flag drains the pipeline and grow-retries the whole
        group's epoch before gathering. Returns
        {job: [StreamChunk, ...]} in shard-major order per job."""
        p = self.pending
        if p is None:
            p = self.begin_flush()
        self.pending = None
        packed_h = np.asarray(p.fetch.result())
        gather_stacked, ranks = p.stacked, p.ranks
        retried = False
        while self._pending is not None and packed_h[:, :, 2].any():
            # grow-retry drains the pipeline: the replayed epoch (and
            # its re-probe) must validate before anything else may
            # dispatch, so this re-fetch is deliberately synchronous
            gather_stacked, self._rovf = self._grow_and_retry()
            packed, ranks = self._probe(gather_stacked, self._rovf)
            # rwlint: allow(sync-fetch-discipline): grow-retry drain — the replayed epoch must validate before the tick proceeds
            packed_h = np.asarray(jax.device_get(packed))
            # the raw fetch above IS this probe's completion: pop the
            # profiler's inflight FIFO or every later completion would
            # match a stale enqueue timestamp
            GLOBAL_PROFILER.note_complete(self._probe.__qualname__)
            retried = True
        if retried:
            # ONE finish over the settled state (begin_flush already
            # finished the no-retry case; per-iteration finishes would
            # just be discarded dispatches)
            self.stacked = self._finish(gather_stacked)
        self._pending = None
        self._rovf = None
        out: dict = {}
        for j, name in enumerate(self.names):
            chunks = []
            for s in range(self.n):
                n_dirty = int(packed_h[s, j, 0])
                if int(packed_h[s, j, 1]):
                    raise RuntimeError(
                        f"sharded co-scheduled job {name!r}: shard {s} "
                        f"group table overflow (per-shard capacity "
                        f"{self.core.capacity}); increase "
                        "agg_table_capacity")
                lo = 0
                while lo < n_dirty:
                    chunks.append(self._gather(
                        gather_stacked, ranks, jnp.int64(s),
                        jnp.int64(j), jnp.int64(lo)))
                    lo += self.core.groups_per_chunk
            out[name] = chunks
        return out

    def flush(self) -> dict:
        """Synchronous barrier flush (begin + finish in one call) —
        exactly ShardedFusedAgg.flush per member, the pre-pipeline
        cadence and still the default."""
        if self.pending is None:
            self.begin_flush()
        return self.finish_flush()

    # -- durability -----------------------------------------------------------

    def checkpoint(self, engines: dict, epoch: int) -> None:
        """Write every (job, shard) delta through each job's OWN
        HashAggExecutor persistence engine (hash partitioning keeps a
        job's per-shard keys disjoint, so the deltas union cleanly in
        that job's state table), then restack the whole group once."""
        self._settle()
        per_job = []
        for name in self.names:
            engine = engines[name]
            shard_states = []
            for s in range(self.n):
                engine.state = jax.tree_util.tree_map(
                    lambda x, s=s, j=self.names.index(name): x[s, j],
                    self.stacked)
                engine._checkpoint_to_state_table(epoch)
                shard_states.append(engine.state)
            per_job.append(stack_states(shard_states))
        self.stacked = self._put(jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=1), *per_job))


class ShardedCoScheduler:
    """Signature-keyed registry of K×S groups (one per mesh Session) —
    the sharded twin of stream/coschedule.CoScheduler."""

    def __init__(self, mesh, recv_width: int = 2):
        self.mesh = mesh
        self.recv_width = recv_width
        self.groups: dict[tuple, ShardedCoGroup] = {}
        self.jobs: dict[str, ShardedCoGroup] = {}

    def add(self, name: str, spec, shard_states=None, start: int = 0,
            batch_no: int = 0) -> ShardedCoGroup:
        group = self.groups.get(spec.signature)
        if group is None:
            group = ShardedCoGroup(self.mesh, spec,
                                   recv_width=self.recv_width)
            self.groups[spec.signature] = group
        group.add(name, shard_states, start=start, seed=spec.seed,
                  batch_no=batch_no)
        self.jobs[name] = group
        return group

    def remove(self, name: str):
        """Drop a job; returns ``(shard_states, group)`` (group for the
        caller's epoch-retirement bookkeeping) or ``(None, None)``."""
        group = self.jobs.pop(name, None)
        if group is None:
            return None, None
        states = group.remove(name)
        if group.n_jobs == 0:
            self.groups.pop(group.signature, None)
        return states, group

    def stats(self) -> dict:
        return {
            "jobs": len(self.jobs),
            "groups": [
                {"shards": g.n, "jobs": list(g.names),
                 "epochs_run": g.epochs_run,
                 "recv_width": g.recv_width,
                 "route_grows": g.route_grows}
                for g in self.groups.values()
            ],
        }
