"""Sharded stream executors — the frontend-facing wrappers that run the
multi-chip cores inside the ordinary executor protocol.

This is the TPU-native replacement for the reference's parallel actor
fan-out: where the reference builds P parallel HashAgg/HashJoin actors
connected by hash dispatchers and merge executors over gRPC exchanges
(reference: src/stream/src/executor/dispatch.rs:532 hash dispatch,
src/stream/src/executor/merge.rs:36 fan-in, docs/consistent-hash.md), here a
SINGLE executor owns mesh-sharded device state and every chunk step is one
XLA program whose internal ``lax.all_to_all`` does the routing over ICI —
the exchange layer has no host-visible existence at all.

An input chunk of capacity C is split into n local chunks of capacity C/n
(leading [n] axis sharded over the mesh); the vnode shuffle inside the step
re-routes rows to their owner shard, so the host-side split is free-form.
Emission gathers per-shard output windows back to the driving device —
correctness-first for now; a sharded MaterializeExecutor keeps egress
device-resident later.

Durability mirrors the single-chip executors: dirty deltas flush to host
StateTables on checkpoint barriers; recovery re-routes committed rows by
replaying them through the sharded step (join) or per-shard direct loads
(agg).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, DEFAULT_CHUNK_CAPACITY, StreamChunk, count_units,
    gather_units_window, pad_chunk, physical_chunk,
)
from ..common.types import Field, Schema
from ..expr.agg import AggCall
from ..ops.hash_table import ht_lookup_or_insert
from ..ops.join_state import JoinType
from ..storage.state_table import StateTable
from ..stream.barrier_align import barrier_align
from ..stream.executor import Executor, SingleInputExecutor
from ..stream.hash_join import _clear_ckpt_marks
from ..stream.message import Barrier
from .sharded_agg import ShardedHashAgg
from .sharded_join import ShardedHashJoin


def split_chunk(chunk: StreamChunk, n: int, sharding) -> StreamChunk:
    """Pad to a multiple of n and reshape into n local chunks (leading [n]
    axis placed on the mesh); the in-step vnode shuffle re-routes rows, so
    this split is free-form."""
    chunk = pad_chunk(chunk, -(-chunk.capacity // n) * n)
    stacked = jax.tree_util.tree_map(
        lambda x: x.reshape((n, -1) + x.shape[1:]), chunk)
    return jax.device_put(
        stacked, jax.tree_util.tree_map(lambda _: sharding, stacked))


class ShardedHashAggExecutor(SingleInputExecutor):
    """Data-parallel grouped aggregation over a device mesh, behind the
    single-chip HashAggExecutor's exact protocol surface."""

    identity = "ShardedHashAgg"

    def __init__(
        self,
        input: Executor,
        mesh,
        group_keys: Sequence[int],
        agg_calls: Sequence[AggCall],
        state_table: Optional[StateTable] = None,
        table_capacity: int = 1 << 14,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
    ):
        super().__init__(input)
        in_schema = input.schema
        key_types = tuple(in_schema[i].type for i in group_keys)
        self.agg = ShardedHashAgg(mesh, key_types, list(group_keys),
                                  list(agg_calls), table_capacity, out_capacity)
        self.schema = Schema(
            tuple(in_schema[i] for i in group_keys)
            + tuple(Field(f"agg{i}", c.output_type)
                    for i, c in enumerate(agg_calls))
        )
        self.state_table = state_table
        self.n = self.agg.n
        core = self.agg.core
        from ..common.chunk import flatten_shards
        self._gather = jax.jit(
            jax.vmap(core.gather_flush_chunk, in_axes=(0, 0, None)))
        self._flatten = jax.jit(flatten_shards)
        self._rank = jax.jit(jax.vmap(core.flush_rank))
        self._finish = jax.jit(jax.vmap(core.finish_flush))
        if self.state_table is not None:
            self._load_from_state_table()

    async def map_chunk(self, chunk: StreamChunk):
        self.agg.step(split_chunk(chunk, self.n, self.agg._sharding))
        if False:
            yield

    async def on_barrier(self, barrier: Barrier):
        st = self.agg.state
        rank = self._rank(st)
        counts, overflow = jax.device_get((rank[:, -1], st.overflow))
        if bool(np.any(overflow)):
            raise RuntimeError(
                f"{self.identity}: group table overflow (per-shard capacity "
                f"{self.agg.core.capacity}); increase table_capacity")
        G = self.agg.core.groups_per_chunk
        lo = 0
        while lo < int(counts.max(initial=0)):
            # egress stays on device: all shards' windows flatten into ONE
            # wide chunk per window (invalid rows are vis-masked by the
            # gather) — no per-shard host slicing (VERDICT r3 item 9)
            batch = self._gather(self.agg.state, rank, jnp.int64(lo))
            yield self._flatten(batch)
            lo += G
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint_to_state_table(barrier.epoch.curr)
        self.agg.state = self._finish(self.agg.state)

    # -- persistence ----------------------------------------------------------

    def _checkpoint_to_state_table(self, epoch: int) -> None:
        st = jax.device_get(self.agg.state)
        wrote = False
        for s in range(self.n):
            idx = np.nonzero(np.asarray(st.ckpt_dirty[s]))[0]
            if not len(idx):
                continue
            wrote = True
            keys_d = [np.asarray(kd[s])[idx] for kd in st.table.key_data]
            keys_m = [np.asarray(km[s])[idx] for km in st.table.key_mask]
            lanes = [np.asarray(l[s])[idx] for l in st.lanes]
            for r in range(len(idx)):
                key_vals = [
                    keys_d[c][r].item() if keys_m[c][r] else None
                    for c in range(len(keys_d))
                ]
                lane_vals = [lanes[j][r].item() for j in range(len(lanes))]
                row = tuple(key_vals) + tuple(lane_vals)
                if lanes[0][r] > 0:
                    self.state_table.insert(row)
                else:
                    self.state_table.delete(row)
        if wrote:
            self.state_table.commit(epoch)
        self.agg.state = self.agg.state.replace(
            ckpt_dirty=jnp.zeros_like(self.agg.state.ckpt_dirty))

    def _load_from_state_table(self) -> None:
        """Recovery: route committed groups to their owner shard (same vnode
        map the shuffle uses) and load keys + lanes directly."""
        from ..common.hashing import vnode_of, vnode_to_shard

        rows = list(self.state_table.scan_all())
        if not rows:
            return
        core = self.agg.core
        nk = len(core.group_keys)
        key_cols = []
        for c in range(nk):
            vals = [r[c] for r in rows]
            mask = np.array([v is not None for v in vals])
            data = np.array([v if v is not None else 0 for v in vals],
                            dtype=core.key_types[c].np_dtype)
            key_cols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
        shard = np.asarray(vnode_to_shard(vnode_of(key_cols), self.n))

        st_host = jax.device_get(self.agg.state)
        shards = []
        for s in range(self.n):
            local = jax.tree_util.tree_map(lambda x: jnp.asarray(x[s]), st_host)
            sel = np.nonzero(shard == s)[0]
            bs = 1024
            for i in range(0, len(sel), bs):
                batch_idx = sel[i:i + bs]
                n = len(batch_idx)
                valid = jnp.arange(bs) < n
                kcols = []
                for c in range(nk):
                    vals = [rows[j][c] for j in batch_idx]
                    mask = np.array([v is not None for v in vals]
                                    + [False] * (bs - n))
                    data = np.array(
                        [v if v is not None else 0 for v in vals] + [0] * (bs - n),
                        dtype=core.key_types[c].np_dtype)
                    kcols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
                table, slots, _, ovf = ht_lookup_or_insert(
                    local.table, kcols, valid)
                if bool(ovf):
                    raise RuntimeError(
                        "sharded agg table overflow during recovery load")
                lanes = list(local.lanes)
                for j in range(len(lanes)):
                    vals = np.array(
                        [rows[r][nk + j] for r in batch_idx] + [0] * (bs - n),
                        dtype=np.dtype(core.lane_dtypes[j]))
                    lanes[j] = lanes[j].at[slots].set(
                        jnp.asarray(vals), mode="drop")
                local = local.replace(table=table, lanes=tuple(lanes))
            local = local.replace(prev_lanes=local.lanes)
            shards.append(local)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *shards)
        self.agg.state = jax.device_put(
            stacked,
            jax.tree_util.tree_map(lambda _: self.agg._sharding, stacked))


class ShardedHashJoinExecutor(Executor):
    """Data-parallel streaming hash join over a device mesh, behind the
    single-chip HashJoinExecutor's exact protocol surface."""

    identity = "ShardedHashJoin"

    def __init__(
        self,
        left: Executor,
        right: Executor,
        mesh,
        left_keys: Sequence[int],
        right_keys: Sequence[int],
        join_type: JoinType = JoinType.INNER,
        condition=None,
        left_state_table: Optional[StateTable] = None,
        right_state_table: Optional[StateTable] = None,
        key_capacity: int = 1 << 10,
        bucket_width: int = 8,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
    ):
        self.left, self.right = left, right
        from ..stream.metrics import ExecutorStats
        self.stats = ExecutorStats()
        self.join = ShardedHashJoin(
            mesh, left.schema, right.schema, left_keys, right_keys,
            join_type, condition=condition, key_capacity=key_capacity,
            bucket_width=bucket_width)
        self.schema = self.join.out_schema
        self.out_capacity = out_capacity
        self.n = self.join.n
        self.state_tables = {"left": left_state_table,
                             "right": right_state_table}
        self._count = jax.jit(jax.vmap(count_units))
        cap = out_capacity
        from ..common.chunk import flatten_shards
        self._gather = jax.jit(jax.vmap(
            lambda ch, lo: gather_units_window(ch, lo, cap),
            in_axes=(0, None)))
        self._flatten = jax.jit(flatten_shards)
        self._clear_ckpt = jax.jit(jax.vmap(_clear_ckpt_marks))
        # match-unit batches buffered in arrival order (interleaved with
        # watermarks, which must not outrun same-epoch data): counts are
        # fetched ONCE per flush for many chunks instead of one device_get
        # per chunk (VERDICT r3 weak #6 / item 9 — per-chunk syncs dominate
        # wall clock on tunneled chips). Flushed at every barrier and
        # whenever MAX_PENDING_UNITS batches are resident, bounding HBM.
        self._pending_msgs: list = []      # ("units", big) | ("wm", wm)
        self._n_pending_units = 0
        # INPUT chunks also batch: a run of same-side chunks is held and
        # joined by ONE fused dispatch (ShardedHashJoin.step_epoch — the
        # generic sharded-fused equi-join surface) instead of one
        # dispatch per chunk; a side switch, watermark, barrier or the
        # MAX_PENDING_UNITS bound cuts the run
        self._in_side = None
        self._in_run: list = []
        if any(self.state_tables.values()):
            self._load_from_state_tables()

    #: device-resident unit batches allowed before a forced flush
    MAX_PENDING_UNITS = 16

    def _run_pending_inputs(self) -> None:
        """Join the buffered same-side input run in one fused dispatch;
        its emission grids queue for the next output flush in order."""
        if not self._in_run:
            return
        bigs = self.join.step_epoch(self._in_side, self._in_run)
        for big in bigs:
            self._pending_msgs.append(("units", big))
        self._n_pending_units += len(bigs)
        self._in_side = None
        self._in_run = []

    def _flush_pending(self):
        """Emit buffered match-unit windows and watermarks in arrival
        order; ONE host transfer covers every pending batch's counts."""
        if not self._n_pending_units:
            for kind, item in self._pending_msgs:
                yield item                     # watermarks only
            self._pending_msgs.clear()
            return
        counts_all = jax.device_get(
            [self._count(item) for kind, item in self._pending_msgs
             if kind == "units"])
        G = self.out_capacity // 2
        ci = 0
        for kind, item in self._pending_msgs:
            if kind == "wm":
                yield item
                continue
            counts = counts_all[ci]
            ci += 1
            lo = 0
            while lo < int(counts.max(initial=0)):
                self.stats.chunks_out += 1
                yield self._flatten(self._gather(item, jnp.int64(lo)))
                lo += G
        self._pending_msgs.clear()
        self._n_pending_units = 0

    async def execute(self):
        from ..stream.metrics import barrier_timer
        stats = self.stats
        async for ev in barrier_align(self.left, self.right):
            kind = ev[0]
            if kind == "chunk":
                _, side, chunk = ev
                stats.chunks_in += 1
                stats.capacity_rows_in += chunk.capacity
                if self._in_side is not None and self._in_side != side:
                    # side switch cuts the fused run (arrival order is
                    # the emission contract)
                    self._run_pending_inputs()
                self._in_side = side
                self._in_run.append(
                    split_chunk(chunk, self.n, self.join._sharding))
                # emission deferred (bounded): inputs AND outputs stay
                # resident on device until the next flush, so the data
                # path has no host sync — and no dispatch — per chunk
                if (len(self._in_run) + self._n_pending_units
                        >= self.MAX_PENDING_UNITS):
                    self._run_pending_inputs()
                    for out in self._flush_pending():
                        yield out
            elif kind == "barrier":
                barrier = ev[1]
                self._run_pending_inputs()
                for out in self._flush_pending():
                    yield out
                with barrier_timer(stats, self.identity, barrier.epoch.curr):
                    self._check_flags()
                    if barrier.checkpoint:
                        self._checkpoint(barrier.epoch.curr)
                yield barrier
                if barrier.is_stop():
                    return
            elif kind == "watermark":
                _, side, wm = ev
                stats.watermarks += 1
                out_idx = self._map_watermark_col(side, wm.col_idx)
                if out_idx is not None:
                    # buffered in order: a watermark must not overtake
                    # same-epoch data rows still pending on device —
                    # including input chunks not yet joined
                    self._run_pending_inputs()
                    self._pending_msgs.append(
                        ("wm", wm.__class__(out_idx, wm.value)))

    def _map_watermark_col(self, side: str, col_idx: int) -> Optional[int]:
        sa = self.join.core.join_type.semi_anti_side
        if sa is not None:
            return col_idx if sa == side else None
        return (col_idx if side == "left"
                else col_idx + len(self.join.core.left_schema))

    def _check_flags(self) -> None:
        st = jax.device_get(self.join.state)
        for side in ("left", "right"):
            s = getattr(st, side)
            if bool(np.any(s.inconsistent)):
                raise RuntimeError(
                    f"{self.identity}: {side} saw delete of an absent row")

    # -- persistence ----------------------------------------------------------

    def _checkpoint(self, epoch: int) -> None:
        st = jax.device_get(self.join.state)
        for side in ("left", "right"):
            table = self.state_tables[side]
            if table is None:
                continue
            side_st = getattr(st, side)
            # deletes strictly before inserts ACROSS ALL SHARDS: a same-pk
            # row whose join key moved to a lower-numbered shard within one
            # checkpoint window would otherwise have its old-shard delete
            # clobber the new-shard upsert (StateTable.delete is pk-keyed)
            deletes, inserts = [], []
            for sh in range(self.n):
                dirty = np.asarray(side_st.ckpt_dirty[sh])
                slots, lanes = np.nonzero(dirty)
                if not len(slots):
                    continue
                occ = np.asarray(side_st.occupied[sh])
                tomb = np.asarray(side_st.tomb[sh])
                datas = [np.asarray(d[sh]) for d in side_st.row_data]
                masks = [np.asarray(m[sh]) for m in side_st.row_mask]

                def row_at(s, l):
                    return tuple(
                        datas[c][s, l].item() if masks[c][s, l] else None
                        for c in range(len(datas)))

                for s, l in zip(slots, lanes):
                    if tomb[s, l] and not occ[s, l]:
                        deletes.append(row_at(s, l))
                    elif occ[s, l]:
                        inserts.append(row_at(s, l))
            for row in deletes:
                table.delete(row)
            for row in inserts:
                table.insert(row)
            table.commit(epoch)
        self.join.state = self._clear_ckpt(self.join.state)

    def _load_from_state_tables(self) -> None:
        """Recovery: replay both sides' committed rows through the sharded
        insert step (the all_to_all re-routes them); outputs discarded."""
        for side in ("left", "right"):
            table = self.state_tables[side]
            if table is None:
                continue
            schema = (self.join.core.left_schema if side == "left"
                      else self.join.core.right_schema)
            rows = list(table.scan_all())
            bs = 256
            stride = self.n * bs
            for i in range(0, len(rows), stride):
                group = rows[i:i + stride]
                chunks = [
                    physical_chunk(schema, group[j * bs:(j + 1) * bs], bs)
                    for j in range(self.n)
                ]
                self.join.step(side, self.join.batch_chunks(chunks))
        self.join.state = self._clear_ckpt(self.join.state)
