from .sharded_agg import (  # noqa: F401
    SHARD_AXIS, ShardedHashAgg, build_sharded_q5_step, make_mesh,
    shard_map_compat, shuffle_chunk_local,
)
from .sharded_join import (  # noqa: F401
    ShardedHashJoin, build_sharded_q7_step,
)
from .executors import (  # noqa: F401
    ShardedHashAggExecutor, ShardedHashJoinExecutor,
)
from .fused import (  # noqa: F401
    ShardedFusedAgg, ShardedFusedJoin, load_shard_states,
    reshard_join_payloads,
)
