from .sharded_agg import (  # noqa: F401
    SHARD_AXIS, ShardedHashAgg, build_sharded_q5_step, make_mesh,
    shuffle_chunk_local,
)
