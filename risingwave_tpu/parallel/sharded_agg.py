"""Sharded (multi-chip) grouped aggregation: vnode shuffle + per-shard upsert.

This is the TPU-native replacement for the reference's hash-dispatch exchange
between parallel HashAgg actors (reference: hash dispatcher
src/stream/src/executor/dispatch.rs:532, vnode partitioning
docs/consistent-hash.md): instead of serialize→gRPC→deserialize per edge, the
shuffle is a ``lax.all_to_all`` over the mesh's ICI *inside the jitted step*,
fused with the grouped-aggregation update (SURVEY.md §2.9, §5 "Distributed
communication backend").

Layout: every state array carries a leading shard axis sharded over the mesh
(``P('shard')``); inside ``shard_map`` each device sees its own [cap] slice
and runs the same pure AggCore code as the single-chip executor.

Routing: row → vnode (hash of group key) → owner shard (contiguous ranges).
Each local chunk of capacity C builds an [n, C] send buffer (a local chunk
has at most C rows for any one target, so per-target capacity C is always
sufficient — no ragged sizes, no recompiles), all-to-alls it, and upserts the
received [n*C] rows.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.chunk import Column, StreamChunk
from ..common.hashing import vnode_of, vnode_to_shard
from ..expr.agg import AggCall, count_star
from ..ops.grouped_agg import AggCore, AggState

SHARD_AXIS = "shard"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across JAX versions: the top-level API (with
    ``check_vma``) when present, else ``jax.experimental.shard_map``
    (whose equivalent knob is ``check_rep``). Replication checking is
    off either way — the hash shuffles communicate via explicit
    ``all_to_all``/``psum``, which the checker cannot always follow."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: int) -> Mesh:
    devs = jax.devices()
    if len(devs) < n_devices:
        from ..common.config import MeshUnavailableError
        raise MeshUnavailableError(
            f"mesh needs {n_devices} devices, process has {len(devs)} "
            f"(on CPU force a virtual mesh with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return Mesh(np.array(devs[:n_devices]), (SHARD_AXIS,))


def chunk_sendbuf(chunk: StreamChunk, n_shards: int,
                  key_idx: Sequence[int]) -> StreamChunk:
    """Per-target send buffers for the hash shuffle: a StreamChunk whose
    leaves are [n_shards, C] — row block ``t`` holds this shard's rows
    owned by shard ``t`` (vnode hash of the key columns), front-packed.
    Pure elementwise/sort work, no collectives — so the multi-job group
    epoch can ``vmap`` it over a leading job axis and hand-batch the ONE
    all_to_all itself (ops/fused_sharded.shuffle_group_chunks)."""
    C = chunk.capacity
    key_cols = [chunk.columns[i] for i in key_idx]
    vn = vnode_of(key_cols)
    tgt = vnode_to_shard(vn, n_shards)
    # invisible rows route to a virtual bucket n (dropped)
    tgt_eff = jnp.where(chunk.vis, tgt, n_shards)
    order = jnp.argsort(tgt_eff)                   # stable
    sorted_tgt = tgt_eff[order]
    bucket_start = jnp.searchsorted(sorted_tgt, jnp.arange(n_shards))
    rank = jnp.arange(C) - bucket_start[jnp.clip(sorted_tgt, 0, n_shards - 1)]
    dest_row = jnp.where(sorted_tgt < n_shards, rank, C)  # drop invisible

    def to_sendbuf(arr):
        src = arr[order]
        buf = jnp.zeros((n_shards, C), arr.dtype)
        return buf.at[jnp.clip(sorted_tgt, 0, n_shards - 1), dest_row].set(
            src, mode="drop")

    return StreamChunk(
        to_sendbuf(chunk.ops), to_sendbuf(chunk.vis),
        tuple(Column(to_sendbuf(c.data), to_sendbuf(c.mask))
              for c in chunk.columns))


def shuffle_chunk_local(chunk: StreamChunk, n_shards: int,
                        key_idx: Sequence[int]) -> StreamChunk:
    """Inside-shard_map hash shuffle: returns the [n*C] chunk of rows this
    shard owns after the all-to-all. Pure; requires SHARD_AXIS binding."""
    C = chunk.capacity
    send = chunk_sendbuf(chunk, n_shards, key_idx)

    def a2a(x):
        return jax.lax.all_to_all(x, SHARD_AXIS, split_axis=0, concat_axis=0,
                                  tiled=True).reshape(n_shards * C)

    return jax.tree_util.tree_map(a2a, send)


class ShardedHashAgg:
    """Data-parallel grouped agg over a device mesh.

    State arrays have shape [n_shards, ...] sharded on the leading axis; the
    jitted ``step`` does shuffle + upsert in one XLA program per chunk batch
    (one local chunk per shard per step)."""

    def __init__(self, mesh: Mesh, key_types, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall], table_capacity: int = 1 << 14,
                 out_capacity: int = 1024):
        self.mesh = mesh
        self.n = mesh.devices.size
        self.core = AggCore(key_types, group_keys, agg_calls, table_capacity,
                            out_capacity)
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))

        def local_init():
            return self.core.init_state()

        # replicate init per shard by vmapping over a dummy leading axis
        init = jax.vmap(lambda _: local_init())(jnp.arange(self.n))
        self.state = jax.device_put(
            init, jax.tree_util.tree_map(lambda _: self._sharding, init))

        core = self.core
        n = self.n
        gk = tuple(group_keys)

        def local_step(state: AggState, chunk: StreamChunk):
            # shard_map keeps the sharded leading axis as size-1; work on the
            # squeezed local view and restore the axis on the way out
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            chunk = jax.tree_util.tree_map(lambda x: x[0], chunk)
            owned = shuffle_chunk_local(chunk, n, gk)
            new_state = core.apply_chunk(state, owned)
            rows_in = jax.lax.psum(jnp.sum(chunk.vis.astype(jnp.int32)),
                                   SHARD_AXIS)
            new_state = jax.tree_util.tree_map(lambda x: x[None], new_state)
            return new_state, rows_in

        self._step = jax.jit(
            shard_map_compat(
                local_step, mesh=mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=(P(SHARD_AXIS), P()),
            )
        )

    def step(self, chunk_batch: StreamChunk):
        """``chunk_batch``: arrays with leading [n_shards] axis (one local
        chunk per shard)."""
        self.state, rows = self._step(self.state, chunk_batch)
        return rows

    # -- host-side helpers ----------------------------------------------------

    def batch_chunks(self, chunks: Sequence[StreamChunk]) -> StreamChunk:
        """Stack n single-shard chunks into one sharded batch."""
        assert len(chunks) == self.n
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *chunks)
        return jax.device_put(
            stacked, jax.tree_util.tree_map(lambda _: self._sharding, stacked))

    def merged_group_values(self):
        """Gather all shards' live groups to host: {key_tuple: (lanes...)}.

        Test/debug surface — production egress goes through flush chunks."""
        st = jax.device_get(self.state)
        out = {}
        for s in range(self.n):
            occ = st.table.occupied[s]
            live = st.lanes[0][s] > 0
            for slot in np.nonzero(occ & live)[0]:
                key = tuple(
                    st.table.key_data[c][s][slot].item()
                    if st.table.key_mask[c][s][slot] else None
                    for c in range(len(st.table.key_data))
                )
                out[key] = tuple(
                    st.lanes[j][s][slot].item() for j in range(len(st.lanes))
                )
        return out


def build_sharded_q5_step(n_devices: int) -> None:
    """Driver dry-run: full sharded NEXmark q5-core step over an n-device
    mesh — window projection, vnode all-to-all shuffle, grouped count — one
    real step executed on tiny shapes."""
    from ..common.types import INT64, TIMESTAMP
    from ..connector import NexmarkConfig, NexmarkGenerator
    from ..expr import Literal, call, col

    mesh = make_mesh(n_devices)
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=64))
    window = Literal(10_000_000, INT64)
    w_expr = call("tumble_start", col(5, TIMESTAMP), window)
    a_expr = col(0, INT64)

    agg = ShardedHashAgg(
        mesh, [INT64, INT64], [0, 1], [count_star()],
        table_capacity=1 << 10, out_capacity=64,
    )
    raw = [gen.next_bid_chunk() for _ in range(n_devices)]
    projected = [c.with_columns((w_expr.eval(c), a_expr.eval(c))) for c in raw]
    batch = agg.batch_chunks(projected)
    rows = agg.step(batch)
    jax.block_until_ready(rows)
    assert int(rows) == n_devices * 64, int(rows)

    # cross-check against host groupby
    from ..common.chunk import chunk_to_rows
    from ..common.types import Schema, Field
    sch = Schema.of(("w", INT64), ("a", INT64))
    expected: dict = {}
    for c in projected:
        for r in chunk_to_rows(c.project([0, 1]), sch):
            expected[r] = expected.get(r, 0) + 1
    got = {k: v[0] for k, v in agg.merged_group_values().items()}
    assert got == expected, f"sharded counts mismatch: {len(got)} vs {len(expected)}"
    print(f"dryrun_multichip({n_devices}): q5-core sharded step OK, "
          f"{len(got)} groups")
