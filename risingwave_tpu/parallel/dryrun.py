"""Multi-chip dry-run entry: the one function the driver's
``__graft_entry__.dryrun_multichip`` subprocess executes.

Asserts the virtual CPU mesh is actually present (the round-1 failure was
silently initializing the single real chip), then jits and runs ONE real
step of every sharded operator the framework ships — currently the
vnode-shuffled grouped agg (q5 core) and, once present, the sharded hash
join (q7 core) — on tiny shapes, with host cross-checks.
"""

from __future__ import annotations


def run_dryrun(n_devices: int) -> None:
    import jax

    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"dryrun needs {n_devices} devices, found {len(devs)} "
            f"({devs[0].platform if devs else 'none'}); JAX_PLATFORMS=cpu + "
            f"--xla_force_host_platform_device_count must be set before jax "
            f"import")
    if devs[0].platform != "cpu":
        raise RuntimeError(
            f"dryrun must run on the virtual CPU mesh, got platform "
            f"{devs[0].platform!r} — refusing to grab real hardware")

    from .sharded_agg import build_sharded_q5_step
    build_sharded_q5_step(n_devices)

    try:
        from .sharded_join import build_sharded_q7_step
    except ImportError:
        # self-describing skip (ADVICE r2): the artifact must say what ran
        print("dryrun_multichip: sharded join SKIPPED (not implemented)")
    else:
        build_sharded_q7_step(n_devices)

    build_sharded_fused_epochs(n_devices)

    print(f"dryrun_multichip({n_devices}): all sharded steps OK")


def build_sharded_fused_epochs(n_devices: int) -> None:
    """One real mesh-sharded FUSED epoch of each shape (the PR-7 fast
    path — ops/fused_sharded.py): q5 agg and q7 interval-join epochs run
    as ONE dispatch across the mesh, cross-checked against the solo
    fused epoch over the same (start, key, k)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..common.types import INT64, TIMESTAMP, Field, Schema
    from ..connector import NexmarkConfig
    from ..connector.nexmark import DeviceBidGenerator
    from ..expr import Literal, call, col
    from ..expr.agg import count_star
    from ..ops.fused_epoch import fused_source_agg_epoch
    from ..ops.grouped_agg import AggCore
    from ..ops.interval_join import IntervalJoinCore
    from .fused import ShardedFusedAgg, ShardedFusedJoin
    from .sharded_agg import make_mesh

    cap, k = 64, n_devices
    mesh = make_mesh(n_devices)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(1_000_000, INT64)),
             col(0, INT64), col(2, INT64)]
    key = jax.random.PRNGKey(5)

    core = AggCore([INT64, INT64], [0, 1], [count_star()], 1 << 10, 64)
    sf = ShardedFusedAgg(mesh, core, gen.chunk_fn(), exprs, cap)
    sf.run_epoch(0, key, k)
    sf.flush()
    solo = fused_source_agg_epoch(gen.chunk_fn(), exprs, core, cap,
                                  donate=False)
    st = solo(core.init_state(), jnp.int64(0), key, k)
    got = {kk: v[0] for kk, v in sf.merged_group_values().items()}
    occ = np.asarray(st.table.occupied) & (np.asarray(st.lanes[0]) > 0)
    kd = [np.asarray(x) for x in st.table.key_data]
    km = [np.asarray(x) for x in st.table.key_mask]
    cnt = np.asarray(st.lanes[0])
    want = {tuple(kd[c][s].item() if km[c][s] else None
                  for c in range(len(kd))): cnt[s].item()
            for s in np.nonzero(occ)[0]}
    assert got == want, (
        f"sharded fused agg mismatch: {len(got)} vs {len(want)} groups")
    print(f"dryrun_multichip({n_devices}): q5 sharded FUSED epoch OK, "
          f"{len(got)} groups, 1 dispatch")

    probe_schema = Schema((Field("window_start", TIMESTAMP),
                           Field("auction", INT64), Field("price", INT64)))
    join_exprs = [call("tumble_start", col(5, TIMESTAMP),
                       Literal(5_000, INT64)),
                  col(0, INT64), col(2, INT64)]
    jcore = IntervalJoinCore(probe_schema, ts_col=0, val_col=2,
                             window_us=5_000, n_buckets=256, lane_width=64)
    sj = ShardedFusedJoin(mesh, jcore, gen.chunk_fn(), join_exprs, cap)
    sj.run_epoch(0, key, k)
    probe, churn = sj.flush(out_capacity=128)
    jax.block_until_ready(sj.stacked.cur_max)
    print(f"dryrun_multichip({n_devices}): q7 sharded FUSED epoch OK, "
          f"{len(probe)} probe + {len(churn)} churn windows, 1 dispatch")
