"""Multi-chip dry-run entry: the one function the driver's
``__graft_entry__.dryrun_multichip`` subprocess executes.

Asserts the virtual CPU mesh is actually present (the round-1 failure was
silently initializing the single real chip), then jits and runs ONE real
step of every sharded operator the framework ships — currently the
vnode-shuffled grouped agg (q5 core) and, once present, the sharded hash
join (q7 core) — on tiny shapes, with host cross-checks.
"""

from __future__ import annotations


def run_dryrun(n_devices: int) -> None:
    import jax

    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"dryrun needs {n_devices} devices, found {len(devs)} "
            f"({devs[0].platform if devs else 'none'}); JAX_PLATFORMS=cpu + "
            f"--xla_force_host_platform_device_count must be set before jax "
            f"import")
    if devs[0].platform != "cpu":
        raise RuntimeError(
            f"dryrun must run on the virtual CPU mesh, got platform "
            f"{devs[0].platform!r} — refusing to grab real hardware")

    from .sharded_agg import build_sharded_q5_step
    build_sharded_q5_step(n_devices)

    try:
        from .sharded_join import build_sharded_q7_step
    except ImportError:
        # self-describing skip (ADVICE r2): the artifact must say what ran
        print("dryrun_multichip: sharded join SKIPPED (not implemented)")
    else:
        build_sharded_q7_step(n_devices)

    print(f"dryrun_multichip({n_devices}): all sharded steps OK")
