"""Worker RPC wire format: length-prefixed JSON frames + message codecs.

Counterpart of the reference's gRPC compute-node boundary
(reference: src/compute/src/rpc/service/stream_service.rs:46-233 control
plane, exchange_service.rs:74-133 data plane, src/rpc_client/src/
stream_client.rs pools). TPU-first deviation: instead of a gRPC stack,
one multiplexed asyncio socket per worker carries BOTH control frames and
permit-metered data frames — the host side of the runtime is thin because
all heavy data parallelism rides XLA collectives inside a process, and the
cross-process edges move boundary streams (DML deltas, changelogs), not
shuffles.

Rows cross processes in the process-independent value encoding
(common/row.py: strings as bytes, never dictionary ids), so each process
keeps its own string dictionary — the same property the durable tier
relies on.

Frame layout: 4-byte little-endian length, then UTF-8 JSON. Binary row
payloads are base64 fields inside the JSON — simple, debuggable, and off
the hot path (single-process pipelines never touch this module).

Control-plane observability: a ``{"type": "stats"}`` request makes the
worker answer with its full monitor snapshot — per-job executor trees,
per-executor counters, exchange-channel queue depths, state bytes, and a
drain of its tracing-span ring (reference: MonitorService.stack_trace,
src/compute/src/rpc/service/monitor_service.rs:46). The session federates
those snapshots into ``Session.metrics()`` / the dashboard so a
worker-hosted job is as visible as a local one. Spans cross as
``Span.to_dict()`` dicts (``common/tracing.py`` is the codec: the worker
ships ``to_dict``, the session re-ingests via ``TraceRecorder.ingest``,
which tolerates unknown keys from a newer worker).
"""

from __future__ import annotations

import asyncio
import base64
import json
import struct
from typing import Optional

from ..common.chunk import StreamChunk, chunk_to_rows, make_chunk
from ..common.row import decode_value_row, encode_value_row
from ..common.types import Schema
from ..stream.message import Barrier, Message, Mutation, MutationKind, Watermark

_LEN = struct.Struct("<I")
MAX_FRAME = 256 << 20


def pack_frame(obj: dict) -> bytes:
    body = json.dumps(obj).encode()
    if len(body) > MAX_FRAME:
        raise ValueError(f"oversized frame: {len(body)} bytes")
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    """Read one frame; None on clean EOF (peer closed)."""
    try:
        head = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ValueError(f"oversized frame: {n} bytes")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return json.loads(body)


async def write_frame(writer: asyncio.StreamWriter, obj: dict,
                      lock: Optional[asyncio.Lock] = None,
                      link: Optional[str] = None,
                      meta: bool = False) -> None:
    """Write one frame; ``lock`` serializes concurrent writer tasks
    (barrier collectors, permit acks) on a shared socket. ``link`` names
    the directed edge for the network fault plane (rpc/faults.py): every
    named send routes through the plane's per-link FaultyTransport, so a
    seeded ChaosSchedule can drop/delay/duplicate/partition this frame
    deterministically. Unnamed sends bypass injection (local tooling)."""
    buf = pack_frame(obj)

    async def emit(b: bytes) -> None:
        if lock is not None:
            async with lock:
                writer.write(b)
                await writer.drain()
        else:
            writer.write(b)
            await writer.drain()

    if link is not None:
        from .faults import FaultyTransport, plane
        if plane().installed:
            await FaultyTransport(link).send(obj, buf, emit, meta=meta)
            return
    await emit(buf)


def read_frame_sync(sock) -> Optional[dict]:
    """Blocking read of one frame from a plain socket; None on clean EOF.
    The compactor control conversation (meta → compactor) is strict
    request/reply with no multiplexed data plane, so its meta-side
    client stays synchronous — no event-loop integration needed
    (reference: the compactor's one gRPC stream,
    src/storage/compactor/src/server.rs:57)."""
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            return None
        buf += chunk
    (n,) = _LEN.unpack(buf)
    if n > MAX_FRAME:
        raise ValueError(f"oversized frame: {n} bytes")
    body = b""
    while len(body) < n:
        chunk = sock.recv(min(1 << 20, n - len(body)))
        if not chunk:
            return None
        body += chunk
    return json.loads(body)


def write_frame_sync(sock, obj: dict, link: Optional[str] = None) -> None:
    buf = pack_frame(obj)
    if link is not None:
        from .faults import FaultyTransport, plane
        if plane().installed:
            FaultyTransport(link).send_sync(obj, buf, sock.sendall)
            return
    sock.sendall(buf)


# -- message codecs -----------------------------------------------------------

def chunk_to_wire(chunk: StreamChunk, schema: Schema) -> dict:
    types = [f.type for f in schema]
    rows = chunk_to_rows(chunk, schema, with_ops=True, physical=True)
    return {
        "t": "chunk",
        "ops": [op for op, _ in rows],
        "rows": [base64.b64encode(encode_value_row(r, types)).decode()
                 for _, r in rows],
    }


def wire_to_chunk(d: dict, schema: Schema, capacity: int) -> StreamChunk:
    types = [f.type for f in schema]
    rows = [decode_value_row(base64.b64decode(r), types) for r in d["rows"]]
    return make_chunk(schema, rows, ops=d["ops"],
                      capacity=max(capacity, len(rows), 1), physical=True)


def message_to_wire(msg: Message, schema: Schema) -> dict:
    if isinstance(msg, StreamChunk):
        return chunk_to_wire(msg, schema)
    if isinstance(msg, Barrier):
        out = {"t": "barrier", "epoch": msg.epoch.curr,
               "checkpoint": msg.checkpoint}
        if msg.mutation is not None:
            out["mutation"] = msg.mutation.kind.value
            if isinstance(msg.mutation.payload, str):
                out["mutation_payload"] = msg.mutation.payload
        return out
    if isinstance(msg, Watermark):
        return {"t": "watermark", "col": msg.col_idx, "value": msg.value}
    raise TypeError(f"cannot serialize message {type(msg).__name__}")


def message_from_wire(d: dict, schema: Schema,
                      capacity: int = 1024) -> Message:
    t = d["t"]
    if t == "chunk":
        return wire_to_chunk(d, schema, capacity)
    if t == "barrier":
        mut = None
        if "mutation" in d:
            mut = Mutation(MutationKind(d["mutation"]),
                           d.get("mutation_payload"))
        return Barrier.new(d["epoch"], checkpoint=d["checkpoint"],
                           mutation=mut)
    if t == "watermark":
        return Watermark(d["col"], d["value"])
    raise TypeError(f"unknown wire message {t!r}")
