"""Worker↔worker remote-exchange wire layer.

Counterpart of the reference's ExchangeService data plane
(reference: src/compute/src/rpc/service/exchange_service.rs:74-133 served
by every compute node; src/rpc_client/src/compute_client.rs opening
streams to peers; exchange/permit.rs:35-107 credit flow). Each worker
process's ONE listening socket serves both the session's control
connection and any number of PEER connections; a peer connection opens
with an ``exg_hello`` frame and then carries only exchange frames:

    producer → consumer   {"type": "exg_data", "chan": C, "msg": <wire>}
    consumer → producer   {"type": "exg_ack",  "chan": C}

Credit flow mirrors ``PermitChannel`` end-to-end across the process
boundary: StreamChunk frames consume a permit on the PRODUCER before the
bytes are written and the permit returns only when the consumer's
executor TAKES the chunk (consumption-acked, not receipt-acked);
barriers and watermarks always pass so the control stream can never
deadlock behind data — the invariant the two-phase checkpoint depends
on. One client connection per (host, port) pair multiplexes every edge
between the two processes, like the reference's pooled compute clients.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Dict, Optional, Tuple

from .wire import MAX_FRAME, read_frame

_LEN = struct.Struct("<I")


class PeerLost(ConnectionError):
    """The remote end of an exchange edge is gone (process death, socket
    reset). Distinguished from executor logic errors so barrier
    collection can classify it as a KILL — the heartbeat-TTL scoped
    recovery path — rather than a poisoned job."""


class EdgeStats:
    """Per-exchange-edge counters, one side of one edge. Surfaced through
    worker stats frames into ``Session.metrics()["exchange"]``,
    Prometheus (``rw_exchange_stat``), and the dashboard."""

    __slots__ = ("edge", "direction", "peer_worker", "chunks", "bytes",
                 "permits_waited", "barriers")

    def __init__(self, edge: str, direction: str, peer_worker: int):
        self.edge = edge              # "job:f<u>a<i>->f<d>a<j>"
        self.direction = direction    # "out" | "in"
        self.peer_worker = peer_worker
        self.chunks = 0
        self.bytes = 0
        self.permits_waited = 0
        self.barriers = 0

    def snapshot(self, backlog: int = 0) -> dict:
        return {"edge": self.edge, "dir": self.direction,
                "peer_worker": self.peer_worker, "chunks": self.chunks,
                "bytes": self.bytes, "permits_waited": self.permits_waited,
                "barriers": self.barriers, "backlog": backlog}


class ExchangePeerClient:
    """Producer-side connection to ONE peer worker's exchange server.
    Owns the socket, the per-channel permit semaphores, and the ack read
    loop. All edges from this process to that peer share the connection
    (per-channel credit keeps them independent)."""

    def __init__(self, host: str, port: int, from_worker: int):
        self.host = host
        self.port = port
        self.from_worker = from_worker
        self.broken = False
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock = asyncio.Lock()
        self._sems: Dict[int, asyncio.Semaphore] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._connect_lock = asyncio.Lock()

    def register(self, chan: int, permits: int) -> None:
        self._sems[chan] = asyncio.Semaphore(permits)

    def unregister(self, chan: int) -> None:
        self._sems.pop(chan, None)

    async def _ensure_connected(self) -> None:
        async with self._connect_lock:
            if self._writer is not None or self.broken:
                return
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port)
            except OSError as e:
                self._mark_broken()
                raise PeerLost(
                    f"exchange peer {self.host}:{self.port}: {e}") from None
            self._writer = writer
            writer.write(self._pack({"type": "exg_hello",
                                     "worker": self.from_worker}))
            await writer.drain()
            self._reader_task = asyncio.ensure_future(
                self._ack_loop(reader))

    @staticmethod
    def _pack(obj: dict) -> bytes:
        body = json.dumps(obj).encode()
        if len(body) > MAX_FRAME:
            raise ValueError(f"oversized exchange frame: {len(body)} bytes")
        return _LEN.pack(len(body)) + body

    async def _ack_loop(self, reader) -> None:
        while True:
            frame = await read_frame(reader)
            if frame is None:
                self._mark_broken()
                return
            if frame.get("type") == "exg_ack":
                sem = self._sems.get(frame["chan"])
                if sem is not None:
                    sem.release()

    def _mark_broken(self) -> None:
        self.broken = True
        for sem in self._sems.values():
            sem.release()        # unblock senders; send() raises PeerLost

    async def send(self, chan: int, wire_msg: dict, is_data: bool,
                   stats: Optional[EdgeStats] = None) -> int:
        """Ship one message on an edge; returns bytes written. Data
        consumes a permit (blocking the SENDING actor when the consumer's
        credit is exhausted — end-to-end backpressure); control frames
        always pass."""
        await self._ensure_connected()
        if is_data:
            sem = self._sems.get(chan)
            if sem is not None:
                if stats is not None and sem.locked():
                    stats.permits_waited += 1
                await sem.acquire()
        if self.broken or self._writer is None:
            raise PeerLost(
                f"exchange peer {self.host}:{self.port} is down")
        buf = self._pack({"type": "exg_data", "chan": chan,
                          "msg": wire_msg})
        try:
            async with self._wlock:
                self._writer.write(buf)
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            self._mark_broken()
            raise PeerLost(
                f"exchange peer {self.host}:{self.port}: {e}") from None
        return len(buf)

    async def aclose(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._reader_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 - already dying
                pass
            self._writer = None


class PeerClientPool:
    """One ``ExchangePeerClient`` per (host, port) target, shared by every
    edge this process produces toward that peer (reference: the pooled
    compute clients of rpc_client/src/lib.rs). A broken client is
    replaced on next lookup so recovery's re-created edges (same worker,
    NEW port after respawn) never reuse a dead socket."""

    def __init__(self, from_worker: int):
        self.from_worker = from_worker
        self._clients: Dict[Tuple[str, int], ExchangePeerClient] = {}

    def get(self, host: str, port: int) -> ExchangePeerClient:
        key = (host, port)
        client = self._clients.get(key)
        if client is None or client.broken:
            client = ExchangePeerClient(host, port, self.from_worker)
            self._clients[key] = client
        return client

    async def aclose(self) -> None:
        for client in self._clients.values():
            await client.aclose()
        self._clients.clear()
