"""Worker↔worker remote-exchange wire layer.

Counterpart of the reference's ExchangeService data plane
(reference: src/compute/src/rpc/service/exchange_service.rs:74-133 served
by every compute node; src/rpc_client/src/compute_client.rs opening
streams to peers; exchange/permit.rs:35-107 credit flow). Each worker
process's ONE listening socket serves both the session's control
connection and any number of PEER connections; a peer connection opens
with an ``exg_hello`` frame and then carries only exchange frames:

    producer → consumer   {"type": "exg_data", "chan": C, "seq": N,
                           "msg": <wire>}
    consumer → producer   {"type": "exg_ack",  "chan": C, "seq": K}
    either → either       {"type": "exg_ping"/"exg_pong", "seq": J}

Credit flow mirrors ``PermitChannel`` end-to-end across the process
boundary: StreamChunk frames consume a permit on the PRODUCER before the
bytes are written and the permit returns only when the consumer's
executor TAKES the chunk (consumption-acked, not receipt-acked);
barriers and watermarks always pass so the control stream can never
deadlock behind data — the invariant the two-phase checkpoint depends
on. One client connection per (host, port) pair multiplexes every edge
between the two processes, like the reference's pooled compute clients.

Hardening (ISSUE 9, the network fault plane forced all three):

* every frame on an edge carries a per-channel SEQUENCE NUMBER; the
  consuming ``ExchangeInput`` dedups duplicates (delivered at-least-once
  by a faulty network becomes exactly-once at the executor) and
  re-orders delayed frames back into send order, and the producer dedups
  duplicated acks so credit accounting cannot inflate;
* an idle-link KEEPALIVE (exg_ping/exg_pong) detects a half-open peer
  socket — a peer that died without a FIN, or a severed link — and marks
  the client broken BEFORE the next epoch's send would burn a permit on
  a doomed frame; ``PeerClientPool`` evicts broken clients on lookup;
* every send routes through the fault plane's per-link transport
  (rpc/faults.py), so a seeded ChaosSchedule can partition, delay, drop
  or duplicate exchange traffic deterministically.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Tuple

from .wire import read_frame


class PeerLost(ConnectionError):
    """The remote end of an exchange edge is gone (process death, socket
    reset, keepalive timeout). Distinguished from executor logic errors
    so barrier collection can classify it as a KILL — the heartbeat-TTL
    scoped recovery path — rather than a poisoned job."""


class AckWatermark:
    """Exactly-once accounting for seq-carrying credit acks, shared by
    the producer-side ack loops (ExchangePeerClient here, RemoteWorker
    in frontend/remote.py). Each DISTINCT ack seq releases exactly one
    permit: duplicates are refused (credit must not inflate) and
    REORDERED acks — a chaos-delayed sibling overtaking — are accepted
    exactly once via a small out-of-order set compacted into the
    watermark (a plain ``seq < expected`` check misreads a late genuine
    ack as a duplicate and leaks its permit forever)."""

    __slots__ = ("next", "_seen")

    def __init__(self) -> None:
        self.next = 0
        self._seen: set = set()

    def accept(self, seq: Optional[int]) -> bool:
        """True iff this ack is a first delivery (release a permit)."""
        if seq is None:
            return True                  # legacy peer: no seq discipline
        if seq < self.next or seq in self._seen:
            return False
        self._seen.add(seq)
        while self.next in self._seen:
            self._seen.discard(self.next)
            self.next += 1
        return True


class SeqReorderBuffer:
    """Consumer-side dedup + re-sequencing for seq-carrying frames,
    shared by the exchange input (stream/remote_exchange.py) and the
    worker's session data channels (worker/host.py). ``feed`` returns
    the frames now deliverable IN SEND ORDER (possibly none: held for a
    gap; possibly several: a gap just closed); duplicates are dropped."""

    __slots__ = ("next_seq", "_held", "dup_frames", "reordered")

    def __init__(self) -> None:
        self.next_seq = 0
        self._held: Dict[int, object] = {}
        self.dup_frames = 0
        self.reordered = 0

    def feed(self, seq: Optional[int], payload) -> list:
        if seq is None:                  # legacy peer: pass through
            return [payload]
        if seq < self.next_seq or seq in self._held:
            self.dup_frames += 1
            return []
        if seq > self.next_seq:
            self.reordered += 1
            self._held[seq] = payload
            return []
        out = [payload]
        self.next_seq += 1
        while self.next_seq in self._held:
            out.append(self._held.pop(self.next_seq))
            self.next_seq += 1
        return out


class EdgeStats:
    """Per-exchange-edge counters, one side of one edge. Surfaced through
    worker stats frames into ``Session.metrics()["exchange"]``,
    Prometheus (``rw_exchange_stat``), and the dashboard."""

    __slots__ = ("edge", "direction", "peer_worker", "chunks", "bytes",
                 "permits_waited", "barriers", "dup_frames", "reordered",
                 "last_barrier_epoch", "epoch_regressions")

    def __init__(self, edge: str, direction: str, peer_worker: int):
        self.edge = edge              # "job:f<u>a<i>->f<d>a<j>"
        self.direction = direction    # "out" | "in"
        self.peer_worker = peer_worker
        self.chunks = 0
        self.bytes = 0
        self.permits_waited = 0
        self.barriers = 0
        # duplicate frames dropped by seq-dedup / frames that arrived
        # out of order and were re-sequenced (network fault plane)
        self.dup_frames = 0
        self.reordered = 0
        # per-edge barrier-epoch monotonicity (the ConsistencyAuditor
        # asserts epoch_regressions == 0 after every chaos run)
        self.last_barrier_epoch = 0
        self.epoch_regressions = 0

    def saw_barrier(self, epoch: int) -> None:
        self.barriers += 1
        if epoch <= self.last_barrier_epoch:
            self.epoch_regressions += 1
        else:
            self.last_barrier_epoch = epoch

    def snapshot(self, backlog: int = 0) -> dict:
        return {"edge": self.edge, "dir": self.direction,
                "peer_worker": self.peer_worker, "chunks": self.chunks,
                "bytes": self.bytes, "permits_waited": self.permits_waited,
                "barriers": self.barriers, "backlog": backlog,
                "dup_frames": self.dup_frames, "reordered": self.reordered,
                "last_barrier_epoch": self.last_barrier_epoch,
                "epoch_regressions": self.epoch_regressions}


class ExchangePeerClient:
    """Producer-side connection to ONE peer worker's exchange server.
    Owns the socket, the per-channel permit semaphores, the keepalive
    prober, and the ack read loop. All edges from this process to that
    peer share the connection (per-channel credit keeps them
    independent)."""

    def __init__(self, host: str, port: int, from_worker: int,
                 peer_worker: Optional[int] = None,
                 keepalive_s: float = 10.0,
                 keepalive_timeout_s: float = 5.0):
        self.host = host
        self.port = port
        self.from_worker = from_worker
        self.peer_worker = peer_worker
        # fault-plane link name for every frame this client sends
        self.link = (f"w{from_worker}->w{peer_worker}"
                     if peer_worker is not None
                     else f"w{from_worker}->{host}:{port}")
        self.keepalive_s = keepalive_s
        self.keepalive_timeout_s = keepalive_timeout_s
        self.broken = False
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock = asyncio.Lock()
        self._sems: Dict[int, asyncio.Semaphore] = {}
        self._seqs: Dict[int, int] = {}       # chan -> next data seq
        self._acks: Dict[int, AckWatermark] = {}
        self.dup_acks = 0
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._pong = asyncio.Event()
        self._last_rx = time.monotonic()
        self._connect_lock = asyncio.Lock()

    def register(self, chan: int, permits: int) -> None:
        self._sems[chan] = asyncio.Semaphore(permits)
        self._seqs[chan] = 0
        self._acks[chan] = AckWatermark()

    def unregister(self, chan: int) -> None:
        self._sems.pop(chan, None)
        self._seqs.pop(chan, None)
        self._acks.pop(chan, None)

    async def _ensure_connected(self) -> None:
        async with self._connect_lock:
            if self._writer is not None or self.broken:
                return
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port)
            except OSError as e:
                self._mark_broken()
                raise PeerLost(
                    f"exchange peer {self.host}:{self.port}: {e}") from None
            self._writer = writer
            writer.write(self._pack({"type": "exg_hello",
                                     "worker": self.from_worker}))
            await writer.drain()
            self._last_rx = time.monotonic()
            self._reader_task = asyncio.ensure_future(
                self._ack_loop(reader))
            if self.keepalive_s and self.keepalive_s > 0:
                self._keepalive_task = asyncio.ensure_future(
                    self._keepalive_loop())

    @staticmethod
    def _pack(obj: dict) -> bytes:
        from .wire import pack_frame
        return pack_frame(obj)

    async def _ack_loop(self, reader) -> None:
        while True:
            frame = await read_frame(reader)
            if frame is None:
                self._mark_broken()
                return
            self._last_rx = time.monotonic()
            t = frame.get("type")
            if t == "exg_ack":
                chan = frame["chan"]
                wm = self._acks.get(chan)
                if wm is not None and not wm.accept(frame.get("seq")):
                    # duplicated ack (network fault): releasing a
                    # permit for it would inflate the edge's credit
                    self.dup_acks += 1
                    continue
                sem = self._sems.get(chan)
                if sem is not None:
                    sem.release()
            elif t == "exg_pong":
                self._pong.set()

    async def _keepalive_loop(self) -> None:
        """Idle-link prober: a peer socket that died without a FIN (or a
        severed link) otherwise looks healthy until the next send wedges
        permit accounting. A ping answered by nothing within the timeout
        marks this client broken — senders fail fast with PeerLost and
        the pool evicts the client on next lookup."""
        interval = self.keepalive_s
        missed = 0
        while not self.broken:
            await asyncio.sleep(interval)
            if self.broken or self._writer is None:
                return
            if time.monotonic() - self._last_rx < interval:
                missed = 0
                continue              # link demonstrably alive
            self._pong.clear()
            try:
                await self._raw_send({"type": "exg_ping", "seq": 0},
                                     meta=True)
            except (PeerLost, ConnectionError, OSError):
                self._mark_broken()
                return
            try:
                await asyncio.wait_for(self._pong.wait(),
                                       self.keepalive_timeout_s)
                missed = 0
            except asyncio.TimeoutError:
                # TWO consecutive missed pongs before declaring the link
                # dead: a peer whose event loop is pinned by a long
                # compute-bound epoch legitimately answers late, and a
                # single-miss policy false-kills healthy graphs under
                # load (found by the netsplit harness)
                missed += 1
                if missed >= 2:
                    self._mark_broken()
                    return

    def _mark_broken(self) -> None:
        self.broken = True
        for sem in self._sems.values():
            sem.release()        # unblock senders; send() raises PeerLost

    async def _raw_send(self, obj: dict, meta: bool = False) -> int:
        """Pack + write one frame through the fault plane. Raises on a
        dead socket; returns bytes written (0 when the plane ate it)."""
        if self.broken or self._writer is None:
            raise PeerLost(
                f"exchange peer {self.host}:{self.port} is down")
        buf = self._pack(obj)

        async def emit(b: bytes) -> None:
            async with self._wlock:
                self._writer.write(b)
                await self._writer.drain()

        from .faults import FaultyTransport, plane
        try:
            if plane().installed:
                sent = await FaultyTransport(self.link).send(
                    obj, buf, emit, meta=meta)
                return len(buf) if sent else 0
            await emit(buf)
        except (ConnectionError, OSError) as e:
            self._mark_broken()
            raise PeerLost(
                f"exchange peer {self.host}:{self.port}: {e}") from None
        return len(buf)

    async def send(self, chan: int, wire_msg: dict, is_data: bool,
                   stats: Optional[EdgeStats] = None) -> int:
        """Ship one message on an edge; returns bytes written. Data
        consumes a permit (blocking the SENDING actor when the consumer's
        credit is exhausted — end-to-end backpressure); control frames
        always pass. Every frame carries the channel's next sequence
        number so the consumer can dedup and re-order faulty delivery."""
        await self._ensure_connected()
        if is_data:
            sem = self._sems.get(chan)
            if sem is not None:
                if stats is not None and sem.locked():
                    stats.permits_waited += 1
                await sem.acquire()
        if self.broken or self._writer is None:
            raise PeerLost(
                f"exchange peer {self.host}:{self.port} is down")
        seq = self._seqs.get(chan, 0)
        self._seqs[chan] = seq + 1
        return await self._raw_send({"type": "exg_data", "chan": chan,
                                     "seq": seq, "msg": wire_msg})

    async def aclose(self) -> None:
        for t in (self._reader_task, self._keepalive_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        self._reader_task = None
        self._keepalive_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 - already dying
                pass
            self._writer = None


class PeerClientPool:
    """One ``ExchangePeerClient`` per (host, port) target, shared by every
    edge this process produces toward that peer (reference: the pooled
    compute clients of rpc_client/src/lib.rs). A broken client — socket
    error, process death, or the keepalive prober declaring a half-open
    link dead — is EVICTED and replaced on next lookup, so recovery's
    re-created edges (same worker, NEW port after respawn) never reuse a
    dead socket and never burn a permit on a doomed frame."""

    def __init__(self, from_worker: int, keepalive_s: float = 10.0,
                 keepalive_timeout_s: float = 5.0):
        self.from_worker = from_worker
        self.keepalive_s = keepalive_s
        self.keepalive_timeout_s = keepalive_timeout_s
        self.evictions = 0
        self._clients: Dict[Tuple[str, int], ExchangePeerClient] = {}

    def get(self, host: str, port: int,
            peer_worker: Optional[int] = None) -> ExchangePeerClient:
        key = (host, port)
        client = self._clients.get(key)
        if client is None or client.broken:
            if client is not None:
                # eviction must also TEAR DOWN the broken client: its
                # reader task blocks in read_frame on a half-open socket
                # that may never deliver EOF, and once replaced in the
                # dict, pool.aclose() can no longer reach it
                self.evictions += 1
                try:
                    asyncio.ensure_future(client.aclose())
                except RuntimeError:     # no running loop (sync caller)
                    pass
            client = ExchangePeerClient(
                host, port, self.from_worker, peer_worker=peer_worker,
                keepalive_s=self.keepalive_s,
                keepalive_timeout_s=self.keepalive_timeout_s)
            self._clients[key] = client
        return client

    async def aclose(self) -> None:
        for client in self._clients.values():
            await client.aclose()
        self._clients.clear()
