"""Deterministic network fault plane under the RPC fabric.

FoundationDB-style deterministic simulation for the cluster's internal
links (reference posture: seeded, replayable fault schedules plus
machine-checked invariants find the distributed bugs random chaos
misses). Every frame the session↔worker control sockets, the
worker↔worker exchange sockets, and the compactor control socket carry
routes through a per-link ``FaultyTransport`` obtained from the
process-global plane; an installed ``ChaosSchedule`` then decides — as a
PURE function of (seed, link, per-link frame seq, frame type, per-link
epoch) — whether to drop, duplicate, reorder, delay, or partition each
frame. Replaying the same seed over the same workload reproduces the
identical per-link injection trace, so a failing run is a repro, not an
anecdote.

Link naming (one string per directed edge):

    s->w0     session control frames toward worker 0
    w0->s     worker 0's replies / barrier acks / data acks
    w0->w1    worker 0's exchange frames toward worker 1 (exg_data/ack)
    s->c0     compactor control (sync frames), and c0->s its replies
    s->udf    UDF-plane batches toward the UDF server (udf/client.py),
              and udf->s its replies (ISSUE 15)
    meta      the meta store's durable txn appends (in-process IO)

Rule matching supports ``fnmatch`` patterns and the shorthand
``"w0<->w1"`` (both directions). ``ChaosSchedule`` is JSON-serializable;
worker subprocesses inherit it through the ``RWTPU_CHAOS`` env var and
persist their injection traces to ``<data_dir>/chaos_trace.jsonl`` so a
killed worker's pre-death injections survive for replay comparison.

Determinism contract: wall-clock-driven frames (keepalive pings/pongs,
stats polls and their replies) pass through the plane WITHOUT consuming
a link seq and WITHOUT entering the trace — they are still subject to
partition/sever windows (that is how the keepalive detects a severed
link) but can never perturb the decision stream of real frames.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: frame types that are wall-clock-driven and therefore excluded from
#: per-link seq accounting and the injection trace (see module docstring)
META_FRAME_TYPES = frozenset({"exg_ping", "exg_pong", "stats"})

FAULT_KINDS = ("partition", "sever", "drop", "delay", "duplicate",
               "meta_fault")


def _hash01(seed: int, link: str, seq: int, salt: int = 0) -> float:
    """Deterministic uniform draw in [0, 1): stable across processes,
    platforms, and PYTHONHASHSEED (uses sha256, not hash())."""
    h = hashlib.sha256(
        f"{seed}|{link}|{seq}|{salt}".encode()).digest()
    return int.from_bytes(h[:8], "little") / float(1 << 64)


@dataclasses.dataclass
class ChaosRule:
    """One per-link fault rule.

    kind      partition | sever | drop | delay | duplicate | meta_fault
    link      fnmatch pattern over link names; "a<->b" matches both
              directions
    types     optional frame-type filter; exchange frames also expose
              their inner message as "exg_data:chunk" / "exg_data:barrier"
    frames    optional [lo, hi) window over the link's frame seq
    epochs    optional [lo, hi) window over the link's last-seen epoch
              (updated from barrier/commit frames ON that link — a
              per-link quantity, so the window is deterministic)
    prob      per-frame probability (seeded hash draw; 1.0 = always)
    count     max times this rule may fire (None = unlimited)
    delay_frames  (kind=delay) hold the frame until N later frames have
              been sent on the link — deterministic reordering
    delay_ms  (kind=delay) wall-clock delay before the write
    """

    kind: str
    link: str = "*"
    types: Optional[List[str]] = None
    frames: Optional[List[int]] = None
    epochs: Optional[List[int]] = None
    prob: float = 1.0
    count: Optional[int] = None
    delay_frames: int = 0
    delay_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    def matches_link(self, link: str) -> bool:
        import fnmatch
        pat = self.link
        if "<->" in pat:
            a, b = pat.split("<->", 1)
            return (fnmatch.fnmatch(link, f"{a}->{b}")
                    or fnmatch.fnmatch(link, f"{b}->{a}"))
        return fnmatch.fnmatch(link, pat)

    def matches(self, link: str, seq: int, ftype: str, subtype: str,
                epoch: int, seed: int, rule_idx: int) -> bool:
        if not self.matches_link(link):
            return False
        if self.types is not None and ftype not in self.types \
                and subtype not in self.types:
            return False
        if self.frames is not None and not (
                self.frames[0] <= seq < self.frames[1]):
            return False
        if self.epochs is not None and not (
                self.epochs[0] <= epoch < self.epochs[1]):
            return False
        if self.prob < 1.0 and _hash01(seed, link, seq,
                                       salt=rule_idx) >= self.prob:
            return False
        return True


class ChaosSchedule:
    """A seeded, JSON-serializable set of per-link fault rules. The
    schedule itself is immutable; mutable per-link state (seq counters,
    hold queues, fire counts) lives in the installing plane so the same
    schedule object can be round-tripped and re-installed for replay."""

    def __init__(self, seed: int, rules: List[ChaosRule],
                 name: str = ""):
        self.seed = int(seed)
        self.rules = list(rules)
        self.name = name

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed, "name": self.name,
            "rules": [dataclasses.asdict(r) for r in self.rules],
        }, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ChaosSchedule":
        d = json.loads(s)
        return cls(d["seed"], [ChaosRule(**r) for r in d["rules"]],
                   name=d.get("name", ""))


class _LinkState:
    __slots__ = ("seq", "epoch", "held", "frames_seen")

    def __init__(self) -> None:
        self.seq = 0
        self.epoch = 0
        # (release_at_seq, payload_bytes) queue of reorder-delayed frames
        self.held: List[Tuple[int, bytes]] = []
        self.frames_seen = 0


class ChaosPlane:
    """Process-global registry: the installed schedule + per-link state
    + counters + the injection trace. ``metrics()["chaos"]`` surfaces
    ``snapshot()``; worker processes ship theirs in stats frames."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.schedule: Optional[ChaosSchedule] = None
        self._links: Dict[str, _LinkState] = {}
        self._fired: Dict[int, int] = {}       # rule idx -> fire count
        self.injections: Dict[str, int] = {}   # kind -> count
        self.trace: List[dict] = []
        self.trace_path: Optional[str] = None
        self._trace_f = None

    # -- lifecycle ------------------------------------------------------------

    def install(self, schedule: Optional[ChaosSchedule],
                trace_path: Optional[str] = None) -> None:
        with self._lock:
            if self._trace_f is not None:
                try:
                    self._trace_f.close()
                except Exception:  # noqa: BLE001
                    pass
                self._trace_f = None
            self.schedule = schedule
            self._links.clear()
            self._fired.clear()
            self.injections = {}
            self.trace = []
            self.trace_path = trace_path
            if schedule is not None and trace_path is not None:
                os.makedirs(os.path.dirname(os.path.abspath(trace_path)),
                            exist_ok=True)
                self._trace_f = open(trace_path, "a", encoding="utf-8")
                # incarnation marker: the file appends across process
                # respawns whose per-stream seqs restart at 0 — readers
                # count these to keep same-(seq,rule) events from
                # different incarnations distinct
                self._trace_f.write(json.dumps(
                    {"marker": "install", "seed": schedule.seed,
                     "name": schedule.name}) + "\n")
                self._trace_f.flush()

    def clear(self) -> None:
        self.install(None)

    @property
    def installed(self) -> bool:
        return self.schedule is not None

    # -- decision core --------------------------------------------------------

    def _state(self, link: str) -> _LinkState:
        st = self._links.get(link)
        if st is None:
            st = self._links[link] = _LinkState()
        return st

    def _record(self, link: str, seq: int, kind: str, rule_idx: int,
                ftype: str, epoch: int) -> None:
        self.injections[kind] = self.injections.get(kind, 0) + 1
        ev = {"link": link, "seq": seq, "kind": kind, "rule": rule_idx,
              "type": ftype, "epoch": epoch}
        self.trace.append(ev)
        if self._trace_f is not None:
            self._trace_f.write(json.dumps(ev, sort_keys=True) + "\n")
            self._trace_f.flush()

    def decide(self, link: str, ftype: str, subtype: str,
               epoch_hint: Optional[int],
               meta: bool) -> Tuple[List[Tuple[str, ChaosRule, int]], int]:
        """One frame's fate. ``link`` here is a STREAM key — the base
        directed edge plus an optional ``#c<chan>``/``#a<chan>`` suffix
        (several exchange edges multiplex one socket, and their
        interleaving is timing-dependent; per-channel streams are the
        deterministic unit, since one actor produces each in order).
        Rules match against the BASE link; seq/epoch/hold state is per
        stream. Returns (actions, seq). ``meta`` frames (keepalive/
        stats) consume no seq and leave no trace, but still honor
        partition/sever windows."""
        base = link.split("#", 1)[0]
        with self._lock:
            sched = self.schedule
            st = self._state(link)
            if epoch_hint is not None:
                st.epoch = max(st.epoch, int(epoch_hint))
            if sched is None:
                if not meta:
                    st.seq += 1
                return [], st.seq - 1
            seq = st.seq
            if not meta:
                st.seq += 1
                st.frames_seen += 1
            actions: List[Tuple[str, ChaosRule, int]] = []
            for idx, rule in enumerate(sched.rules):
                if meta and rule.kind not in ("partition", "sever"):
                    continue
                if rule.count is not None \
                        and self._fired.get(idx, 0) >= rule.count:
                    continue
                if not rule.matches(base, seq, ftype, subtype, st.epoch,
                                    sched.seed, idx):
                    continue
                self._fired[idx] = self._fired.get(idx, 0) + 1
                actions.append((rule.kind, rule, idx))
                if not meta:
                    self._record(link, seq, rule.kind, idx, ftype,
                                 st.epoch)
            return actions, seq

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "installed": self.schedule is not None,
                "seed": self.schedule.seed if self.schedule else None,
                "name": self.schedule.name if self.schedule else "",
                "injections": dict(self.injections),
                "links": {l: {"frames": st.frames_seen, "seq": st.seq,
                              "epoch": st.epoch, "held": len(st.held)}
                          for l, st in sorted(self._links.items())},
                "trace_len": len(self.trace),
            }

    def trace_by_link(self) -> Dict[str, List[dict]]:
        with self._lock:
            out: Dict[str, List[dict]] = {}
            for ev in self.trace:
                out.setdefault(ev["link"], []).append(ev)
            return out


_PLANE = ChaosPlane()

#: env var carrying a schedule JSON into worker/compactor subprocesses
CHAOS_ENV = "RWTPU_CHAOS"


def plane() -> ChaosPlane:
    return _PLANE


def install(schedule: Optional[ChaosSchedule],
            trace_path: Optional[str] = None) -> ChaosPlane:
    _PLANE.install(schedule, trace_path=trace_path)
    return _PLANE


def install_from_env(trace_path: Optional[str] = None) -> bool:
    """Worker-process bring-up: adopt the spawning session's schedule
    (RWTPU_CHAOS env JSON). Returns True when a schedule was installed."""
    s = os.environ.get(CHAOS_ENV)
    if not s:
        return False
    _PLANE.install(ChaosSchedule.from_json(s), trace_path=trace_path)
    return True


def chaos_snapshot() -> dict:
    return _PLANE.snapshot()


def _frame_kind(obj: dict) -> Tuple[str, str, Optional[int]]:
    """(ftype, subtype, epoch_hint) of one frame. Exchange data frames
    expose their inner message type; barrier-ish frames expose their
    epoch so per-link epoch windows advance deterministically."""
    ftype = str(obj.get("type", "?"))
    subtype = ftype
    epoch = None
    if ftype == "exg_data":
        msg = obj.get("msg") or {}
        subtype = f"exg_data:{msg.get('t', '?')}"
        if msg.get("t") == "barrier":
            epoch = msg.get("epoch")
    elif ftype in ("barrier", "commit"):
        epoch = obj.get("epoch")
    return ftype, subtype, epoch


class FaultyTransport:
    """Per-link frame gate. Send sites build their frame and hand it
    here with an ``emit`` callback performing the actual socket write;
    recv sites pass inbound frames through ``recv`` (which only counts —
    all faults are injected sender-side, where determinism lives)."""

    def __init__(self, link: str, pl: Optional[ChaosPlane] = None):
        self.link = link
        self.plane = pl or _PLANE

    # -- helpers --------------------------------------------------------------

    def _stream_key(self, obj: dict, ftype: str) -> str:
        """Per-channel stream key: one exchange socket multiplexes many
        edges (and their acks), whose interleaving is wall-clock-
        dependent — per-channel streams are produced by ONE actor in
        order, so seq-keyed decisions replay deterministically."""
        chan = obj.get("chan")
        if chan is None:
            return self.link
        tag = "a" if ftype in ("exg_ack", "ack") else "c"
        return f"{self.link}#{tag}{chan}"

    def _plan(self, obj: dict, meta: bool):
        ftype, subtype, epoch = _frame_kind(obj)
        if not meta and ftype in META_FRAME_TYPES:
            meta = True
        key = self._stream_key(obj, ftype)
        actions, seq = self.plane.decide(key, ftype, subtype,
                                         epoch, meta)
        dropped = any(k in ("partition", "sever", "drop")
                      for k, _, _ in actions)
        dup = any(k == "duplicate" for k, _, _ in actions)
        delay_ms = max((r.delay_ms for k, r, _ in actions
                        if k == "delay"), default=0.0)
        delay_frames = max((r.delay_frames for k, r, _ in actions
                            if k == "delay"), default=0)
        is_barrier = subtype.endswith("barrier")
        if is_barrier:
            # barriers are never reorder-held: they are the epoch cut,
            # and the cut flushing the hold queue (below) is what keeps
            # a frame held near stream end from being lost forever
            delay_frames = 0
        return key, seq, dropped, dup, delay_ms, delay_frames, is_barrier

    def _release_due(self, key: str, seq: int,
                     all_held: bool = False) -> List[bytes]:
        # a frame held at seq S with delay n releases once n LATER
        # frames have been sent, i.e. before emitting seq > S + n — or
        # unconditionally before a BARRIER (all_held), so reordering
        # stays within an epoch and nothing is held past stream end
        with self.plane._lock:
            st = self.plane._state(key)
            if all_held:
                due = [b for (_at, b) in st.held]
                st.held = []
            else:
                due = [b for (at, b) in st.held if at < seq]
                st.held = [(at, b) for (at, b) in st.held if at >= seq]
        return due

    def _hold(self, key: str, seq: int, n: int, buf: bytes) -> None:
        with self.plane._lock:
            self.plane._state(key).held.append((seq + n, buf))

    # -- async send -----------------------------------------------------------

    async def send(self, obj: dict, buf: bytes, emit,
                   meta: bool = False) -> bool:
        """Route one outbound frame. ``emit`` is an async callable
        taking the packed bytes. Returns False when the frame was
        dropped/held (callers treat it as written — that is the point:
        the network ate it)."""
        if not self.plane.installed:
            await emit(buf)
            return True
        (key, seq, dropped, dup, delay_ms, delay_frames,
         is_barrier) = self._plan(obj, meta)
        if dropped:
            # an active partition/sever/drop window eats EVERYTHING on
            # the stream — including frames a delay rule was holding
            # (releasing them mid-window would leak traffic through the
            # documented total-starvation contract; they stay held and
            # flush with the first frame after the window)
            return False
        for late in self._release_due(key, seq, all_held=is_barrier):
            await emit(late)
        if delay_ms > 0:
            import asyncio
            await asyncio.sleep(delay_ms / 1000.0)
        if delay_frames > 0:
            self._hold(key, seq, delay_frames, buf)
            return False
        await emit(buf)
        if dup:
            await emit(buf)
        return True

    # -- sync send (compactor control conversation) ---------------------------

    def send_sync(self, obj: dict, buf: bytes, emit,
                  meta: bool = False) -> bool:
        if not self.plane.installed:
            emit(buf)
            return True
        (key, seq, dropped, dup, delay_ms, delay_frames,
         is_barrier) = self._plan(obj, meta)
        if dropped:
            return False       # window eats held frames too (see send)
        for late in self._release_due(key, seq, all_held=is_barrier):
            emit(late)
        if delay_ms > 0:
            time.sleep(delay_ms / 1000.0)
        if delay_frames > 0:
            self._hold(key, seq, delay_frames, buf)
            return False
        emit(buf)
        if dup:
            emit(buf)
        return True


def meta_io(op: str, key: str) -> None:
    """Meta-store durable-IO injection point (link "meta"): a
    ``meta_fault`` rule matching the "meta" link raises OSError here,
    exercising the meta tier's torn-txn handling from the same seeded
    registry as the wire faults."""
    if not _PLANE.installed:
        return
    actions, _seq = _PLANE.decide("meta", op, f"{op}:{key}", None, False)
    for kind, _rule, _idx in actions:
        if kind == "meta_fault":
            raise OSError(f"chaos: meta store {op} {key!r} failed")
