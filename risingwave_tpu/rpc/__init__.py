from .wire import (  # noqa: F401
    chunk_to_wire, message_from_wire, message_to_wire, read_frame,
    wire_to_chunk, write_frame,
)
