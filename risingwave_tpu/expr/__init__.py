from .expr import (  # noqa: F401
    Cast, Expr, FunctionCall, InputRef, Literal, call, cast, col, eval_many,
    input_refs, register,
)
from .agg import AggCall, agg, count_star  # noqa: F401
