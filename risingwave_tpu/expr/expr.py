"""Vectorized expression engine.

Counterpart of the reference's ``Expression::{eval, eval_row}`` engine
(reference: src/expr/src/expr/mod.rs:85-126 and the ~40 scalar-function
modules under src/expr/src/vector_op/). Here an expression is a small static
tree whose ``eval(chunk) -> Column`` is pure jnp over column arrays — the
whole tree inlines into the enclosing jitted operator step, so XLA fuses the
expression with the operator (no interpreter at runtime, unlike the
reference's boxed-trait-object evaluation).

Null semantics are SQL three-valued logic: masks propagate through strict
functions; AND/OR use Kleene logic; CASE/COALESCE/IS NULL handle masks
explicitly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Schema, TypeKind


class Expr:
    """Base class. Subclasses are immutable, hashable plan-time objects."""

    #: result logical type — set by each subclass
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        raise NotImplementedError

    # operator sugar for plan building / tests
    def __add__(self, o): return call("add", self, _lit(o))
    def __sub__(self, o): return call("subtract", self, _lit(o))
    def __mul__(self, o): return call("multiply", self, _lit(o))
    def __truediv__(self, o): return call("divide", self, _lit(o))
    def __mod__(self, o): return call("modulus", self, _lit(o))
    def __eq__(self, o): return call("equal", self, _lit(o))  # type: ignore[override]
    def __ne__(self, o): return call("not_equal", self, _lit(o))  # type: ignore[override]
    def __lt__(self, o): return call("less_than", self, _lit(o))
    def __le__(self, o): return call("less_than_or_equal", self, _lit(o))
    def __gt__(self, o): return call("greater_than", self, _lit(o))
    def __ge__(self, o): return call("greater_than_or_equal", self, _lit(o))
    def __and__(self, o): return call("and", self, _lit(o))
    def __or__(self, o): return call("or", self, _lit(o))
    def __invert__(self): return call("not", self)
    def __hash__(self):  # keep Expr usable as dict key despite __eq__ override
        return id(self)


def _lit(v) -> "Expr":
    return v if isinstance(v, Expr) else Literal.infer(v)


@dataclasses.dataclass(frozen=True, eq=False)
class InputRef(Expr):
    """Reference to input column ``index`` (reference: expr/expr_input_ref.rs)."""

    index: int
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        return chunk.columns[self.index]


@dataclasses.dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any
    type: DataType

    @staticmethod
    def infer(v: Any) -> "Literal":
        from ..common import types as T
        if isinstance(v, bool):
            return Literal(v, T.BOOL)
        if isinstance(v, int):
            return Literal(v, T.INT64)
        if isinstance(v, float):
            return Literal(v, T.FLOAT64)
        if isinstance(v, str):
            return Literal(v, T.VARCHAR)
        if v is None:
            return Literal(None, T.INT64)
        raise TypeError(f"cannot infer literal type for {v!r}")

    def eval(self, chunk: StreamChunk) -> Column:
        cap = chunk.capacity
        if self.value is None:
            data = jnp.zeros(cap, self.type.dtype)
            return Column(data, jnp.zeros(cap, jnp.bool_))
        phys = self.type.to_physical(self.value)
        return Column(
            jnp.full(cap, phys, self.type.dtype), jnp.ones(cap, jnp.bool_)
        )


# ---------------------------------------------------------------------------
# Scalar function registry
# ---------------------------------------------------------------------------

#: name -> (impl, type_infer). impl(datas, masks, out_type) -> (data, mask).
_REGISTRY: dict[str, tuple[Callable, Callable]] = {}


def register(name: str, type_infer: Callable[[Sequence[DataType]], DataType]):
    def deco(fn):
        _REGISTRY[name] = (fn, type_infer)
        return fn
    return deco


@dataclasses.dataclass(frozen=True, eq=False)
class FunctionCall(Expr):
    name: str
    args: tuple[Expr, ...]
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        impl, _ = _REGISTRY[self.name]
        cols = [a.eval(chunk) for a in self.args]
        data, mask = impl([c.data for c in cols], [c.mask for c in cols], self.type)
        return Column(data, mask)


def call(name: str, *args: Expr) -> FunctionCall:
    if name not in _REGISTRY:
        raise KeyError(f"unknown function {name!r}")
    _, infer = _REGISTRY[name]
    out_type = infer([a.type for a in args])
    return FunctionCall(name, tuple(args), out_type)


def col(index: int, type: DataType) -> InputRef:
    return InputRef(index, type)


def input_refs(schema: Schema) -> list[InputRef]:
    return [InputRef(i, f.type) for i, f in enumerate(schema)]


# -- type inference helpers --------------------------------------------------

from ..common import types as T  # noqa: E402

_NUM_ORDER = [
    TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.DECIMAL,
    TypeKind.FLOAT32, TypeKind.FLOAT64,
]


def _promote(ts: Sequence[DataType]) -> DataType:
    """Widest numeric type; a non-numeric operand (timestamp/date/interval
    arithmetic) wins regardless of position."""
    for t in ts:
        if t.kind not in _NUM_ORDER:
            return t
    best = ts[0]
    for t in ts[1:]:
        if t.kind == best.kind:
            continue
        if _NUM_ORDER.index(t.kind) > _NUM_ORDER.index(best.kind):
            best = t
    return best


def _t_bool(ts): return T.BOOL
def _t_same(ts): return _promote(ts)
def _t_first(ts): return ts[0]
def _t_float(ts): return T.FLOAT64
def _t_int64(ts): return T.INT64


def _strict_mask(masks):
    m = masks[0]
    for mm in masks[1:]:
        m = m & mm
    return m


def _binary(fn):
    def impl(datas, masks, out_type):
        a, b = datas
        ct = jnp.result_type(a.dtype, b.dtype)
        return fn(a.astype(ct), b.astype(ct)).astype(out_type.dtype), _strict_mask(masks)
    return impl


def _unary(fn):
    def impl(datas, masks, out_type):
        return fn(datas[0]).astype(out_type.dtype), masks[0]
    return impl


def _cmp(fn):
    def impl(datas, masks, out_type):
        a, b = datas
        ct = jnp.result_type(a.dtype, b.dtype)
        return fn(a.astype(ct), b.astype(ct)), _strict_mask(masks)
    return impl


# arithmetic (reference: src/expr/src/vector_op/arithmetic_op.rs)
register("add", _t_same)(_binary(jnp.add))
register("subtract", _t_same)(_binary(jnp.subtract))
register("multiply", _t_same)(_binary(jnp.multiply))
register("neg", _t_first)(_unary(jnp.negative))
register("abs", _t_first)(_unary(jnp.abs))


@register("divide", _t_same)
def _divide(datas, masks, out_type):
    a, b = datas
    mask = _strict_mask(masks) & (b != 0)  # div-by-zero -> NULL (SQL raises; we null)
    safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
    if out_type.is_float:
        r = a.astype(out_type.dtype) / safe_b.astype(out_type.dtype)
    else:
        # SQL integer division truncates toward zero (lax.div is C-style),
        # unlike python/jnp floor division.
        ct = jnp.result_type(a.dtype, b.dtype)
        r = jax.lax.div(a.astype(ct), safe_b.astype(ct)).astype(out_type.dtype)
    return r, mask


@register("modulus", _t_same)
def _modulus(datas, masks, out_type):
    a, b = datas
    mask = _strict_mask(masks) & (b != 0)
    safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
    # SQL modulus takes the dividend's sign (C-style rem), not jnp.mod's
    ct = jnp.result_type(a.dtype, b.dtype)
    return jax.lax.rem(a.astype(ct), safe_b.astype(ct)).astype(out_type.dtype), mask


# comparison (reference: src/expr/src/vector_op/cmp.rs)
register("equal", _t_bool)(_cmp(jnp.equal))
register("not_equal", _t_bool)(_cmp(jnp.not_equal))
register("less_than", _t_bool)(_cmp(jnp.less))
register("less_than_or_equal", _t_bool)(_cmp(jnp.less_equal))
register("greater_than", _t_bool)(_cmp(jnp.greater))
register("greater_than_or_equal", _t_bool)(_cmp(jnp.greater_equal))


# Kleene AND/OR (reference: src/expr/src/vector_op/conjunction.rs)
@register("and", _t_bool)
def _and(datas, masks, out_type):
    a, b = datas
    ma, mb = masks
    av = a & ma
    bv = b & mb
    false_a = ma & ~a
    false_b = mb & ~b
    result = av & bv
    known = (ma & mb) | false_a | false_b
    return result, known


@register("or", _t_bool)
def _or(datas, masks, out_type):
    a, b = datas
    ma, mb = masks
    true_a = ma & a
    true_b = mb & b
    result = true_a | true_b
    known = (ma & mb) | true_a | true_b
    return result, known


@register("not", _t_bool)
def _not(datas, masks, out_type):
    return ~datas[0], masks[0]


# null handling
@register("is_null", _t_bool)
def _is_null(datas, masks, out_type):
    return ~masks[0], jnp.ones_like(masks[0])


@register("is_not_null", _t_bool)
def _is_not_null(datas, masks, out_type):
    return masks[0], jnp.ones_like(masks[0])


@register("coalesce", _t_first)
def _coalesce(datas, masks, out_type):
    data = jnp.zeros_like(datas[0]).astype(out_type.dtype)
    mask = jnp.zeros_like(masks[0])
    # iterate last-arg-first so the first non-null argument wins
    for d, m in zip(reversed(datas), reversed(masks)):
        data = jnp.where(m, d.astype(out_type.dtype), data)
        mask = mask | m
    return data, mask


# conditional: case(cond1, val1, cond2, val2, ..., else_val)
@register("case", lambda ts: ts[1])
def _case(datas, masks, out_type):
    n = len(datas)
    has_else = n % 2 == 1
    if has_else:
        data = datas[-1].astype(out_type.dtype)
        mask = masks[-1]
        pairs = (n - 1) // 2
    else:
        data = jnp.zeros_like(datas[1]).astype(out_type.dtype)
        mask = jnp.zeros_like(masks[0])
        pairs = n // 2
    for i in reversed(range(pairs)):
        cond = datas[2 * i] & masks[2 * i]
        data = jnp.where(cond, datas[2 * i + 1].astype(out_type.dtype), data)
        mask = jnp.where(cond, masks[2 * i + 1], mask)
    return data, mask


# cast
@dataclasses.dataclass(frozen=True, eq=False)
class Cast(Expr):
    arg: Expr
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        c = self.arg.eval(chunk)
        src, dst = self.arg.type, self.type
        data = c.data
        if src.kind == TypeKind.DECIMAL and dst.is_float:
            data = data.astype(dst.dtype) / (10 ** src.scale)
        elif dst.kind == TypeKind.DECIMAL and not src.kind == TypeKind.DECIMAL:
            data = jnp.round(data.astype(jnp.float64) * 10 ** dst.scale).astype(jnp.int64)
        else:
            data = data.astype(dst.dtype)
        return Column(data, c.mask)


def cast(arg: Expr, to: DataType) -> Expr:
    return Cast(arg, to) if arg.type != to else arg


# math
register("round", _t_first)(_unary(jnp.round))
register("floor", _t_first)(_unary(jnp.floor))
register("ceil", _t_first)(_unary(jnp.ceil))


# temporal: epoch-microsecond arithmetic (reference: vector_op/extract.rs,
# tumble_start in vector_op/tumble.rs)
USECS_PER_SEC = 1_000_000
USECS_PER_MIN = 60 * USECS_PER_SEC
USECS_PER_HOUR = 60 * USECS_PER_MIN
USECS_PER_DAY = 24 * USECS_PER_HOUR


@register("tumble_start", lambda ts: T.TIMESTAMP)
def _tumble_start(datas, masks, out_type):
    ts, window = datas
    w = window.astype(jnp.int64)
    safe = jnp.where(w == 0, 1, w)
    return (ts.astype(jnp.int64) // safe) * safe, _strict_mask(masks) & (w != 0)


@register("extract_epoch", _t_int64)
def _extract_epoch(datas, masks, out_type):
    return datas[0].astype(jnp.int64) // USECS_PER_SEC, masks[0]


@register("extract_hour", _t_int64)
def _extract_hour(datas, masks, out_type):
    return (datas[0].astype(jnp.int64) % USECS_PER_DAY) // USECS_PER_HOUR, masks[0]


def eval_many(exprs: Sequence[Expr], chunk: StreamChunk) -> tuple[Column, ...]:
    return tuple(e.eval(chunk) for e in exprs)
