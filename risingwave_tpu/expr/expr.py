"""Vectorized expression engine.

Counterpart of the reference's ``Expression::{eval, eval_row}`` engine
(reference: src/expr/src/expr/mod.rs:85-126 and the ~40 scalar-function
modules under src/expr/src/vector_op/). Here an expression is a small static
tree whose ``eval(chunk) -> Column`` is pure jnp over column arrays — the
whole tree inlines into the enclosing jitted operator step, so XLA fuses the
expression with the operator (no interpreter at runtime, unlike the
reference's boxed-trait-object evaluation).

Null semantics are SQL three-valued logic: masks propagate through strict
functions; AND/OR use Kleene logic; CASE/COALESCE/IS NULL handle masks
explicitly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Schema, TypeKind


class Expr:
    """Base class. Subclasses are immutable, hashable plan-time objects."""

    #: result logical type — set by each subclass
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        raise NotImplementedError

    # operator sugar for plan building / tests
    def __add__(self, o): return call("add", self, _lit(o))
    def __sub__(self, o): return call("subtract", self, _lit(o))
    def __mul__(self, o): return call("multiply", self, _lit(o))
    def __truediv__(self, o): return call("divide", self, _lit(o))
    def __mod__(self, o): return call("modulus", self, _lit(o))
    def __eq__(self, o): return call("equal", self, _lit(o))  # type: ignore[override]
    def __ne__(self, o): return call("not_equal", self, _lit(o))  # type: ignore[override]
    def __lt__(self, o): return call("less_than", self, _lit(o))
    def __le__(self, o): return call("less_than_or_equal", self, _lit(o))
    def __gt__(self, o): return call("greater_than", self, _lit(o))
    def __ge__(self, o): return call("greater_than_or_equal", self, _lit(o))
    def __and__(self, o): return call("and", self, _lit(o))
    def __or__(self, o): return call("or", self, _lit(o))
    def __invert__(self): return call("not", self)
    def __hash__(self):  # keep Expr usable as dict key despite __eq__ override
        return id(self)


def _lit(v) -> "Expr":
    return v if isinstance(v, Expr) else Literal.infer(v)


@dataclasses.dataclass(frozen=True, eq=False)
class InputRef(Expr):
    """Reference to input column ``index`` (reference: expr/expr_input_ref.rs)."""

    index: int
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        return chunk.columns[self.index]


@dataclasses.dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any
    type: DataType

    @staticmethod
    def infer(v: Any) -> "Literal":
        from ..common import types as T
        if isinstance(v, bool):
            return Literal(v, T.BOOL)
        if isinstance(v, int):
            return Literal(v, T.INT64)
        if isinstance(v, float):
            return Literal(v, T.FLOAT64)
        if isinstance(v, str):
            return Literal(v, T.VARCHAR)
        if v is None:
            return Literal(None, T.INT64)
        raise TypeError(f"cannot infer literal type for {v!r}")

    def eval(self, chunk: StreamChunk) -> Column:
        cap = chunk.capacity
        if self.value is None:
            data = jnp.zeros(cap, self.type.dtype)
            return Column(data, jnp.zeros(cap, jnp.bool_))
        phys = self.type.to_physical(self.value)
        return Column(
            jnp.full(cap, phys, self.type.dtype), jnp.ones(cap, jnp.bool_)
        )


# ---------------------------------------------------------------------------
# Scalar function registry
# ---------------------------------------------------------------------------

#: name -> (impl, type_infer). impl(datas, masks, out_type) -> (data, mask).
_REGISTRY: dict[str, tuple[Callable, Callable]] = {}


def register(name: str, type_infer: Callable[[Sequence[DataType]], DataType]):
    def deco(fn):
        _REGISTRY[name] = (fn, type_infer)
        return fn
    return deco


@dataclasses.dataclass(frozen=True, eq=False)
class FunctionCall(Expr):
    name: str
    args: tuple[Expr, ...]
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        impl, _ = _REGISTRY[self.name]
        cols = [a.eval(chunk) for a in self.args]
        data, mask = impl([c.data for c in cols], [c.mask for c in cols], self.type)
        return Column(data, mask)


_DECIMAL_ALIGN_FNS = {
    "add", "subtract", "modulus", "equal", "not_equal", "less_than",
    "less_than_or_equal", "greater_than", "greater_than_or_equal",
}


def _decimal_fixup(name: str, args: tuple) -> tuple:
    """Fixed-point scale handling (reference: Decimal arithmetic in
    src/common/src/types/decimal.rs). DECIMAL is a scaled int64; aligned
    scales make +/-/cmp plain int ops; ``multiply`` adds scales (its type
    inference); ``divide`` and any float operand lower decimals to f64."""
    if not any(a.type.kind == TypeKind.DECIMAL for a in args):
        return args
    if name == "divide" or any(a.type.is_float for a in args):
        return tuple(
            cast(a, T.FLOAT64) if a.type.kind == TypeKind.DECIMAL else a
            for a in args)
    from ..common.types import decimal as _dec
    s = max(a.type.scale for a in args)

    def align(a):
        return cast(a, _dec(s)) if (a.type.kind == TypeKind.DECIMAL
                                    or a.type.is_integral) else a

    if name in _DECIMAL_ALIGN_FNS or name == "coalesce":
        return tuple(align(a) for a in args)
    if name == "case":
        # value positions only: odd indices + the trailing ELSE
        has_else = len(args) % 2 == 1
        out = list(args)
        for i in range(1, len(args) - (1 if has_else else 0), 2):
            out[i] = align(args[i])
        if has_else:
            out[-1] = align(args[-1])
        return tuple(out)
    return args


_STR_ORDER_FNS = {
    "less_than", "less_than_or_equal", "greater_than",
    "greater_than_or_equal",
}


def call(name: str, *args: Expr) -> FunctionCall:
    if name not in _REGISTRY:
        raise KeyError(f"unknown function {name!r}")
    args = _decimal_fixup(name, tuple(args))
    # Ordering comparisons on VARCHAR/BYTEA compare lexicographic *ranks*,
    # never raw dictionary ids (ids are insertion-ordered — reference order
    # semantics: src/common/src/util/memcmp_encoding.rs). The str_ variant
    # fetches ONE rank table after both operands are evaluated, so operand
    # evaluation that interns new strings (literals, string functions)
    # cannot skew the two sides' rank spaces. Equality stays on ids
    # (bijective with strings).
    if name in _STR_ORDER_FNS and all(a.type.is_string for a in args):
        name = "str_" + name
    _, infer = _REGISTRY[name]
    out_type = infer([a.type for a in args])
    return FunctionCall(name, tuple(args), out_type)


def col(index: int, type: DataType) -> InputRef:
    return InputRef(index, type)


def input_refs(schema: Schema) -> list[InputRef]:
    return [InputRef(i, f.type) for i, f in enumerate(schema)]


# -- type inference helpers --------------------------------------------------

from ..common import types as T  # noqa: E402

_NUM_ORDER = [
    TypeKind.INT16, TypeKind.INT32, TypeKind.INT64, TypeKind.DECIMAL,
    TypeKind.FLOAT32, TypeKind.FLOAT64,
]


def _promote(ts: Sequence[DataType]) -> DataType:
    """Widest numeric type; a non-numeric operand (timestamp/date/interval
    arithmetic) wins regardless of position."""
    for t in ts:
        if t.kind not in _NUM_ORDER:
            return t
    best = ts[0]
    for t in ts[1:]:
        if t.kind == best.kind:
            if t.kind == TypeKind.DECIMAL and t.scale > best.scale:
                best = t
            continue
        if _NUM_ORDER.index(t.kind) > _NUM_ORDER.index(best.kind):
            best = t
    return best


def _t_bool(ts): return T.BOOL
def _t_same(ts): return _promote(ts)
def _t_first(ts): return ts[0]
def _t_float(ts): return T.FLOAT64
def _t_int64(ts): return T.INT64


def _strict_mask(masks):
    m = masks[0]
    for mm in masks[1:]:
        m = m & mm
    return m


def _binary(fn):
    def impl(datas, masks, out_type):
        a, b = datas
        ct = jnp.result_type(a.dtype, b.dtype)
        return fn(a.astype(ct), b.astype(ct)).astype(out_type.dtype), _strict_mask(masks)
    return impl


def _unary(fn):
    def impl(datas, masks, out_type):
        return fn(datas[0]).astype(out_type.dtype), masks[0]
    return impl


def _cmp(fn):
    def impl(datas, masks, out_type):
        a, b = datas
        ct = jnp.result_type(a.dtype, b.dtype)
        return fn(a.astype(ct), b.astype(ct)), _strict_mask(masks)
    return impl


def _t_mul(ts):
    """Fixed-point product: scales add (decimal(s1) * decimal(s2) →
    decimal(s1+s2)); mixed float operands were lowered by _decimal_fixup."""
    decs = [t for t in ts if t.kind == TypeKind.DECIMAL]
    if decs:
        return T.decimal(sum(t.scale for t in decs))
    return _promote(ts)


# arithmetic (reference: src/expr/src/vector_op/arithmetic_op.rs)
register("add", _t_same)(_binary(jnp.add))
register("subtract", _t_same)(_binary(jnp.subtract))
register("multiply", _t_mul)(_binary(jnp.multiply))
register("neg", _t_first)(_unary(jnp.negative))
register("abs", _t_first)(_unary(jnp.abs))


@register("divide", _t_same)
def _divide(datas, masks, out_type):
    a, b = datas
    mask = _strict_mask(masks) & (b != 0)  # div-by-zero -> NULL (SQL raises; we null)
    safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
    if out_type.is_float:
        r = a.astype(out_type.dtype) / safe_b.astype(out_type.dtype)
    else:
        # SQL integer division truncates toward zero (lax.div is C-style),
        # unlike python/jnp floor division.
        ct = jnp.result_type(a.dtype, b.dtype)
        r = jax.lax.div(a.astype(ct), safe_b.astype(ct)).astype(out_type.dtype)
    return r, mask


@register("modulus", _t_same)
def _modulus(datas, masks, out_type):
    a, b = datas
    mask = _strict_mask(masks) & (b != 0)
    safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
    # SQL modulus takes the dividend's sign (C-style rem), not jnp.mod's
    ct = jnp.result_type(a.dtype, b.dtype)
    return jax.lax.rem(a.astype(ct), safe_b.astype(ct)).astype(out_type.dtype), mask


# comparison (reference: src/expr/src/vector_op/cmp.rs)
register("equal", _t_bool)(_cmp(jnp.equal))
register("not_equal", _t_bool)(_cmp(jnp.not_equal))
register("less_than", _t_bool)(_cmp(jnp.less))
register("less_than_or_equal", _t_bool)(_cmp(jnp.less_equal))
register("greater_than", _t_bool)(_cmp(jnp.greater))
register("greater_than_or_equal", _t_bool)(_cmp(jnp.greater_equal))


# Kleene AND/OR (reference: src/expr/src/vector_op/conjunction.rs)
@register("and", _t_bool)
def _and(datas, masks, out_type):
    a, b = datas
    ma, mb = masks
    av = a & ma
    bv = b & mb
    false_a = ma & ~a
    false_b = mb & ~b
    result = av & bv
    known = (ma & mb) | false_a | false_b
    return result, known


@register("or", _t_bool)
def _or(datas, masks, out_type):
    a, b = datas
    ma, mb = masks
    true_a = ma & a
    true_b = mb & b
    result = true_a | true_b
    known = (ma & mb) | true_a | true_b
    return result, known


@register("not", _t_bool)
def _not(datas, masks, out_type):
    return ~datas[0], masks[0]


# null handling
@register("is_null", _t_bool)
def _is_null(datas, masks, out_type):
    return ~masks[0], jnp.ones_like(masks[0])


@register("is_not_null", _t_bool)
def _is_not_null(datas, masks, out_type):
    return masks[0], jnp.ones_like(masks[0])


@register("coalesce", _t_first)
def _coalesce(datas, masks, out_type):
    data = jnp.zeros_like(datas[0]).astype(out_type.dtype)
    mask = jnp.zeros_like(masks[0])
    # iterate last-arg-first so the first non-null argument wins
    for d, m in zip(reversed(datas), reversed(masks)):
        data = jnp.where(m, d.astype(out_type.dtype), data)
        mask = mask | m
    return data, mask


# conditional: case(cond1, val1, cond2, val2, ..., else_val)
@register("case", lambda ts: ts[1])
def _case(datas, masks, out_type):
    n = len(datas)
    has_else = n % 2 == 1
    if has_else:
        data = datas[-1].astype(out_type.dtype)
        mask = masks[-1]
        pairs = (n - 1) // 2
    else:
        data = jnp.zeros_like(datas[1]).astype(out_type.dtype)
        mask = jnp.zeros_like(masks[0])
        pairs = n // 2
    for i in reversed(range(pairs)):
        cond = datas[2 * i] & masks[2 * i]
        data = jnp.where(cond, datas[2 * i + 1].astype(out_type.dtype), data)
        mask = jnp.where(cond, masks[2 * i + 1], mask)
    return data, mask


# cast
@dataclasses.dataclass(frozen=True, eq=False)
class Cast(Expr):
    arg: Expr
    type: DataType

    def eval(self, chunk: StreamChunk) -> Column:
        c = self.arg.eval(chunk)
        src, dst = self.arg.type, self.type
        data = c.data

        def _round_div(d, factor):
            # PG rounds half away from zero when narrowing fixed point
            f = jnp.asarray(factor, d.dtype)
            half = jnp.where(d >= 0, f // 2, -(f // 2))
            return jax.lax.div(d + half, f)

        if src.kind == TypeKind.DECIMAL and dst.kind == TypeKind.DECIMAL:
            if dst.scale >= src.scale:
                data = data * (10 ** (dst.scale - src.scale))
            else:
                data = _round_div(data, 10 ** (src.scale - dst.scale))
        elif src.kind == TypeKind.DECIMAL and dst.is_float:
            data = data.astype(dst.dtype) / (10 ** src.scale)
        elif src.kind == TypeKind.DECIMAL:
            data = _round_div(data, 10 ** src.scale).astype(dst.dtype)
        elif dst.kind == TypeKind.DECIMAL:
            data = jnp.round(
                data.astype(jnp.float64) * 10 ** dst.scale).astype(jnp.int64)
        elif (src.kind == TypeKind.DATE and dst.kind == TypeKind.TIMESTAMP):
            data = data.astype(jnp.int64) * USECS_PER_DAY
        elif (src.kind == TypeKind.TIMESTAMP and dst.kind == TypeKind.DATE):
            data = (data.astype(jnp.int64) // USECS_PER_DAY).astype(dst.dtype)
        else:
            data = data.astype(dst.dtype)
        return Column(data, c.mask)


def cast(arg: Expr, to: DataType) -> Expr:
    return Cast(arg, to) if arg.type != to else arg


# math
register("round", _t_first)(_unary(jnp.round))
register("floor", _t_first)(_unary(jnp.floor))
register("ceil", _t_first)(_unary(jnp.ceil))


# temporal: epoch-microsecond arithmetic (reference: vector_op/extract.rs,
# tumble_start in vector_op/tumble.rs)
USECS_PER_SEC = 1_000_000
USECS_PER_MIN = 60 * USECS_PER_SEC
USECS_PER_HOUR = 60 * USECS_PER_MIN
USECS_PER_DAY = 24 * USECS_PER_HOUR


@register("tumble_start", lambda ts: T.TIMESTAMP)
def _tumble_start(datas, masks, out_type):
    ts, window = datas
    w = window.astype(jnp.int64)
    safe = jnp.where(w == 0, 1, w)
    return (ts.astype(jnp.int64) // safe) * safe, _strict_mask(masks) & (w != 0)


# (field-specific extract registrations are created on demand by
# make_extract below, keyed on the argument's logical type)


# ---------------------------------------------------------------------------
# Temporal extract family (reference: src/expr/src/vector_op/extract.rs)
# ---------------------------------------------------------------------------
# Vectorized civil-date math (Howard Hinnant's algorithm) — pure integer
# ops, fuses into the surrounding jitted step; no host round trip.


def _civil_from_days(days):
    z = days.astype(jnp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y, m, d, doy


def make_extract(field: str, arg: Expr) -> Expr:
    """extract() needs the argument's logical type (date vs timestamp) to
    find the day number — FunctionCall impls only see raw arrays, so the
    binder routes extract through per-(field, type) registered wrappers."""
    field = field.lower()
    t = arg.type
    days_div = 1 if t.kind == TypeKind.DATE else USECS_PER_DAY

    def with_days(fn):
        def impl(datas, masks, out_type):
            days = datas[0].astype(jnp.int64) // days_div
            return fn(days).astype(jnp.int64), masks[0]
        return impl

    def time_part(unit_us, modulo):
        def impl(datas, masks, out_type):
            us = datas[0].astype(jnp.int64)
            return (us % modulo) // unit_us, masks[0]
        return impl

    name = f"__extract_{field}_{t.kind.name.lower()}"
    if name not in _REGISTRY:
        if field == "year":
            impl = with_days(lambda d: _civil_from_days(d)[0])
        elif field == "month":
            impl = with_days(lambda d: _civil_from_days(d)[1])
        elif field == "day":
            impl = with_days(lambda d: _civil_from_days(d)[2])
        elif field == "quarter":
            impl = with_days(lambda d: (_civil_from_days(d)[1] + 2) // 3)
        elif field == "dow":        # Sunday = 0 (PG); 1970-01-01 = Thursday
            impl = with_days(lambda d: (d + 4) % 7)
        elif field == "doy":
            def impl(datas, masks, out_type):
                days = datas[0].astype(jnp.int64) // days_div
                y, m, _, _ = _civil_from_days(days)
                jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
                return days - jan1 + 1, masks[0]
        elif field == "epoch":
            if t.kind == TypeKind.DATE:
                def impl(datas, masks, out_type):
                    return (datas[0].astype(jnp.int64)
                            * (USECS_PER_DAY // USECS_PER_SEC)), masks[0]
            else:
                def impl(datas, masks, out_type):
                    return datas[0].astype(jnp.int64) // USECS_PER_SEC, masks[0]
        elif field == "hour":
            impl = time_part(USECS_PER_HOUR, USECS_PER_DAY)
        elif field == "minute":
            impl = time_part(USECS_PER_MIN, USECS_PER_HOUR)
        elif field == "second":
            impl = time_part(USECS_PER_SEC, USECS_PER_MIN)
        else:
            raise KeyError(f"unsupported EXTRACT field {field!r}")
        _REGISTRY[name] = (impl, _t_int64)
    return call(name, arg)


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (jnp.where(m > 2, m - 3, m + 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


# ---------------------------------------------------------------------------
# String functions over dictionary ids (reference: src/expr/src/vector_op/
# {lower,upper,length,substr,concat_op,like}.rs)
# ---------------------------------------------------------------------------
# VARCHAR columns carry int32 dictionary ids; string *content* lives in the
# host dictionary. These impls compute on the HOST over concrete arrays,
# per UNIQUE id (dictionary-sized work, not row-sized), re-interning
# results — the survey's "varlen strings on device: dictionary-encode at
# ingest, host fallback path for string ops" (SURVEY.md §7). They must
# only run EAGERLY: Project/Filter detect them via ``uses_host_callback``
# and skip jit (some PJRT backends — axon — support no host callbacks at
# all, so pure_callback inside jit is not an option). Inside a trace the
# host transfer below raises TracerArrayConversionError, loudly.


def _lookup_str(i: int) -> str:
    from ..common.types import GLOBAL_STRING_DICT
    try:
        return GLOBAL_STRING_DICT.lookup(int(i))
    except (KeyError, IndexError):
        return ""


def _intern_str(s: str) -> int:
    from ..common.types import GLOBAL_STRING_DICT
    return GLOBAL_STRING_DICT.intern(s)


def _register_str_to_str(name: str, pyfn):
    """pyfn(str, *scalar_args) -> str; first arg is the id column, the rest
    are broadcast numeric columns. Work is per unique argument tuple
    (dictionary-sized), never per row."""
    def impl(datas, masks, out_type):
        import numpy as np
        cols = [np.asarray(d) for d in datas]    # host transfer (eager only)
        stacked = np.stack([c.astype(np.int64) for c in cols], axis=1)
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        results = np.empty(len(uniq), np.int32)
        for u, tup in enumerate(uniq):
            results[u] = _intern_str(
                pyfn(_lookup_str(tup[0]), *(int(v) for v in tup[1:])))
        return jnp.asarray(results[inverse]), _strict_mask(masks)
    _REGISTRY[name] = (impl, lambda ts: T.VARCHAR)


_register_str_to_str("lower", lambda s: s.lower())
_register_str_to_str("upper", lambda s: s.upper())
_register_str_to_str("trim", lambda s: s.strip())
_register_str_to_str("ltrim", lambda s: s.lstrip())
_register_str_to_str("rtrim", lambda s: s.rstrip())
# PG semantics: the window is [start-1, start-1+n) in VIRTUAL positions —
# a start below 1 consumes length before the string begins
def _substr(s, start, n=None):
    if n is None:
        return s[max(start - 1, 0):]
    return s[max(start - 1, 0):max(start - 1 + n, 0)]


_register_str_to_str("substr", _substr)
_register_str_to_str("substring", _substr)


@register("length", _t_int64)
def _length(datas, masks, out_type):
    import numpy as np
    ids = np.asarray(datas[0])
    uniq, inverse = np.unique(ids, return_inverse=True)
    results = np.array([len(_lookup_str(u)) for u in uniq], np.int64)
    return jnp.asarray(results[inverse]), masks[0]


# regexp functions (reference: src/expr/src/vector_op/regexp.rs). Host
# impls over UNIQUE id tuples (dictionary-sized work), compiled patterns
# cached; eager-only like every dictionary-reading function.

import functools as _functools


@_functools.lru_cache(maxsize=256)
def _compile_re(pattern: str, py_flags: int = 0):
    import re
    return re.compile(pattern, py_flags)


def _re_flags(flags: str) -> int:
    # PG flag letters (ref src/expr/src/vector_op/regexp.rs options parse).
    # 'g' is handled by callers (it selects replace-all, not a re flag).
    import re
    f = 0
    for ch in flags:
        if ch == "i":
            f |= re.IGNORECASE
        elif ch in ("n", "m"):     # PG: newline-sensitive matching
            f |= re.MULTILINE
        elif ch == "s":            # PG: '.' matches newline
            f |= re.DOTALL
        elif ch == "x":
            f |= re.VERBOSE
        elif ch in ("c", "g"):     # 'c' = case-sensitive (the default)
            pass
        else:
            raise ValueError(f"invalid regexp flag: {ch!r}")
    return f


def _register_regexp(name: str, pyfn, type_infer):
    def impl(datas, masks, out_type):
        import numpy as np
        cols = [np.asarray(d).astype(np.int64) for d in datas]
        stacked = np.stack(cols, axis=1)
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        results = np.zeros(len(uniq), out_type.np_dtype)
        valid = np.ones(len(uniq), bool)
        for u, tup in enumerate(uniq):
            strs = [_lookup_str(int(i)) for i in tup]
            r = pyfn(*strs)
            if r is None:                      # SQL NULL (e.g. no match)
                valid[u] = False
            else:
                results[u] = _intern_str(r) if out_type.is_string else r
        return (jnp.asarray(results[inverse]),
                _strict_mask(masks) & jnp.asarray(valid[inverse]))
    _REGISTRY[name] = (impl, type_infer)


_register_regexp("regexp_like",
                 lambda s, p: _compile_re(p).search(s) is not None,
                 _t_bool)
_register_regexp("regexp_count",
                 lambda s, p: len(_compile_re(p).findall(s)),
                 _t_int64)
def _pg_replacement_template(r: str) -> str:
    """Translate a PG replacement string to a Python re.sub template by a
    left-to-right escape scan: \\& (whole match) -> \\g<0>, \\1..\\9 kept,
    \\\\ kept as literal backslash, any other escape taken as the literal
    character (Python's template parser would reject e.g. \\g)."""
    out = []
    i = 0
    while i < len(r):
        c = r[i]
        if c == "\\" and i + 1 < len(r):
            n = r[i + 1]
            if n == "&":
                out.append("\\g<0>")
            elif n.isdigit() or n == "\\":
                out.append(c + n)
            else:
                out.append(n)
            i += 2
        elif c == "\\":                   # trailing lone backslash
            out.append("\\\\")
            i += 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _pg_regexp_replace(s, p, r, flags=""):
    # PG semantics (ref src/expr/src/vector_op/regexp.rs): replace only
    # the FIRST match unless the 'g' flag is given; 'i' = case-insensitive.
    count = 0 if "g" in flags else 1
    return _compile_re(p, _re_flags(flags)).sub(
        _pg_replacement_template(r), s, count=count)


def _pg_regexp_match(s, p, flags=""):
    # PG regexp_match returns text[] of captures; until array types exist
    # we return the first capture group when the pattern has groups, else
    # the whole match (closest scalar approximation — divergence documented).
    if "g" in flags:
        raise ValueError(
            "regexp_match does not support the global option")  # as in PG
    m = _compile_re(p, _re_flags(flags)).search(s)
    if m is None:
        return None
    return m.group(1) if m.re.groups else m.group(0)


_register_regexp("regexp_replace", _pg_regexp_replace, lambda ts: T.VARCHAR)
_register_regexp("regexp_match", _pg_regexp_match, lambda ts: T.VARCHAR)


def _register_host_fn(name: str, str_args: tuple, pyfn, type_infer,
                      convert=None):
    """Generic host-tier registration: ``str_args`` marks which positions
    carry dictionary ids (decoded to str); the rest pass as ints. Work is
    per UNIQUE argument tuple over rows whose args are all non-NULL —
    NULL/masked lanes hold dtype sentinels that must never reach pyfn (a
    sentinel 0 position argument would crash split_part, a garbage
    timestamp would overflow to_char). A None result is SQL NULL.
    ``convert(result, out_type)`` maps pyfn's python result to the
    physical scalar (default: intern strings, pass numerics)."""
    if convert is None:
        def convert(r, out_type):
            return _intern_str(r) if out_type.is_string else r

    def impl(datas, masks, out_type):
        import numpy as np
        cols = [np.asarray(d).astype(np.int64) for d in datas]
        in_valid = np.asarray(_strict_mask(masks))
        if in_valid.ndim == 0:
            in_valid = np.full(len(cols[0]), bool(in_valid))
        stacked = np.stack(cols, axis=1)
        stacked[~in_valid] = 0        # collapse masked lanes to one tuple
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        results = np.zeros(len(uniq), out_type.np_dtype)
        valid = np.ones(len(uniq), bool)
        evaluated = np.zeros(len(uniq), bool)
        evaluated[inverse[in_valid]] = True
        for u, tup in enumerate(uniq):
            if not evaluated[u]:
                valid[u] = False
                continue
            args = [_lookup_str(int(v)) if i in str_args else int(v)
                    for i, v in enumerate(tup)]
            r = pyfn(*args)
            if r is None:
                valid[u] = False
            else:
                results[u] = convert(r, out_type)
        return (jnp.asarray(results[inverse]),
                jnp.asarray(in_valid) & jnp.asarray(valid[inverse]))
    _REGISTRY[name] = (impl, type_infer)


def _split_part(s: str, delim: str, n: int):
    # PG split_part: 1-based field index; negative counts from the end;
    # out-of-range yields '' (ref: src/expr/src/vector_op/split_part.rs)
    if n == 0:
        raise ValueError("field position must not be zero")
    parts = s.split(delim) if delim else [s]
    i = n - 1 if n > 0 else len(parts) + n
    return parts[i] if 0 <= i < len(parts) else ""


_register_host_fn("split_part", (0, 1), _split_part, lambda ts: T.VARCHAR)


def _regexp_match_group(s: str, p: str, n: int):
    """(regexp_match(s, p))[n] — 1-based group of the first match, NULL on
    no match / out-of-range. With no capture groups, [1] is the whole
    match (regexp_match then returns a 1-element array in PG)."""
    m = _compile_re(p).search(s)
    if m is None:
        return None
    if m.re.groups == 0:
        return m.group(0) if n == 1 else None
    if 1 <= n <= m.re.groups:
        return m.group(n)
    return None


_register_host_fn("regexp_match_group", (0, 1), _regexp_match_group,
                  lambda ts: T.VARCHAR)


def _array_access(list_id: int, n: int):
    """1-based element access over a list-dictionary id; out-of-range is
    NULL (PG array subscript semantics)."""
    from ..common.types import GLOBAL_LIST_DICT
    elems = GLOBAL_LIST_DICT.lookup(int(list_id))
    return elems[n - 1] if 1 <= n <= len(elems) else None


_register_host_fn("array_access", (), _array_access,
                  lambda ts: ts[0].elem_type)


# JSONB operators (reference: src/expr/src/vector_op/jsonb_access.rs).
# JSONB values are dictionary ids of canonical JSON text; access parses
# per UNIQUE id (dictionary-sized work), results re-canonicalized.

import json as _json


@_functools.lru_cache(maxsize=4096)
def _jsonb_parse(s: str):
    try:
        return _json.loads(s) if s else None
    except ValueError:
        return None


def _jsonb_canon(v) -> str:
    return _json.dumps(v, separators=(",", ":"), sort_keys=True)


_MISSING = object()    # distinguishes an ABSENT key from a JSON null value


def _jsonb_get(j, key):
    if isinstance(j, dict):
        return j.get(key, _MISSING) if isinstance(key, str) else _MISSING
    if isinstance(j, list) and isinstance(key, int):
        return j[key] if -len(j) <= key < len(j) else _MISSING
    return _MISSING


def _jsonb_access(s: str, key, as_text: bool):
    v = _jsonb_get(_jsonb_parse(s), key)
    if v is _MISSING:
        return None                 # absent key → SQL NULL
    if as_text:
        # ->> maps a present JSON null to SQL NULL (PG semantics)
        if v is None:
            return None
        return v if isinstance(v, str) else _jsonb_canon(v)
    return _jsonb_canon(v)          # -> on a null value yields jsonb 'null'


def _register_jsonb(name, key_is_str, as_text, out_infer):
    str_args = (0, 1) if key_is_str else (0,)
    _register_host_fn(
        name, str_args,
        lambda s, k: _jsonb_access(s, k, as_text), out_infer)


def _t_jsonb(ts):
    from ..common.types import JSONB as _J
    return _J


_register_jsonb("jsonb_get_field", True, False, _t_jsonb)
_register_jsonb("jsonb_get_elem", False, False, _t_jsonb)
_register_jsonb("jsonb_get_field_text", True, True, lambda ts: T.VARCHAR)
_register_jsonb("jsonb_get_elem_text", False, True, lambda ts: T.VARCHAR)


def _jsonb_typeof(s: str):
    v = _jsonb_parse(s)
    if s == "null":
        return "null"
    if v is None:
        return None
    return {dict: "object", list: "array", str: "string", bool: "boolean",
            int: "number", float: "number"}.get(type(v))


_register_host_fn("jsonb_typeof", (0,), _jsonb_typeof,
                  lambda ts: T.VARCHAR)


def _jsonb_array_length(s: str):
    v = _jsonb_parse(s)
    return len(v) if isinstance(v, list) else None


_register_host_fn("jsonb_array_length", (0,), _jsonb_array_length,
                  _t_int64)


def _struct_field(sid: int, fi: int):
    """(struct).field — element fi of the interned field tuple; the
    binder sets the out type from the declared field type (reference
    composite access: src/expr/src/expr/expr_field.rs)."""
    from ..common.types import GLOBAL_LIST_DICT
    fields = GLOBAL_LIST_DICT.lookup(sid)
    return fields[fi] if 0 <= fi < len(fields) else None


_register_host_fn("struct_field", (), _struct_field, _t_int64,
                  convert=lambda r, out_type: out_type.to_physical(r))


@register("array_length", _t_int64)
def _array_length(datas, masks, out_type):
    import numpy as np
    from ..common.types import GLOBAL_LIST_DICT
    ids = np.asarray(datas[0])
    uniq, inverse = np.unique(ids, return_inverse=True)
    results = np.array([len(GLOBAL_LIST_DICT.lookup(int(u))) for u in uniq],
                       np.int64)
    return jnp.asarray(results[inverse]), masks[0]


# to_char over timestamps (reference: src/expr/src/vector_op/to_char.rs —
# a Postgres-pattern subset: YYYY/YY/MM/DD/HH24/HH12/HH/MI/SS/MS/AM/PM;
# numeric patterns match case-insensitively as in PG)

_TO_CHAR_PATTERNS = [
    ("YYYY", lambda dt: f"{dt[0]:04d}"),
    ("YY", lambda dt: f"{dt[0] % 100:02d}"),
    ("MM", lambda dt: f"{dt[1]:02d}"),
    ("DD", lambda dt: f"{dt[2]:02d}"),
    ("HH24", lambda dt: f"{dt[3]:02d}"),
    ("HH12", lambda dt: f"{(dt[3] % 12) or 12:02d}"),
    ("HH", lambda dt: f"{(dt[3] % 12) or 12:02d}"),
    ("MI", lambda dt: f"{dt[4]:02d}"),
    ("SS", lambda dt: f"{dt[5]:02d}"),
    ("MS", lambda dt: f"{dt[6] // 1000:03d}"),
    ("AM", lambda dt: "AM" if dt[3] < 12 else "PM"),
    ("PM", lambda dt: "AM" if dt[3] < 12 else "PM"),
]


@_functools.lru_cache(maxsize=64)
def _to_char_compile(fmt: str):
    """fmt -> [literal | pattern-fn] segments, longest pattern first."""
    segs: list = []
    i = 0
    up = fmt.upper()
    while i < len(fmt):
        for pat, fn in _TO_CHAR_PATTERNS:
            if up.startswith(pat, i):
                segs.append(fn)
                i += len(pat)
                break
        else:
            segs.append(fmt[i])
            i += 1
    return segs


def _to_char(us: int, fmt: str) -> str:
    import datetime
    d = datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=us)
    dt = (d.year, d.month, d.day, d.hour, d.minute, d.second, d.microsecond)
    return "".join(seg if isinstance(seg, str) else seg(dt)
                   for seg in _to_char_compile(fmt))


_register_host_fn("to_char", (1,), _to_char, lambda ts: T.VARCHAR)


@register("str_rank", _t_int64)
def _str_rank(datas, masks, out_type):
    """id -> lexicographic rank via the dictionary's rank side table.

    Eager-only (in HOST_CALLBACK_FNS): the table refreshes as strings are
    interned, so it must be fetched fresh per evaluation — baked into a jit
    trace it would go stale and silently mis-order."""
    from ..common.types import GLOBAL_STRING_DICT
    table = GLOBAL_STRING_DICT.device_ranks()
    ids = jnp.clip(datas[0].astype(jnp.int32), 0, table.shape[0] - 1)
    return table[ids], masks[0]


def _str_cmp(fn):
    """String ordering comparison: both ids map through a SINGLE rank-table
    fetch taken after operand evaluation, so in-evaluation interning (a
    literal's first eval, upper()/substr() products) can never put the two
    sides in different rank spaces. Eager-only, like str_rank."""
    def impl(datas, masks, out_type):
        from ..common.types import GLOBAL_STRING_DICT
        table = GLOBAL_STRING_DICT.device_ranks()
        n = table.shape[0]
        a = table[jnp.clip(datas[0].astype(jnp.int32), 0, n - 1)]
        b = table[jnp.clip(datas[1].astype(jnp.int32), 0, n - 1)]
        return fn(a, b), _strict_mask(masks)
    return impl


register("str_less_than", _t_bool)(_str_cmp(jnp.less))
register("str_less_than_or_equal", _t_bool)(_str_cmp(jnp.less_equal))
register("str_greater_than", _t_bool)(_str_cmp(jnp.greater))
register("str_greater_than_or_equal", _t_bool)(_str_cmp(jnp.greater_equal))


@register("concat_op", lambda ts: T.VARCHAR)
def _concat_op(datas, masks, out_type):
    import numpy as np
    a, b = np.asarray(datas[0]), np.asarray(datas[1])
    pairs, inverse = np.unique(np.stack([a, b], axis=1), axis=0,
                               return_inverse=True)
    results = np.array([
        _intern_str(_lookup_str(pa) + _lookup_str(pb)) for pa, pb in pairs],
        np.int32)
    return jnp.asarray(results[inverse]), _strict_mask(masks)


def _like_to_regex(pattern: str) -> "re.Pattern":
    import re
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            # LIKE's default escape: \% and \_ match literally
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _make_like(negated: bool, name: str):
    def impl(datas, masks, out_type):
        import numpy as np
        ids, pat_ids = np.asarray(datas[0]), np.asarray(datas[1])
        pairs, inverse = np.unique(np.stack([ids, pat_ids], axis=1), axis=0,
                                   return_inverse=True)
        rx_cache: dict = {}
        results = np.empty(len(pairs), np.bool_)
        for u, (uid, pid) in enumerate(pairs):
            rx = rx_cache.get(pid)
            if rx is None:
                rx = rx_cache[pid] = _like_to_regex(_lookup_str(pid))
            results[u] = (rx.match(_lookup_str(uid)) is not None) != negated
        return jnp.asarray(results[inverse]), _strict_mask(masks)
    _REGISTRY[name] = (impl, _t_bool)


_make_like(False, "like")
_make_like(True, "not_like")


#: functions implemented via jax.pure_callback — they cannot appear inside
#: a jitted step on backends without host-callback support (axon PJRT);
#: operators check ``uses_host_callback`` and fall back to eager eval
HOST_CALLBACK_FNS = {
    "lower", "upper", "trim", "ltrim", "rtrim", "substr", "substring",
    "length", "concat_op", "like", "not_like",
    "regexp_like", "regexp_count", "regexp_replace", "regexp_match",
    "regexp_match_group", "split_part", "to_char", "array_access",
    "array_length", "struct_field", "jsonb_get_field", "jsonb_get_elem",
    "jsonb_get_field_text", "jsonb_get_elem_text", "jsonb_typeof",
    "jsonb_array_length",
    # not host callbacks, but must run eagerly: they read the live rank table
    "str_rank", "str_less_than", "str_less_than_or_equal",
    "str_greater_than", "str_greater_than_or_equal",
}


def uses_host_callback(e: Expr) -> bool:
    if isinstance(e, FunctionCall):
        return (e.name in HOST_CALLBACK_FNS
                or any(uses_host_callback(a) for a in e.args))
    if isinstance(e, Cast):
        return uses_host_callback(e.arg)
    return False


def eval_many(exprs: Sequence[Expr], chunk: StreamChunk) -> tuple[Column, ...]:
    return tuple(e.eval(chunk) for e in exprs)
