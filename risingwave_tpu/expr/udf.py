"""User-defined scalar functions — registration + expression glue.

Counterpart of the reference's UDF support
(reference: src/udf/src/lib.rs:28 ArrowFlightUdfClient + expr_udf.rs —
external UDF servers over Arrow Flight). Since ISSUE 15 the default is
the same posture: registered functions evaluate OUT OF PROCESS in a
dedicated UDF server (udf/server.py) behind the client plane
(udf/client.py) — per-call deadlines, kill + seeded respawn +
bounded-retry batch replay, generation fencing, bounded in-flight
backpressure — so user code can never wedge an epoch
(docs/robustness.md "UDF isolation plane"). ``[udf] mode = "inproc"``
keeps the old in-process evaluation as the documented degraded mode;
both modes share one evaluator (udf/runtime.py), so results are
bit-exact either way.

This module is only the expression-engine glue: ``register_udf`` /
``drop_udf`` keep their signatures and SQL call sites unchanged; the
registered impl converts device columns to host batches, crosses the
plane, and re-encodes the result (interning returned strings into THIS
process's dictionary). UDFs stay host-callback functions, so the
enclosing Project/Filter runs eagerly. NULL handling is strict: any
NULL argument yields NULL without calling the function.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..common.types import DataType
from ..udf.client import udf_plane
from ..udf.registry import UdfSpec
from .expr import HOST_CALLBACK_FNS, _REGISTRY

#: names registered through register_udf — drop_udf refuses anything else
#: (the host-callback set also contains built-in string functions)
_UDF_NAMES: set = set()


def register_udf(name: str, fn: Callable, arg_types: Sequence[DataType],
                 return_type: DataType, vectorized: bool = False) -> None:
    """Register ``fn`` as a SQL scalar function.

    ``vectorized=False``: fn(*scalar_args) -> scalar, called per visible
    row (logical values: VARCHAR args arrive as str, results re-intern).
    ``vectorized=True``: fn(*numpy_arrays) -> numpy_array over physical
    values (no VARCHAR support).

    Portability is validated HERE (out-of-process mode): a function that
    cannot ship to the server — unmarshalable closure, non-wire type —
    refuses at registration, naming ``[udf] mode = "inproc"``.
    """
    name = name.lower()
    if name in _REGISTRY:
        raise ValueError(f"function {name!r} already exists")
    spec = UdfSpec(name, fn, tuple(arg_types), return_type,
                   bool(vectorized))
    plane = udf_plane()
    plane.register(spec)
    import jax.numpy as jnp

    def impl(datas, masks, out_type):
        data, mask = plane.call(
            name,
            [np.asarray(d) for d in datas],
            [np.asarray(m) for m in masks])
        if return_type.is_string:
            # returned strings intern into THIS process's dictionary
            phys = np.full(len(mask), return_type.null_sentinel(),
                           return_type.np_dtype)
            for i in np.nonzero(mask)[0]:
                phys[i] = return_type.to_physical(data[i])
            data = phys
        return jnp.asarray(data), jnp.asarray(mask)

    _REGISTRY[name] = (impl, lambda ts: return_type)
    HOST_CALLBACK_FNS.add(name)
    _UDF_NAMES.add(name)


def drop_udf(name: str) -> None:
    name = name.lower()
    if name not in _UDF_NAMES:
        raise ValueError(f"{name!r} is not a registered UDF")
    _UDF_NAMES.discard(name)
    HOST_CALLBACK_FNS.discard(name)
    _REGISTRY.pop(name, None)
    udf_plane().drop(name)
