"""User-defined scalar functions (in-process Python).

Counterpart of the reference's UDF support
(reference: src/udf/src/lib.rs:28 ArrowFlightUdfClient + expr_udf.rs —
external Python/Java UDF servers over Arrow Flight). This build runs the
UDF *in process*: the host tier already owns a Python interpreter, so the
Flight hop would add serialization for nothing. The interchange module
(common/interchange.py) provides the Arrow boundary when out-of-process
isolation is wanted later.

UDFs evaluate on the host and are registered as host-callback functions,
so the enclosing Project/Filter runs eagerly (same rule as the string
library — some PJRT backends reject host callbacks inside compiled
programs). NULL handling is strict: any NULL argument yields NULL without
calling the function.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..common.types import DataType
from .expr import HOST_CALLBACK_FNS, _REGISTRY, _strict_mask

#: names registered through register_udf — drop_udf refuses anything else
#: (the host-callback set also contains built-in string functions)
_UDF_NAMES: set = set()


def register_udf(name: str, fn: Callable, arg_types: Sequence[DataType],
                 return_type: DataType, vectorized: bool = False) -> None:
    """Register ``fn`` as a SQL scalar function.

    ``vectorized=False``: fn(*scalar_args) -> scalar, called per visible
    row (logical values: VARCHAR args arrive as str, results re-intern).
    ``vectorized=True``: fn(*numpy_arrays) -> numpy_array over physical
    values (no VARCHAR support).
    """
    name = name.lower()
    if name in _REGISTRY:
        raise ValueError(f"function {name!r} already exists")
    arg_types = list(arg_types)
    import jax.numpy as jnp

    def impl(datas, masks, out_type):
        mask = _strict_mask(masks)
        m = np.asarray(mask)
        if vectorized:
            arrs = [np.asarray(d) for d in datas]
            out = np.asarray(fn(*arrs))
            return jnp.asarray(out.astype(return_type.np_dtype)), mask
        arrs = [np.asarray(d) for d in datas]
        out = np.zeros(len(m), return_type.np_dtype)
        rows = np.nonzero(m)[0]
        for r in rows:
            args = [t.to_python(a[r]) for t, a in zip(arg_types, arrs)]
            v = fn(*args)
            out[r] = (return_type.to_physical(v)
                      if v is not None else return_type.null_sentinel())
            if v is None:
                m[r] = False
        return jnp.asarray(out), jnp.asarray(m)

    _REGISTRY[name] = (impl, lambda ts: return_type)
    HOST_CALLBACK_FNS.add(name)
    _UDF_NAMES.add(name)


def drop_udf(name: str) -> None:
    name = name.lower()
    if name not in _UDF_NAMES:
        raise ValueError(f"{name!r} is not a registered UDF")
    _UDF_NAMES.discard(name)
    HOST_CALLBACK_FNS.discard(name)
    _REGISTRY.pop(name, None)
