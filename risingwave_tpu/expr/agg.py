"""Aggregate function specifications.

Counterpart of the reference's agg impls + registry
(reference: src/expr/src/agg/general.rs, src/expr/src/agg/def.rs). An
``AggSpec`` describes, for one aggregate call, how its device-resident state
is initialised, updated from a signed delta batch, merged, and projected to an
output value. The hash-agg executor scatters these updates into its
device-resident group table (SURVEY.md §7 kernel plan) — the spec itself is
pure jnp and shape-free, so it works for the global (simple agg) case and the
per-group scatter case alike.

Retraction: count/sum handle Delete deltas exactly (subtract). min/max are
exact for append-only inputs; under retraction they keep a best-effort bound
and set ``needs_append_only`` so the planner can insert the reference's
equivalent of MaterializedInput state (src/expr/src/agg — AggStateStorage::
MaterializedInput, stream/src/executor/aggregation/agg_state.rs:34,65) once
that path lands.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..common.types import DataType, FLOAT64, INT64
from ..common.chunk import Column


@dataclasses.dataclass(frozen=True)
class AggCall:
    """A planned aggregate: kind + input column index (-1 for count(*))."""

    kind: str                      # count / sum / min / max / avg / …
    arg: int = -1                  # input column index; -1 => count(*)
    arg_type: Optional[DataType] = None
    distinct: bool = False
    #: constant side argument: string_agg's delimiter,
    #: percentile_cont's fraction (python value, not an expression)
    extra: Optional[object] = None

    #: HLL registers for approx_count_distinct: m=16 → ~26% rel. error,
    #: 16 int64 lanes per group (reference capability:
    #: src/expr/src/agg/approx_count_distinct.rs — register semantics,
    #: TPU-first layout: registers are lanes so the update is the same
    #: scatter-max kernel every other agg uses)
    HLL_M = 16

    @property
    def output_type(self) -> DataType:
        if self.kind in ("count", "approx_count_distinct"):
            return INT64
        if self.kind in ("avg", "percentile_cont"):
            return FLOAT64
        if self.kind == "array_agg":
            from ..common.types import TypeKind
            assert self.arg_type is not None
            return DataType(TypeKind.LIST, elem_kind=self.arg_type.kind)
        if self.kind == "string_agg":
            from ..common.types import VARCHAR
            return VARCHAR
        assert self.arg_type is not None
        return self.arg_type

    @property
    def needs_append_only(self) -> bool:
        # HLL registers are monotone maxima — deletes cannot retract them
        return self.kind in ("min", "max", "approx_count_distinct")

    #: agg kinds that can never be fixed device lanes (ragged multiset
    #: state); always routed to stream/materialized_agg.py
    MATERIALIZED_KINDS = frozenset(
        {"array_agg", "string_agg", "percentile_cont", "mode"})

    @property
    def lanes_unsupported(self) -> bool:
        """True when this call cannot run on the fixed-lane device path at
        all (exact DISTINCT dedup or collecting aggregates). Device
        executors raise on these; the planner routes them to
        MaterializedAggExecutor (reference: AggStateStorage::
        MaterializedInput, distinct dedup tables)."""
        return ((self.distinct and self.kind != "approx_count_distinct")
                or self.kind in self.MATERIALIZED_KINDS)

    @property
    def is_string_minmax(self) -> bool:
        """MIN/MAX over VARCHAR/BYTEA: the lane stores the dictionary *id*
        (stable under dictionary growth), but comparisons happen in packed
        ``rank<<32 | id`` space using the dictionary's lexicographic rank
        table fetched fresh at update time (reference order semantics:
        src/common/src/util/memcmp_encoding.rs). Executors pass ``str_ranks``
        to contributions() and wrap reduces in pack_lane()/unpack_lane()."""
        return (self.kind in ("min", "max") and self.arg_type is not None
                and self.arg_type.is_string)

    # ---- state layout -------------------------------------------------------
    # Every agg state is a fixed number of float64/int64 lanes so the group
    # table can hold all aggs in one [groups, total_lanes] array per dtype.
    # Layout per kind:
    #   count -> 1 int lane (running count)
    #   sum   -> 1 num lane (running sum; int64 for integral, f64 for float)
    #   avg   -> 2 lanes (sum, count)
    #   min   -> 1 num lane (+append-only)
    #   max   -> 1 num lane (+append-only)

    @property
    def num_lanes(self) -> int:
        if self.kind == "avg":
            return 2
        if self.kind == "approx_count_distinct":
            return self.HLL_M
        return 1

    def init_lanes(self):
        """Initial per-lane values (python scalars, cast by the table)."""
        if self.kind in ("min", "max"):
            return [self._minmax_sentinel()]
        return [0.0] * self.num_lanes

    def update(self, lanes, value, vmask, signs):
        """Combine a batch of rows into state lanes via a reduction.

        ``lanes``: current state, list of [G]-or-scalar arrays (one per lane).
        ``value``: the arg column data for the batch rows ([N]).
        ``vmask``: arg non-null & row visible ([N] bool).
        ``signs``: +1 insert / -1 delete / 0 invisible ([N] int32).

        Returns per-row *contributions* (list of [N] arrays) plus a reduce op
        name per lane ('add' | 'min' | 'max') — the caller performs the
        scatter/segment reduction, which is where grouped vs global differ.
        """
        raise NotImplementedError("use contributions() + reduce_ops()")

    def _minmax_sentinel(self):
        """Identity element for min/max lanes; int64 extrema for integral
        and string (dictionary-id) args, ±inf for floats."""
        if self._integral_arg() or self.is_string_minmax:
            big = jnp.iinfo(jnp.int64).max
            return big if self.kind == "min" else -big
        return jnp.inf if self.kind == "min" else -jnp.inf

    def _integral_arg(self) -> bool:
        return self.arg_type is not None and self.arg_type.is_integral

    def contributions(self, value, vmask, signs, str_ranks=None):
        """Per-row contribution arrays, one per lane ([N] each).

        ``str_ranks``: dictionary rank table, required iff
        ``is_string_minmax`` — contributions are then packed
        ``rank<<32 | id`` values comparable by lexicographic order."""
        s = signs
        if self.is_string_minmax:
            if str_ranks is None:
                raise ValueError(
                    "MIN/MAX over VARCHAR requires the dictionary rank "
                    "table (str_ranks)")
            ids = value.astype(jnp.int64)
            rank = str_ranks[jnp.clip(value.astype(jnp.int32), 0,
                                      str_ranks.shape[0] - 1)]
            packed = (rank << 32) | ids
            v = jnp.where(vmask & (s > 0), packed, self._minmax_sentinel())
            return [v]
        if self.kind == "count":
            if self.arg < 0:
                return [s.astype(jnp.int64)]
            return [jnp.where(vmask, s, 0).astype(jnp.int64)]
        if self.kind == "sum":
            v = jnp.where(vmask, value, 0)
            return [(v * s).astype(self.state_dtypes()[0])]
        if self.kind == "avg":
            v = jnp.where(vmask, value, 0).astype(jnp.float64)
            if self.arg_type is not None and self.arg_type.kind.name == "DECIMAL":
                v = v / 10 ** self.arg_type.scale
            return [v * s, jnp.where(vmask, s, 0).astype(jnp.int64)]
        if self.kind in ("min", "max"):
            dt = self.state_dtypes()[0]
            v = jnp.where(vmask & (s > 0), value, self._minmax_sentinel())
            return [v.astype(dt)]
        if self.kind == "approx_count_distinct":
            return self._hll_contributions(value, vmask & (s > 0))
        raise ValueError(self.kind)

    def _hll_contributions(self, value, contributing):
        """HyperLogLog register updates: hash the value, low bits pick a
        register, rho = leading-zero run of the rest + 1; each lane j gets
        max(rho where register==j). All lanes reduce with max."""
        import jax
        from ..common.hashing import _splitmix64
        if value.dtype in (jnp.float32, jnp.float64):
            vi = jax.lax.bitcast_convert_type(
                value.astype(jnp.float64), jnp.int64)
        else:
            vi = value.astype(jnp.int64)
        h = _splitmix64(vi.astype(jnp.uint64))
        m = self.HLL_M
        reg = (h & jnp.uint64(m - 1)).astype(jnp.int32)
        w = h >> jnp.uint64(4)          # top 4 bits now zero
        rho = (jax.lax.clz(w.astype(jnp.int64)) - 4 + 1).astype(jnp.int64)
        out = []
        for j in range(m):
            out.append(jnp.where(contributing & (reg == j), rho, 0))
        return out

    def pack_lane(self, lane, str_ranks):
        """Lift a stored string-minmax lane (ids) into packed comparison
        space before a min/max reduce; identity for every other agg.
        Sentinels pass through unchanged."""
        if not self.is_string_minmax:
            return lane
        sent = self._minmax_sentinel()
        ids = jnp.clip(lane, 0, str_ranks.shape[0] - 1).astype(jnp.int32)
        packed = (str_ranks[ids] << 32) | lane
        return jnp.where(lane == sent, lane, packed)

    def unpack_lane(self, lane):
        """Drop the rank component after a reduce, leaving the stable id."""
        if not self.is_string_minmax:
            return lane
        sent = self._minmax_sentinel()
        return jnp.where(lane == sent, lane, lane & 0xFFFFFFFF)

    def reduce_ops(self) -> list[str]:
        if self.kind == "min":
            return ["min"]
        if self.kind == "max":
            return ["max"]
        if self.kind == "approx_count_distinct":
            return ["max"] * self.HLL_M
        return ["add"] * self.num_lanes

    def state_dtypes(self):
        if self.kind == "count":
            return [jnp.int64]
        if self.kind == "approx_count_distinct":
            return [jnp.int64] * self.HLL_M
        if self.kind == "sum":
            if self.arg_type is not None and self.arg_type.is_float:
                return [jnp.float64]
            return [jnp.int64]
        if self.kind == "avg":
            return [jnp.float64, jnp.int64]
        # min/max: exact int64 lanes for integral/string args, f64 otherwise
        return [jnp.int64 if (self._integral_arg() or self.is_string_minmax)
                else jnp.float64]

    def output(self, lanes, count_nonzero):
        """Project state lanes ([G] arrays) to (data, mask) output columns.

        ``count_nonzero``: [G] bool — group has any live rows (drives group
        liveness, computed by the executor from its row-count lane)."""
        if self.kind == "count":
            return lanes[0], jnp.ones_like(count_nonzero)
        if self.kind == "sum":
            return lanes[0], count_nonzero
        if self.kind == "avg":
            cnt = lanes[1]
            safe = jnp.where(cnt == 0, 1, cnt)
            return lanes[0] / safe, cnt != 0
        if self.kind in ("min", "max"):
            sent = self._minmax_sentinel()
            if self._integral_arg() or self.is_string_minmax:
                valid = lanes[0] != sent
            else:
                valid = jnp.isfinite(lanes[0])
            out = jnp.where(valid, lanes[0], 0)
            return out.astype(self.output_type.dtype), valid
        if self.kind == "approx_count_distinct":
            m = float(self.HLL_M)
            regs = jnp.stack(lanes)                        # [m, G]
            s = jnp.sum(2.0 ** (-regs.astype(jnp.float64)), axis=0)
            raw = 0.673 * m * m / s                        # alpha_16
            zeros = jnp.sum(regs == 0, axis=0)
            small = m * jnp.log(m / jnp.maximum(zeros, 1))
            est = jnp.where((raw <= 2.5 * m) & (zeros > 0), small, raw)
            return (jnp.round(est).astype(jnp.int64),
                    jnp.ones_like(count_nonzero))
        raise ValueError(self.kind)


def count_star() -> AggCall:
    return AggCall("count", -1)


def agg(kind: str, arg: int, arg_type: DataType, distinct: bool = False) -> AggCall:
    return AggCall(kind, arg, arg_type, distinct)
