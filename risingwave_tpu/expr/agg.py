"""Aggregate function specifications.

Counterpart of the reference's agg impls + registry
(reference: src/expr/src/agg/general.rs, src/expr/src/agg/def.rs). An
``AggSpec`` describes, for one aggregate call, how its device-resident state
is initialised, updated from a signed delta batch, merged, and projected to an
output value. The hash-agg executor scatters these updates into its
device-resident group table (SURVEY.md §7 kernel plan) — the spec itself is
pure jnp and shape-free, so it works for the global (simple agg) case and the
per-group scatter case alike.

Retraction: count/sum handle Delete deltas exactly (subtract). min/max are
exact for append-only inputs; under retraction they keep a best-effort bound
and set ``needs_append_only`` so the planner can insert the reference's
equivalent of MaterializedInput state (src/expr/src/agg — AggStateStorage::
MaterializedInput, stream/src/executor/aggregation/agg_state.rs:34,65) once
that path lands.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..common.types import DataType, FLOAT64, INT64
from ..common.chunk import Column


@dataclasses.dataclass(frozen=True)
class AggCall:
    """A planned aggregate: kind + input column index (-1 for count(*))."""

    kind: str                      # count / sum / min / max / avg
    arg: int = -1                  # input column index; -1 => count(*)
    arg_type: Optional[DataType] = None
    distinct: bool = False

    @property
    def output_type(self) -> DataType:
        if self.kind == "count":
            return INT64
        if self.kind == "avg":
            return FLOAT64
        assert self.arg_type is not None
        return self.arg_type

    @property
    def needs_append_only(self) -> bool:
        return self.kind in ("min", "max")

    # ---- state layout -------------------------------------------------------
    # Every agg state is a fixed number of float64/int64 lanes so the group
    # table can hold all aggs in one [groups, total_lanes] array per dtype.
    # Layout per kind:
    #   count -> 1 int lane (running count)
    #   sum   -> 1 num lane (running sum; int64 for integral, f64 for float)
    #   avg   -> 2 lanes (sum, count)
    #   min   -> 1 num lane (+append-only)
    #   max   -> 1 num lane (+append-only)

    @property
    def num_lanes(self) -> int:
        return 2 if self.kind == "avg" else 1

    def init_lanes(self):
        """Initial per-lane values (python scalars, cast by the table)."""
        if self.kind in ("min", "max"):
            return [self._minmax_sentinel()]
        return [0.0] * self.num_lanes

    def update(self, lanes, value, vmask, signs):
        """Combine a batch of rows into state lanes via a reduction.

        ``lanes``: current state, list of [G]-or-scalar arrays (one per lane).
        ``value``: the arg column data for the batch rows ([N]).
        ``vmask``: arg non-null & row visible ([N] bool).
        ``signs``: +1 insert / -1 delete / 0 invisible ([N] int32).

        Returns per-row *contributions* (list of [N] arrays) plus a reduce op
        name per lane ('add' | 'min' | 'max') — the caller performs the
        scatter/segment reduction, which is where grouped vs global differ.
        """
        raise NotImplementedError("use contributions() + reduce_ops()")

    def _minmax_sentinel(self):
        """Identity element for min/max lanes; int64 extrema for integral
        args (exact for full-range ids/timestamps), ±inf for floats."""
        if self._integral_arg():
            big = jnp.iinfo(jnp.int64).max
            return big if self.kind == "min" else -big
        return jnp.inf if self.kind == "min" else -jnp.inf

    def _integral_arg(self) -> bool:
        return self.arg_type is not None and self.arg_type.is_integral

    def contributions(self, value, vmask, signs):
        """Per-row contribution arrays, one per lane ([N] each)."""
        s = signs
        if self.kind == "count":
            if self.arg < 0:
                return [s.astype(jnp.int64)]
            return [jnp.where(vmask, s, 0).astype(jnp.int64)]
        if self.kind == "sum":
            v = jnp.where(vmask, value, 0)
            return [(v * s).astype(self.state_dtypes()[0])]
        if self.kind == "avg":
            v = jnp.where(vmask, value, 0).astype(jnp.float64)
            if self.arg_type is not None and self.arg_type.kind.name == "DECIMAL":
                v = v / 10 ** self.arg_type.scale
            return [v * s, jnp.where(vmask, s, 0).astype(jnp.int64)]
        if self.kind in ("min", "max"):
            dt = self.state_dtypes()[0]
            v = jnp.where(vmask & (s > 0), value, self._minmax_sentinel())
            return [v.astype(dt)]
        raise ValueError(self.kind)

    def reduce_ops(self) -> list[str]:
        if self.kind == "min":
            return ["min"]
        if self.kind == "max":
            return ["max"]
        return ["add"] * self.num_lanes

    def state_dtypes(self):
        if self.kind == "count":
            return [jnp.int64]
        if self.kind == "sum":
            if self.arg_type is not None and self.arg_type.is_float:
                return [jnp.float64]
            return [jnp.int64]
        if self.kind == "avg":
            return [jnp.float64, jnp.int64]
        # min/max: exact int64 lanes for integral args, f64 otherwise
        return [jnp.int64 if self._integral_arg() else jnp.float64]

    def output(self, lanes, count_nonzero):
        """Project state lanes ([G] arrays) to (data, mask) output columns.

        ``count_nonzero``: [G] bool — group has any live rows (drives group
        liveness, computed by the executor from its row-count lane)."""
        if self.kind == "count":
            return lanes[0], jnp.ones_like(count_nonzero)
        if self.kind == "sum":
            return lanes[0], count_nonzero
        if self.kind == "avg":
            cnt = lanes[1]
            safe = jnp.where(cnt == 0, 1, cnt)
            return lanes[0] / safe, cnt != 0
        if self.kind in ("min", "max"):
            sent = self._minmax_sentinel()
            if self._integral_arg():
                valid = lanes[0] != sent
            else:
                valid = jnp.isfinite(lanes[0])
            out = jnp.where(valid, lanes[0], 0)
            return out.astype(self.output_type.dtype), valid
        raise ValueError(self.kind)


def count_star() -> AggCall:
    return AggCall("count", -1)


def agg(kind: str, arg: int, arg_type: DataType, distinct: bool = False) -> AggCall:
    return AggCall(kind, arg, arg_type, distinct)
