"""MetaServer: the control plane as its own process.

The paper's layer map makes the meta node a distinct role — "cluster
brain: catalog, barrier injection bookkeeping, Hummock version
management, scheduling" (reference: src/meta/src/rpc/server.rs). Until
now our ``MetaService`` was an object living *inside* the session, so a
second frontend could never attach. This module lifts the exact same
surface behind the ``rpc/wire.py`` frame protocol:

* **request/reply** — each connection carries sequential
  ``{"method", "params"}`` frames answered by ``{"ok", "result"}`` or
  ``{"ok": false, "error", "message"}``. The method names mirror
  ``MetaService``/``MetaStore`` one-to-one so ``MetaClient`` can be a
  drop-in for the in-process service.
* **subscription push** — a connection that sends ``subscribe`` is
  switched into one-way push mode: the server replays the notification
  log from the requested version, then streams every subsequent
  ``notify`` as its own frame. This is the reference's
  ``NotificationService`` observer stream (meta/src/rpc/server.rs +
  notification.rs) — readers learn about DDL, checkpoints, and system
  params without polling.
* **leader lease** — a TTL lease with monotonic *terms*
  (docs/control-plane.md "The election protocol"). The persisted store
  key (``leader``) holds ``{"session", "term", "acquired_at", "reason"}``;
  ``lease.acquire`` is a CAS that admits only a strictly newer term (or
  the same ``(session, term)`` re-arming itself) — a racing candidate
  loses with a typed ``lease_lost`` error, never a retryable conflict.
  ``lease.renew`` heartbeats extend the deadline, which lives in server
  memory only (a meta restart re-arms one fresh TTL — renewals must not
  consume durable-store IO or the chaos plane's deterministic frame
  stream). The loop thread runs an expiry detector: a lease past its
  deadline pushes one ``leader_down`` notification so standbys can race
  ``lease.acquire`` for term+1. *Fencing* stays server-side: barrier /
  checkpoint publishes carrying a stale term are refused, so an
  ex-writer that lost the lease can neither conduct nor commit.
* **remote pin registry** — serving sessions report the SST runs their
  pinned snapshots reference; the union is pushed on the
  ``hummock_pins`` channel so the writer's vacuum can treat remote
  readers like local pins (storage safety rule: an object may be
  deleted iff no version, pin, or in-flight task references it).

The server is runnable two ways: in-thread (``MetaServer.start()`` —
tests, playground composition) and as a standalone process
(``python -m risingwave_tpu.meta.server`` / ``ctl meta serve``). State
durability is exactly the MetaService's: a ``FileMetaStore`` JSONL under
``data_dir`` when one is given, so kill -9 + restart resumes catalog,
placements, and the leader lease; the notification log is in-memory and
dies with the process — reconnecting clients must full-resync, which
``MetaClient`` does.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
import time
from typing import Any, Dict, Optional, Set

from ..rpc.wire import pack_frame, read_frame
from .service import MetaService
from .store import TxnConflict

#: store key holding the writer lease (persisted: fencing survives a
#: meta restart on the same data dir)
LEADER_KEY = "leader"
#: persisted acquisition history (term, holder, acquired_at, reason) —
#: the rw_leader_history catalog relation and `ctl meta leader` read it
LEADER_HISTORY_KEY = "leader_history"
#: persisted count of elections (acquisitions over an EXPIRED lease)
LEADER_FAILOVERS_KEY = "leader_failovers"
LEADER_HISTORY_CAP = 64

#: default TTL: a writer that misses this many seconds of heartbeats is
#: declared down ([meta] lease_ttl_s overrides; heartbeats default to
#: lease_ttl_s / 4 client-side)
DEFAULT_LEASE_TTL_S = 2.0


class MetaServer:
    """Serve one ``MetaService`` over wire frames.

    All request handling runs on the asyncio loop thread, so the
    underlying ``MetaService`` needs no extra locking: frames on one
    connection are sequential, and frames across connections are
    serialized by the loop.
    """

    def __init__(self, data_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 lease_ttl_s: float = DEFAULT_LEASE_TTL_S):
        self.service = MetaService(data_dir=data_dir)
        self._host = host
        self._port = port
        self.addr: Optional[str] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        # conn-id -> set of SST names its pinned snapshots reference
        self._remote_pins: Dict[int, Set[str]] = {}
        self._conn_ids = iter(range(1, 1 << 62))
        self.stats = {"connections": 0, "requests": 0, "subscribers": 0,
                      "fenced_rejections": 0, "lease_renews": 0,
                      "leader_expiries": 0}
        # TTL lease state. The deadline is deliberately MEMORY-only:
        # persisting every renewal would fsync the JSONL store several
        # times a second AND feed wall-clock-driven events into the
        # chaos plane's deterministic meta-IO stream. A restarted meta
        # re-arms one fresh TTL for whatever holder the store records —
        # the holder's next heartbeat confirms it, or expiry elects.
        self.lease_ttl_s = float(lease_ttl_s)
        self._lease_deadline: Optional[float] = None
        # term whose leader_down has already been pushed (one
        # notification per expiry, not one per detector sweep)
        self._down_term: Optional[int] = None
        if self.service.store.get(LEADER_KEY) is not None:
            self._lease_deadline = time.time() + self.lease_ttl_s

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> str:
        """Start serving on a daemon thread; returns ``host:port``."""
        self._thread = threading.Thread(
            target=self._run, name="meta-server", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("MetaServer failed to start")
        assert self.addr is not None
        return self.addr

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._open())
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(self._close())
            loop.close()

    async def _open(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, self._port)
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"
        # TTL expiry detector on the SAME loop thread as request
        # handling — the single-threaded MetaService invariant holds
        self._expiry_task = self._loop.create_task(self._expiry_loop())

    async def _close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in asyncio.all_tasks(self._loop):
            if task is not asyncio.current_task():
                task.cancel()
        await asyncio.sleep(0)
        close = getattr(self.service.store, "close", None)
        if close is not None:
            close()

    def stop(self) -> None:
        if self._loop is None:
            return
        loop = self._loop
        if loop.is_running():
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._loop = None

    # -- connection handling ---------------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        conn_id = next(self._conn_ids)
        self.stats["connections"] += 1
        observer = None
        try:
            while True:
                req = await read_frame(reader)
                if req is None:
                    break
                self.stats["requests"] += 1
                method = req.get("method", "")
                params = req.get("params") or {}
                if method == "subscribe":
                    observer = await self._subscribe(writer, params)
                    continue
                try:
                    result = self._dispatch(conn_id, method, params)
                    reply = {"ok": True, "result": result}
                except LeaseLost as e:
                    reply = {"ok": False, "error": "lease_lost",
                             "message": str(e)}
                except TxnConflict as e:
                    reply = {"ok": False, "error": "txn_conflict",
                             "message": str(e)}
                except Fenced as e:
                    self.stats["fenced_rejections"] += 1
                    reply = {"ok": False, "error": "fenced",
                             "message": str(e)}
                except Exception as e:  # surface, don't kill the conn
                    reply = {"ok": False, "error": "internal",
                             "message": f"{type(e).__name__}: {e}"}
                writer.write(pack_frame(reply))
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            if observer is not None:
                self.service.notifications.unsubscribe_all(observer)
                self.stats["subscribers"] -= 1
            if self._remote_pins.pop(conn_id, None) is not None:
                self._notify_pins()
            writer.close()

    async def _subscribe(self, writer: asyncio.StreamWriter,
                         params: dict):
        """Switch this connection into push mode: replay from
        ``from_version`` then stream live notifications. Pushes are
        fire-and-forget writes from the loop thread — a slow subscriber
        buffers in its transport, a dead one is dropped on write error."""
        from_version = int(params.get("from_version", 0))

        def push(version: int, channel: str, info: Any) -> None:
            try:
                writer.write(pack_frame({"channel": channel, "info": info,
                                         "version": version}))
            except Exception:
                pass

        # subscribe to every channel: the client-side relay fans out
        self.service.notifications.subscribe_all(
            push, from_version=from_version)
        self.stats["subscribers"] += 1
        await writer.drain()
        return push

    # -- request dispatch ------------------------------------------------------

    def _dispatch(self, conn_id: int, method: str, p: dict) -> Any:
        svc = self.service
        store = svc.store
        if method == "ping":
            return {"version": svc.notifications.current_version}
        # meta store surface
        if method == "store.get":
            return store.get(p["key"])
        if method == "store.put":
            store.put(p["key"], p["value"])
            return None
        if method == "store.delete":
            store.delete(p["key"])
            return None
        if method == "store.list_prefix":
            return [[k, v] for k, v in store.list_prefix(p["prefix"])]
        if method == "store.txn":
            pre = [(k, v) for k, v in p.get("preconditions", [])]
            ops = [tuple(op) for op in p.get("ops", [])]
            store.txn(preconditions=pre, ops=ops)
            return None
        # notification hub
        if method == "notify":
            return svc.notifications.notify(p["channel"], p["info"])
        if method == "current_version":
            return svc.notifications.current_version
        # job worker registry
        if method == "register_job":
            w = svc.register_job(p["name"])
            return w.worker_id
        if method == "deregister_job":
            svc.deregister_job(p["name"])
            return None
        if method == "job_heartbeat":
            svc.job_heartbeat(p["name"])
            return None
        if method == "sync_jobs":
            svc.sync_jobs(p["names"])
            return None
        if method == "advance_epoch_clock":
            svc.advance_epoch_clock(p["epoch"])
            return None
        if method == "check_job_failures":
            return svc.check_job_failures()
        if method == "register_compute":
            svc.register_compute(p["worker_id"], p["host"], p["port"],
                                 p.get("parallelism", 1))
            return None
        # fragment placement
        if method == "save_placement":
            from .fragment import FragmentPlacement
            svc.save_placement(FragmentPlacement.from_json(p["placement"]))
            return None
        if method == "load_placement":
            placement = svc.load_placement(p["job"])
            return None if placement is None else placement.to_json()
        if method == "drop_placement":
            svc.drop_placement(p["job"])
            return None
        if method == "all_placements":
            return {job: pl.to_json()
                    for job, pl in svc.all_placements().items()}
        # barrier conduction (fenced: only the current leader publishes)
        if method == "publish_barrier":
            self._check_fence(p)
            svc.publish_barrier(p["epoch"], p["checkpoint"],
                                term=p.get("generation"))
            return None
        if method == "publish_checkpoint":
            self._check_fence(p)
            svc.publish_checkpoint(p["committed_epoch"],
                                   term=p.get("generation"))
            return None
        # leader lease (TTL + term-fenced election)
        if method == "lease.acquire":
            return self._lease_acquire(p)
        if method == "lease.renew":
            return self._lease_renew(p)
        if method == "lease.info":
            return self._lease_info()
        if method == "lease.assert":
            self._check_fence(p)
            return True
        # remote pin registry (vacuum safety for reader snapshots)
        if method == "pins.report":
            self._remote_pins[conn_id] = set(p["ssts"])
            self._notify_pins()
            return None
        if method == "pins.union":
            return sorted(self._pins_union())
        raise ValueError(f"unknown meta method: {method}")

    def _check_fence(self, p: dict) -> None:
        raw = self.service.store.get(LEADER_KEY)
        if raw is None:
            return
        holder = json.loads(raw)
        h_term = int(holder.get("term", holder.get("generation", 0)))
        term = p.get("generation", p.get("term"))
        if term is not None and int(term) != h_term:
            raise Fenced(
                f"term {term} fenced by leader "
                f"{holder['session']} term {h_term}")

    # -- TTL leader lease ------------------------------------------------------

    def _lease_acquire(self, p: dict) -> int:
        """CAS on the lease record. Admits a strictly newer term (a new
        writer attaching, or an election winner at down-term + 1) or the
        holder itself re-arming; every other claimant gets the typed
        ``LeaseLost`` — NEVER a retryable conflict, because a replayed
        acquire after a competitor won would be a split brain."""
        now = time.time()
        session = p["session"]
        term = int(p.get("term", p.get("generation")))
        store = self.service.store
        raw = store.get(LEADER_KEY)
        reason = str(p.get("reason") or "bootstrap")
        leaderless_s = None
        if raw is not None:
            holder = json.loads(raw)
            h_term = int(holder.get("term", holder.get("generation", 0)))
            expired = (self._lease_deadline is not None
                       and now >= self._lease_deadline)
            if holder.get("session") == session and term == h_term:
                # the holder re-asserting its own lease: re-arm only
                self._lease_deadline = now + self.lease_ttl_s
                self._down_term = None
                return term
            if term <= h_term:
                raise LeaseLost(
                    f"lease.acquire term {term} refused: "
                    f"{holder.get('session')} holds term {h_term}"
                    + (" (expired)" if expired else " (live)"))
            if p.get("reason") is None:
                reason = "election" if expired else "takeover"
            if expired and self._lease_deadline is not None:
                leaderless_s = now - self._lease_deadline
        record = {"session": session, "term": term, "generation": term,
                  "acquired_at": now, "reason": reason}
        try:
            store.txn(preconditions=[(LEADER_KEY, raw)],
                      ops=[("put", LEADER_KEY,
                            json.dumps(record, sort_keys=True))])
        except TxnConflict as e:
            # the CAS itself lost (a durable-IO race under chaos): a
            # competitor moved the record between read and write
            raise LeaseLost(f"lease.acquire CAS lost: {e}") from e
        self._lease_deadline = now + self.lease_ttl_s
        self._down_term = None
        if reason == "election":
            n = int(store.get(LEADER_FAILOVERS_KEY) or "0") + 1
            store.put(LEADER_FAILOVERS_KEY, str(n))
        entry = {"term": term, "holder": session, "acquired_at": now,
                 "reason": reason}
        if leaderless_s is not None:
            entry["leaderless_s"] = round(leaderless_s, 3)
        hist = json.loads(store.get(LEADER_HISTORY_KEY) or "[]")
        hist.append(entry)
        store.put(LEADER_HISTORY_KEY,
                  json.dumps(hist[-LEADER_HISTORY_CAP:]))
        self.service.notifications.notify("leader", {
            "session": session, "generation": term, "term": term,
            "deadline": self._lease_deadline, "reason": reason})
        return term

    def _lease_renew(self, p: dict) -> float:
        """Heartbeat: extend the holder's deadline. Wire + memory only —
        no store IO (see __init__). A renewal under a superseded or
        vanished lease is ``LeaseLost``: the heartbeat thread must stop,
        not retry."""
        session = p["session"]
        term = int(p.get("term", p.get("generation")))
        raw = self.service.store.get(LEADER_KEY)
        if raw is None:
            raise LeaseLost(f"lease.renew term {term}: no lease held")
        holder = json.loads(raw)
        h_term = int(holder.get("term", holder.get("generation", 0)))
        if holder.get("session") != session or h_term != term:
            raise LeaseLost(
                f"lease.renew for {session} term {term} refused: "
                f"{holder.get('session')} holds term {h_term}")
        self._lease_deadline = time.time() + self.lease_ttl_s
        if self._down_term == term:
            # the holder came back before any candidate won: revive (a
            # successor, if one is mid-election, still fences by term)
            self._down_term = None
        self.stats["lease_renews"] += 1
        return self._lease_deadline

    def _lease_info(self) -> dict:
        store = self.service.store
        now = time.time()
        info: Dict[str, Any] = {
            "holder": None, "term": None, "acquired_at": None,
            "reason": None, "lease_ttl_s": self.lease_ttl_s,
            "ttl_remaining_s": None, "expired": None,
            "failovers": int(store.get(LEADER_FAILOVERS_KEY) or "0"),
            "history": json.loads(store.get(LEADER_HISTORY_KEY) or "[]"),
        }
        raw = store.get(LEADER_KEY)
        if raw is not None:
            holder = json.loads(raw)
            info["holder"] = holder.get("session")
            info["term"] = int(holder.get("term",
                                          holder.get("generation", 0)))
            info["acquired_at"] = holder.get("acquired_at")
            info["reason"] = holder.get("reason")
            if self._lease_deadline is not None:
                info["ttl_remaining_s"] = round(
                    self._lease_deadline - now, 3)
                info["expired"] = now >= self._lease_deadline
        return info

    async def _expiry_loop(self) -> None:
        """Detect a holder that stopped heartbeating and push ONE
        ``leader_down`` so standbys race ``lease.acquire`` at term+1."""
        interval = max(0.02, min(self.lease_ttl_s / 4.0, 0.25))
        while True:
            await asyncio.sleep(interval)
            try:
                self._check_lease_expiry()
            except Exception:  # noqa: BLE001 - detector must outlive IO
                pass

    def _check_lease_expiry(self) -> None:
        raw = self.service.store.get(LEADER_KEY)
        if raw is None or self._lease_deadline is None:
            return
        now = time.time()
        if now < self._lease_deadline:
            return
        holder = json.loads(raw)
        term = int(holder.get("term", holder.get("generation", 0)))
        if self._down_term == term:
            return
        self._down_term = term
        self.stats["leader_expiries"] += 1
        self.service.notifications.notify("leader_down", {
            "session": holder.get("session"), "term": term,
            "generation": term, "deadline": self._lease_deadline,
            "detected_at": now})

    def _pins_union(self) -> Set[str]:
        out: Set[str] = set()
        for ssts in self._remote_pins.values():
            out.update(ssts)
        return out

    def _notify_pins(self) -> None:
        self.service.notifications.notify(
            "hummock_pins", {"ssts": sorted(self._pins_union())})


class Fenced(RuntimeError):
    """A stale writer tried to publish under a lost lease."""


class LeaseLost(RuntimeError):
    """A lease acquire/renew was refused: a competitor holds (or won)
    the lease. Typed distinctly from ``TxnConflict`` because the caller
    must NOT retry — a replayed acquire after a competitor won would be
    a split brain."""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="risingwave-meta",
        description="Serve the meta control plane over wire frames.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default=None,
                    help="durable meta store directory (JSONL)")
    ap.add_argument("--lease-ttl", type=float, default=DEFAULT_LEASE_TTL_S,
                    help="leader lease TTL in seconds (default %(default)s)")
    args = ap.parse_args(argv)
    server = MetaServer(data_dir=args.data_dir, host=args.host,
                        port=args.port, lease_ttl_s=args.lease_ttl)
    addr = server.start()
    # machine-readable readiness line: subprocess drivers parse this
    print(f"META_READY {addr}", flush=True)
    try:
        assert server._thread is not None
        while server._thread.is_alive():
            server._thread.join(timeout=1.0)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
