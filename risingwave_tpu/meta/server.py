"""MetaServer: the control plane as its own process.

The paper's layer map makes the meta node a distinct role — "cluster
brain: catalog, barrier injection bookkeeping, Hummock version
management, scheduling" (reference: src/meta/src/rpc/server.rs). Until
now our ``MetaService`` was an object living *inside* the session, so a
second frontend could never attach. This module lifts the exact same
surface behind the ``rpc/wire.py`` frame protocol:

* **request/reply** — each connection carries sequential
  ``{"method", "params"}`` frames answered by ``{"ok", "result"}`` or
  ``{"ok": false, "error", "message"}``. The method names mirror
  ``MetaService``/``MetaStore`` one-to-one so ``MetaClient`` can be a
  drop-in for the in-process service.
* **subscription push** — a connection that sends ``subscribe`` is
  switched into one-way push mode: the server replays the notification
  log from the requested version, then streams every subsequent
  ``notify`` as its own frame. This is the reference's
  ``NotificationService`` observer stream (meta/src/rpc/server.rs +
  notification.rs) — readers learn about DDL, checkpoints, and system
  params without polling.
* **leader lease** — a single persisted store key (``leader``) holding
  ``{"session", "generation"}``. Acquisition is last-writer-wins (no
  election — the single-leader assumption is documented in
  docs/control-plane.md); *fencing* is enforced server-side: barrier /
  checkpoint publishes carrying a stale generation are refused, so an
  ex-writer that lost the lease can neither conduct nor commit.
* **remote pin registry** — serving sessions report the SST runs their
  pinned snapshots reference; the union is pushed on the
  ``hummock_pins`` channel so the writer's vacuum can treat remote
  readers like local pins (storage safety rule: an object may be
  deleted iff no version, pin, or in-flight task references it).

The server is runnable two ways: in-thread (``MetaServer.start()`` —
tests, playground composition) and as a standalone process
(``python -m risingwave_tpu.meta.server`` / ``ctl meta serve``). State
durability is exactly the MetaService's: a ``FileMetaStore`` JSONL under
``data_dir`` when one is given, so kill -9 + restart resumes catalog,
placements, and the leader lease; the notification log is in-memory and
dies with the process — reconnecting clients must full-resync, which
``MetaClient`` does.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
from typing import Any, Dict, Optional, Set

from ..rpc.wire import pack_frame, read_frame
from .service import MetaService
from .store import TxnConflict

#: store key holding the writer lease (persisted: fencing survives a
#: meta restart on the same data dir)
LEADER_KEY = "leader"


class MetaServer:
    """Serve one ``MetaService`` over wire frames.

    All request handling runs on the asyncio loop thread, so the
    underlying ``MetaService`` needs no extra locking: frames on one
    connection are sequential, and frames across connections are
    serialized by the loop.
    """

    def __init__(self, data_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.service = MetaService(data_dir=data_dir)
        self._host = host
        self._port = port
        self.addr: Optional[str] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        # conn-id -> set of SST names its pinned snapshots reference
        self._remote_pins: Dict[int, Set[str]] = {}
        self._conn_ids = iter(range(1, 1 << 62))
        self.stats = {"connections": 0, "requests": 0, "subscribers": 0,
                      "fenced_rejections": 0}

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> str:
        """Start serving on a daemon thread; returns ``host:port``."""
        self._thread = threading.Thread(
            target=self._run, name="meta-server", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("MetaServer failed to start")
        assert self.addr is not None
        return self.addr

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._open())
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(self._close())
            loop.close()

    async def _open(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self._host, self._port)
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"

    async def _close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in asyncio.all_tasks(self._loop):
            if task is not asyncio.current_task():
                task.cancel()
        await asyncio.sleep(0)
        close = getattr(self.service.store, "close", None)
        if close is not None:
            close()

    def stop(self) -> None:
        if self._loop is None:
            return
        loop = self._loop
        if loop.is_running():
            loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._loop = None

    # -- connection handling ---------------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        conn_id = next(self._conn_ids)
        self.stats["connections"] += 1
        observer = None
        try:
            while True:
                req = await read_frame(reader)
                if req is None:
                    break
                self.stats["requests"] += 1
                method = req.get("method", "")
                params = req.get("params") or {}
                if method == "subscribe":
                    observer = await self._subscribe(writer, params)
                    continue
                try:
                    result = self._dispatch(conn_id, method, params)
                    reply = {"ok": True, "result": result}
                except TxnConflict as e:
                    reply = {"ok": False, "error": "txn_conflict",
                             "message": str(e)}
                except Fenced as e:
                    self.stats["fenced_rejections"] += 1
                    reply = {"ok": False, "error": "fenced",
                             "message": str(e)}
                except Exception as e:  # surface, don't kill the conn
                    reply = {"ok": False, "error": "internal",
                             "message": f"{type(e).__name__}: {e}"}
                writer.write(pack_frame(reply))
                await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            if observer is not None:
                self.service.notifications.unsubscribe_all(observer)
                self.stats["subscribers"] -= 1
            if self._remote_pins.pop(conn_id, None) is not None:
                self._notify_pins()
            writer.close()

    async def _subscribe(self, writer: asyncio.StreamWriter,
                         params: dict):
        """Switch this connection into push mode: replay from
        ``from_version`` then stream live notifications. Pushes are
        fire-and-forget writes from the loop thread — a slow subscriber
        buffers in its transport, a dead one is dropped on write error."""
        from_version = int(params.get("from_version", 0))

        def push(version: int, channel: str, info: Any) -> None:
            try:
                writer.write(pack_frame({"channel": channel, "info": info,
                                         "version": version}))
            except Exception:
                pass

        # subscribe to every channel: the client-side relay fans out
        self.service.notifications.subscribe_all(
            push, from_version=from_version)
        self.stats["subscribers"] += 1
        await writer.drain()
        return push

    # -- request dispatch ------------------------------------------------------

    def _dispatch(self, conn_id: int, method: str, p: dict) -> Any:
        svc = self.service
        store = svc.store
        if method == "ping":
            return {"version": svc.notifications.current_version}
        # meta store surface
        if method == "store.get":
            return store.get(p["key"])
        if method == "store.put":
            store.put(p["key"], p["value"])
            return None
        if method == "store.delete":
            store.delete(p["key"])
            return None
        if method == "store.list_prefix":
            return [[k, v] for k, v in store.list_prefix(p["prefix"])]
        if method == "store.txn":
            pre = [(k, v) for k, v in p.get("preconditions", [])]
            ops = [tuple(op) for op in p.get("ops", [])]
            store.txn(preconditions=pre, ops=ops)
            return None
        # notification hub
        if method == "notify":
            return svc.notifications.notify(p["channel"], p["info"])
        if method == "current_version":
            return svc.notifications.current_version
        # job worker registry
        if method == "register_job":
            w = svc.register_job(p["name"])
            return w.worker_id
        if method == "deregister_job":
            svc.deregister_job(p["name"])
            return None
        if method == "job_heartbeat":
            svc.job_heartbeat(p["name"])
            return None
        if method == "sync_jobs":
            svc.sync_jobs(p["names"])
            return None
        if method == "advance_epoch_clock":
            svc.advance_epoch_clock(p["epoch"])
            return None
        if method == "check_job_failures":
            return svc.check_job_failures()
        if method == "register_compute":
            svc.register_compute(p["worker_id"], p["host"], p["port"],
                                 p.get("parallelism", 1))
            return None
        # fragment placement
        if method == "save_placement":
            from .fragment import FragmentPlacement
            svc.save_placement(FragmentPlacement.from_json(p["placement"]))
            return None
        if method == "load_placement":
            placement = svc.load_placement(p["job"])
            return None if placement is None else placement.to_json()
        if method == "drop_placement":
            svc.drop_placement(p["job"])
            return None
        if method == "all_placements":
            return {job: pl.to_json()
                    for job, pl in svc.all_placements().items()}
        # barrier conduction (fenced: only the current leader publishes)
        if method == "publish_barrier":
            self._check_fence(p)
            svc.publish_barrier(p["epoch"], p["checkpoint"])
            return None
        if method == "publish_checkpoint":
            self._check_fence(p)
            svc.publish_checkpoint(p["committed_epoch"])
            return None
        # leader lease
        if method == "lease.acquire":
            store.put(LEADER_KEY, json.dumps(
                {"session": p["session"], "generation": p["generation"]}))
            svc.notifications.notify(
                "leader", {"session": p["session"],
                           "generation": p["generation"]})
            return p["generation"]
        if method == "lease.assert":
            self._check_fence(p)
            return True
        # remote pin registry (vacuum safety for reader snapshots)
        if method == "pins.report":
            self._remote_pins[conn_id] = set(p["ssts"])
            self._notify_pins()
            return None
        if method == "pins.union":
            return sorted(self._pins_union())
        raise ValueError(f"unknown meta method: {method}")

    def _check_fence(self, p: dict) -> None:
        raw = self.service.store.get(LEADER_KEY)
        if raw is None:
            return
        holder = json.loads(raw)
        generation = p.get("generation")
        if generation is not None and generation != holder["generation"]:
            raise Fenced(
                f"generation {generation} fenced by leader "
                f"{holder['session']} generation {holder['generation']}")

    def _pins_union(self) -> Set[str]:
        out: Set[str] = set()
        for ssts in self._remote_pins.values():
            out.update(ssts)
        return out

    def _notify_pins(self) -> None:
        self.service.notifications.notify(
            "hummock_pins", {"ssts": sorted(self._pins_union())})


class Fenced(RuntimeError):
    """A stale writer tried to publish under a lost lease."""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="risingwave-meta",
        description="Serve the meta control plane over wire frames.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default=None,
                    help="durable meta store directory (JSONL)")
    args = ap.parse_args(argv)
    server = MetaServer(data_dir=args.data_dir, host=args.host,
                        port=args.port)
    addr = server.start()
    # machine-readable readiness line: subprocess drivers parse this
    print(f"META_READY {addr}", flush=True)
    try:
        assert server._thread is not None
        while server._thread.is_alive():
            server._thread.join(timeout=1.0)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
