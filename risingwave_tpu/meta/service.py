"""MetaService: the assembled control plane the Session delegates to.

Round-3 verdict (weak #4): the meta components existed but were a side
library — the Session owned catalog, barriers, and recovery directly, and
the heartbeat detector detected failures nothing reacted to. This module
is the integration point that fixes that:

* ``MetaService`` owns the MetaStore (durable JSONL under the session's
  data dir when one is configured), the NotificationManager, and the
  ClusterManager.
* ``MetaBackedCatalog`` write-throughs every catalog mutation into the
  MetaStore as a CAS transaction and publishes a versioned "catalog"
  notification — the reference's CatalogManager contract
  (src/meta/src/manager/catalog/ + notification.rs:75-218).
* The Session registers every stream job as a worker, heartbeats it on
  each collected barrier, publishes "barrier"/"checkpoint" notifications
  from the conduction loop, and wires the cluster manager's failure
  listeners to scoped job recovery (src/meta/src/manager/cluster.rs:320-344
  heartbeat expiry → src/meta/src/barrier/recovery.rs:110).

The cluster clock is *epoch-based* (injected by the Session): a worker's
heartbeat timestamp is the last epoch whose barrier the job collected, and
the TTL is measured in epochs — deterministic under tests and independent
of wall-clock stalls (compiles, tunnels).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Optional

from .cluster import ClusterManager, WorkerNode
from .notification import NotificationManager
from .store import FileMetaStore, MetaStore


class MetaService:
    """One control plane instance (single-process deployment of the
    reference's meta node: store + notifications + cluster manager)."""

    #: barrier epochs a job may miss before it is declared dead
    HEARTBEAT_TTL_EPOCHS = 3

    def __init__(self, data_dir: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None):
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            self.store: MetaStore = FileMetaStore(
                os.path.join(data_dir, "meta.jsonl"))
        else:
            self.store = MetaStore()
        self.notifications = NotificationManager()
        self._epoch_clock = 0.0
        self.cluster = ClusterManager(
            heartbeat_ttl_s=float(self.HEARTBEAT_TTL_EPOCHS),
            clock=clock or (lambda: self._epoch_clock))
        self._worker_of_job: dict[str, int] = {}

    # -- job worker registry ---------------------------------------------------

    def register_job(self, name: str) -> WorkerNode:
        w = self.cluster.add_worker(host=name, parallelism=1)
        self._worker_of_job[name] = w.worker_id
        return w

    def deregister_job(self, name: str) -> None:
        wid = self._worker_of_job.pop(name, None)
        if wid is not None:
            self.cluster.delete_worker(wid)

    def job_heartbeat(self, name: str) -> None:
        wid = self._worker_of_job.get(name)
        if wid is not None:
            self.cluster.heartbeat(wid)

    def sync_jobs(self, names) -> None:
        """Reconcile the worker registry with the live job set (idempotent;
        called once per barrier cycle). Registration order follows the job
        order so detector sweeps are deterministic."""
        names = list(dict.fromkeys(names))
        for n in names:
            if n not in self._worker_of_job:
                self.register_job(n)
        name_set = set(names)
        for n in list(self._worker_of_job):
            if n not in name_set:
                self.deregister_job(n)

    def advance_epoch_clock(self, epoch: int) -> None:
        self._epoch_clock = float(epoch)

    def check_job_failures(self) -> list[str]:
        """Run the TTL expiry check; returns the names of jobs newly
        declared DOWN (their failure listeners have already fired)."""
        expired = self.cluster.check_heartbeats()
        return [w.host for w in expired]

    def on_job_failure(self, fn: Callable[[str], None]) -> None:
        self.cluster.on_failure(lambda w: fn(w.host))

    # -- compute nodes + fragment placement ------------------------------------

    def register_compute(self, worker_id: int, host: str, port: int,
                         parallelism: int = 1):
        return self.cluster.register_compute(worker_id, host, port,
                                             parallelism)

    def save_placement(self, placement) -> None:
        """Persist a spanning job's fragment→worker mapping (reference:
        the fragment catalog's persisted vnode mappings,
        manager/catalog/fragment.rs). Durable when the store is — a
        session restart re-places the SAME fragments onto the SAME
        workers, whose per-worker stores hold those fragments' state."""
        key = f"placement/{placement.job}"
        self.store.put(key, json.dumps(placement.to_json()))
        self.notifications.notify(
            "placement", {"job": placement.job,
                          "workers": placement.workers()})

    def load_placement(self, job: str):
        from .fragment import FragmentPlacement
        raw = self.store.get(f"placement/{job}")
        if raw is None:
            return None
        return FragmentPlacement.from_json(json.loads(raw))

    def drop_placement(self, job: str) -> None:
        self.store.delete(f"placement/{job}")

    def all_placements(self) -> dict:
        from .fragment import FragmentPlacement
        out = {}
        for key, raw in self.store.list_prefix("placement/"):
            p = FragmentPlacement.from_json(json.loads(raw))
            out[p.job] = p
        return out

    # -- barrier conduction publishing ----------------------------------------

    def publish_barrier(self, epoch: int, checkpoint: bool,
                        term: Optional[int] = None) -> None:
        """``term`` is the publisher's lease term (remote writers only):
        carrying it in the payload lets observers — notably the
        split-brain probe — verify that conduction terms never move
        backwards across a failover."""
        info = {"epoch": epoch, "checkpoint": checkpoint}
        if term is not None:
            info["term"] = int(term)
        self.notifications.notify("barrier", info)

    def publish_checkpoint(self, committed_epoch: int,
                           term: Optional[int] = None) -> None:
        info = {"committed_epoch": committed_epoch}
        if term is not None:
            info["term"] = int(term)
        self.notifications.notify("checkpoint", info)


class MetaBackedCatalog:
    """Write-through layer: catalog mutations become MetaStore CAS
    transactions plus versioned notifications, with the in-memory Catalog
    as the read cache (the frontend catalog replica of the reference).

    Composed (not inherited) over the existing ``frontend.catalog.Catalog``
    so the Session keeps its read surface unchanged; only the mutation
    methods route through here.
    """

    def __init__(self, catalog, meta: MetaService):
        self.view = catalog
        self.meta = meta

    # one key per object: catalog/<kind>/<name> -> JSON summary
    @staticmethod
    def _key(kind: str, name: str) -> str:
        return f"catalog/{kind}/{name}"

    @staticmethod
    def _summary(kind: str, obj) -> str:
        d = {"kind": kind, "name": obj.name}
        schema = getattr(obj, "schema", None)
        if schema is not None:
            d["columns"] = [(f.name, f.type.kind.value) for f in schema]
        # "table"/"columns"/"mv_name" carry IndexDef (no schema attr, so
        # the "columns" key cannot collide with the schema list above) —
        # serving sessions rebuild index entries from these
        for attr in ("table_id", "connector", "pk", "definition",
                     "from_name", "table", "columns", "mv_name"):
            v = getattr(obj, attr, None)
            if v is not None and v != "":
                d[attr] = list(v) if isinstance(v, tuple) else v
        return json.dumps(d)

    def _put(self, kind: str, obj) -> None:
        key = self._key(kind, obj.name)
        # plain put, not CAS-on-absence: uniqueness is enforced by the
        # in-memory add_* above, and recovery's DDL replay re-creates
        # objects whose keys a durable store already holds
        self.meta.store.put(key, self._summary(kind, obj))
        self.meta.notifications.notify(
            "catalog", {"op": "create", "kind": kind, "name": obj.name})

    def _del(self, kind: str, name: str) -> None:
        key = self._key(kind, name)
        self.meta.store.delete(key)
        self.meta.notifications.notify(
            "catalog", {"op": "drop", "kind": kind, "name": name})

    # -- mutation surface (mirrors Catalog's) ---------------------------------

    def add_source(self, s) -> None:
        self.view.add_source(s)
        self._put("source", s)

    def add_table(self, t) -> None:
        self.view.add_table(t)
        self._put("table", t)

    def add_mv(self, mv) -> None:
        self.view.add_mv(mv)
        self._put("materialized_view", mv)

    def add_sink(self, s) -> None:
        self.view.add_sink(s)
        self._put("sink", s)

    def add_index(self, ix) -> None:
        self.view.add_index(ix)
        self._put("index", ix)

    def drop(self, kind: str, name: str, if_exists: bool = False) -> bool:
        existed = self.view.drop(kind, name, if_exists)
        if existed:
            self._del(kind, name)
        return existed
