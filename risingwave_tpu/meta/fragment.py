"""Stream fragmenter + FragmentManager.

Counterparts of the reference's fragmenter and fragment registry
(reference: src/frontend/src/stream_fragmenter/mod.rs:115 — cut the plan
at exchange edges; src/meta/src/stream/stream_graph/fragment.rs:237;
manager/catalog/fragment.rs — persisted fragment→actor mapping).

In the TPU design an "exchange edge" is a *distribution change*: the
operators below it can run in one fused device step, and crossing it
requires a shuffle (all_to_all by key) or a singleton gather. Fragments
therefore cut at: hash-distributed Agg/Join inputs (shuffle by group/join
key), singleton operators (SimpleAgg/TopN/Sort), and Union fan-ins. The
fragment graph is what the meta tier schedules onto mesh slices and what
reschedule remaps (vnode → shard assignment per fragment).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..frontend import planner as P


@dataclasses.dataclass
class Fragment:
    fragment_id: int
    root: P.PlanNode                 # subtree executed inside this fragment
    distribution: str                # "hash" | "single" | "source"
    #: shuffle keys on this fragment's OUTPUT exchange (column indices of
    #: its root schema); empty = gather-to-singleton or passthrough
    dist_keys: Tuple[int, ...] = ()
    upstream: Tuple[int, ...] = ()   # fragment ids feeding this one


@dataclasses.dataclass
class FragmentGraph:
    fragments: Dict[int, Fragment]
    root_id: int

    def explain(self) -> str:
        lines = []
        for fid in sorted(self.fragments):
            f = self.fragments[fid]
            up = f" <- {list(f.upstream)}" if f.upstream else ""
            keys = f" keys={list(f.dist_keys)}" if f.dist_keys else ""
            lines.append(
                f"Fragment {fid} [{f.distribution}{keys}]{up}: "
                f"{f.root.label()}")
        return "\n".join(lines)


def fragment_plan(plan: P.PlanNode) -> FragmentGraph:
    """Cut a plan tree into fragments at distribution changes."""
    fragments: Dict[int, Fragment] = {}
    counter = {"next": 0}

    def new_fragment(root, distribution, dist_keys=(), upstream=()):
        fid = counter["next"]
        counter["next"] += 1
        fragments[fid] = Fragment(fid, root, distribution,
                                  tuple(dist_keys), tuple(upstream))
        return fid

    def visit(node: P.PlanNode) -> Tuple[P.PlanNode, List[int]]:
        """Returns (node, upstream fragment ids feeding the CURRENT
        fragment through exchanges below this node)."""
        if isinstance(node, P.PAgg):
            # exchange below the agg: hash by group key, or gather to a
            # singleton for the global agg
            child, child_up = visit(node.input)
            up = new_fragment(child, _dist_of(child),
                              tuple(node.group_keys), child_up)
            return node, [up]
        if isinstance(node, P.PJoin):
            left, lup = visit(node.left)
            right, rup = visit(node.right)
            lf = new_fragment(left, _dist_of(left),
                              tuple(node.left_keys), lup)
            rf = new_fragment(right, _dist_of(right),
                              tuple(node.right_keys), rup)
            return node, [lf, rf]            # hash exchange both sides
        if isinstance(node, P.PTopN):
            child, child_up = visit(node.input)
            if not node.group_by:
                up = new_fragment(child, _dist_of(child), (), child_up)
                return node, [up]            # gather to singleton
        if isinstance(node, P.PUnion):
            ups = []
            for inp in node.inputs:
                c, cup = visit(inp)
                ups.append(new_fragment(c, _dist_of(c), (), cup))
            return node, ups
        ups: List[int] = []
        for c in node.children:
            _, cup = visit(c)
            ups.extend(cup)
        return node, ups

    root, ups = visit(plan)
    root_id = new_fragment(root, _dist_of(root), (), ups)
    return FragmentGraph(fragments, root_id)


def _dist_of(node: P.PlanNode) -> str:
    if isinstance(node, (P.PSource, P.PTableScan, P.PMvScan, P.PValues)):
        return "source"
    if isinstance(node, P.PAgg):
        return "hash" if node.group_keys else "single"
    if isinstance(node, P.PJoin):
        return "hash"
    if isinstance(node, P.PTopN):
        return "single" if not node.group_by else "hash"
    return "inherit"


class FragmentManager:
    """Registry of fragment graphs per streaming job (reference:
    FragmentManager, manager/catalog/fragment.rs)."""

    def __init__(self) -> None:
        self._graphs: Dict[str, FragmentGraph] = {}

    def register(self, job_name: str, graph: FragmentGraph) -> None:
        self._graphs[job_name] = graph

    def drop(self, job_name: str) -> None:
        self._graphs.pop(job_name, None)

    def get(self, job_name: str) -> Optional[FragmentGraph]:
        return self._graphs.get(job_name)

    def all_jobs(self) -> List[str]:
        return sorted(self._graphs)
