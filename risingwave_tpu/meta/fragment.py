"""Stream fragmenter + FragmentManager.

Counterparts of the reference's fragmenter and fragment registry
(reference: src/frontend/src/stream_fragmenter/mod.rs:115 — cut the plan
at exchange edges; src/meta/src/stream/stream_graph/fragment.rs:237;
manager/catalog/fragment.rs — persisted fragment→actor mapping).

In the TPU design an "exchange edge" is a *distribution change*: the
operators below it can run in one fused device step, and crossing it
requires a shuffle (all_to_all by key) or a singleton gather. Fragments
therefore cut at: hash-distributed Agg/Join inputs (shuffle by group/join
key), singleton operators (SimpleAgg/TopN/Sort), and Union fan-ins. The
fragment graph is what the meta tier schedules onto mesh slices and what
reschedule remaps (vnode → shard assignment per fragment).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..frontend import planner as P


@dataclasses.dataclass
class Fragment:
    fragment_id: int
    root: P.PlanNode                 # subtree executed inside this fragment
    distribution: str                # "hash" | "single" | "source"
    #: shuffle keys on this fragment's OUTPUT exchange (column indices of
    #: its root schema); empty = gather-to-singleton or passthrough
    dist_keys: Tuple[int, ...] = ()
    upstream: Tuple[int, ...] = ()   # fragment ids feeding this one


@dataclasses.dataclass
class FragmentGraph:
    fragments: Dict[int, Fragment]
    root_id: int

    def explain(self) -> str:
        lines = []
        for fid in sorted(self.fragments):
            f = self.fragments[fid]
            up = f" <- {list(f.upstream)}" if f.upstream else ""
            keys = f" keys={list(f.dist_keys)}" if f.dist_keys else ""
            lines.append(
                f"Fragment {fid} [{f.distribution}{keys}]{up}: "
                f"{f.root.label()}")
        return "\n".join(lines)


def fragment_plan(plan: P.PlanNode) -> FragmentGraph:
    """Cut a plan tree into fragments at distribution changes."""
    fragments: Dict[int, Fragment] = {}
    counter = {"next": 0}

    def new_fragment(root, distribution, dist_keys=(), upstream=()):
        fid = counter["next"]
        counter["next"] += 1
        fragments[fid] = Fragment(fid, root, distribution,
                                  tuple(dist_keys), tuple(upstream))
        return fid

    def visit(node: P.PlanNode) -> Tuple[P.PlanNode, List[int]]:
        """Returns (node, upstream fragment ids feeding the CURRENT
        fragment through exchanges below this node)."""
        if isinstance(node, P.PAgg):
            # exchange below the agg: hash by group key, or gather to a
            # singleton for the global agg
            child, child_up = visit(node.input)
            up = new_fragment(child, _dist_of(child),
                              tuple(node.group_keys), child_up)
            return node, [up]
        if isinstance(node, P.PJoin):
            left, lup = visit(node.left)
            right, rup = visit(node.right)
            lf = new_fragment(left, _dist_of(left),
                              tuple(node.left_keys), lup)
            rf = new_fragment(right, _dist_of(right),
                              tuple(node.right_keys), rup)
            return node, [lf, rf]            # hash exchange both sides
        if isinstance(node, P.PTopN):
            child, child_up = visit(node.input)
            if not node.group_by:
                up = new_fragment(child, _dist_of(child), (), child_up)
                return node, [up]            # gather to singleton
        if isinstance(node, P.PUnion):
            ups = []
            for inp in node.inputs:
                c, cup = visit(inp)
                ups.append(new_fragment(c, _dist_of(c), (), cup))
            return node, ups
        ups: List[int] = []
        for c in node.children:
            _, cup = visit(c)
            ups.extend(cup)
        return node, ups

    root, ups = visit(plan)
    root_id = new_fragment(root, _dist_of(root), (), ups)
    return FragmentGraph(fragments, root_id)


def _dist_of(node: P.PlanNode) -> str:
    if isinstance(node, (P.PSource, P.PTableScan, P.PMvScan, P.PValues)):
        return "source"
    if isinstance(node, P.PAgg):
        return "hash" if node.group_keys else "single"
    if isinstance(node, P.PJoin):
        return "hash"
    if isinstance(node, P.PTopN):
        return "single" if not node.group_by else "hash"
    return "inherit"


# -- cross-worker span graphs -------------------------------------------------
# The fragment cut above describes the topology; a SPAN graph is the
# deployable form: each fragment's subtree is rewritten so its cut-point
# children become PExchange leaves (the serialized plan the worker
# receives names its exchange inputs explicitly), and the root fragment —
# the one that materializes — is part of the graph too. This is what the
# FragmentScheduler places onto worker processes by vnode mapping
# (reference: the meta DdlController turning the fragment graph into
# per-compute-node actor builds, src/meta/src/stream/stream_graph/).


class SpanUnsupported(ValueError):
    """Plan shape the cross-worker spanning path cannot deploy; the
    caller falls back to whole-job placement."""


@dataclasses.dataclass
class SpanFragment:
    fragment_id: int
    plan: P.PlanNode                 # subtree with PExchange cut leaves
    distribution: str                # "hash" | "single" | "source" | "inherit"
    dist_keys: Tuple[int, ...]       # keys of this fragment's OUTPUT exchange
    upstream: Tuple[int, ...]        # feeding fragment ids, PExchange order
    is_root: bool = False            # materializing fragment


@dataclasses.dataclass
class SpanGraph:
    fragments: Dict[int, SpanFragment]
    root_id: int

    def explain(self) -> str:
        lines = []
        for fid in sorted(self.fragments):
            f = self.fragments[fid]
            up = f" <- {list(f.upstream)}" if f.upstream else ""
            keys = f" keys={list(f.dist_keys)}" if f.dist_keys else ""
            root = " ROOT" if f.is_root else ""
            lines.append(f"Fragment {fid} [{f.distribution}{keys}]{root}"
                         f"{up}: {f.plan.label()}")
        return "\n".join(lines)


#: node kinds the spanning deployment understands. Anything else (over
#: windows, temporal joins, dynamic filters, project-set, ...) keeps the
#: whole-job placement path — correctness first, coverage grows per shape.
_SPAN_NODES = (P.PSource, P.PProject, P.PFilter, P.PHopWindow, P.PAgg,
               P.PJoin, P.PTopN, P.PUnion)
_ROW_WISE = (P.PProject, P.PFilter, P.PHopWindow)


def span_plan(plan: P.PlanNode) -> SpanGraph:
    """Cut a plan tree into a deployable span graph: the same cut points
    as ``fragment_plan`` with each parent's cut child replaced by a
    ``PExchange`` leaf naming the feeding fragment. Raises
    ``SpanUnsupported`` for shapes outside the supported node set or
    plans with non-source leaves (scans need the session-side bus)."""

    def check(node: P.PlanNode) -> None:
        if not isinstance(node, _SPAN_NODES):
            raise SpanUnsupported(
                f"cannot span {type(node).__name__} across workers")
        for c in node.children:
            check(c)

    check(plan)
    fragments: Dict[int, SpanFragment] = {}
    counter = {"next": 0}

    def new_fragment(root, distribution, dist_keys=(), upstream=()):
        fid = counter["next"]
        counter["next"] += 1
        fragments[fid] = SpanFragment(fid, root, distribution,
                                      tuple(dist_keys), tuple(upstream))
        return fid

    def cut(child: P.PlanNode, dist_keys, child_up) -> P.PExchange:
        fid = new_fragment(child, _dist_of(child), dist_keys, child_up)
        return P.PExchange(schema=child.schema, pk=tuple(child.pk),
                           upstream=fid)

    def visit(node: P.PlanNode) -> Tuple[P.PlanNode, List[int]]:
        """Returns (node with PExchange splices, upstream fragment ids
        feeding the CURRENT fragment, in exchange-leaf order)."""
        if isinstance(node, P.PAgg):
            child, child_up = visit(node.input)
            exch = cut(child, tuple(node.group_keys), child_up)
            return dataclasses.replace(node, input=exch), [exch.upstream]
        if isinstance(node, P.PJoin):
            left, lup = visit(node.left)
            right, rup = visit(node.right)
            lex = cut(left, tuple(node.left_keys), lup)
            rex = cut(right, tuple(node.right_keys), rup)
            return (dataclasses.replace(node, left=lex, right=rex),
                    [lex.upstream, rex.upstream])
        if isinstance(node, P.PTopN) and not node.group_by:
            child, child_up = visit(node.input)
            exch = cut(child, (), child_up)      # gather to singleton
            return dataclasses.replace(node, input=exch), [exch.upstream]
        if isinstance(node, P.PUnion):
            new_inputs, ups = [], []
            for inp in node.inputs:
                c, cup = visit(inp)
                exch = cut(c, (), cup)
                new_inputs.append(exch)
                ups.append(exch.upstream)
            return dataclasses.replace(node, inputs=tuple(new_inputs)), ups
        if isinstance(node, (P.PSource,)):
            return node, []
        # single-input pass-through nodes stay inside the current fragment
        child, child_up = visit(node.input)
        return dataclasses.replace(node, input=child), child_up

    root, ups = visit(plan)
    root_id = new_fragment(root, _dist_of(root), (), ups)
    fragments[root_id].is_root = True
    if len(fragments) < 2:
        raise SpanUnsupported("plan has no exchange cut; nothing to span")
    return SpanGraph(fragments, root_id)


def shardable(frag: SpanFragment) -> bool:
    """True if the fragment may run as MULTIPLE actors with its input
    exchange hash-split: a single grouped-agg core (cut directly below by
    its group keys) under any chain of row-wise operators. Each actor
    then owns a disjoint group-key shard, exactly the in-process
    multi-actor agg layout (frontend/fragments.py).

    ROOT fragments with a grouped-agg core shard too: each root actor
    materializes ITS vnode slice of the MV table into its own worker's
    store — the table becomes vnode-distributed across workers (the
    reference's distributed StorageTable), scans union the slices
    (``Session._remote_scan``), and the serving plane's two-phase
    partial agg tasks run where the vnodes live (frontend/serving.py).
    The agg's pk IS its group keys, so the materialize pk routing and
    the input exchange routing agree by construction."""
    if len(frag.upstream) != 1:
        return False
    node = frag.plan
    while isinstance(node, _ROW_WISE):
        node = node.input
    return (isinstance(node, P.PAgg) and bool(node.group_keys)
            and isinstance(node.input, P.PExchange))


# -- fragment placement (vnode mapping onto worker processes) -----------------

@dataclasses.dataclass
class ActorPlacement:
    fragment_id: int
    actor: int                       # index within the fragment
    worker: int                      # worker process id
    vnode_start: int                 # owned vnode range [start, end)
    vnode_end: int


@dataclasses.dataclass
class FragmentPlacement:
    """Persisted fragment→worker mapping of one spanning job (reference:
    the persisted vnode mappings of manager/catalog/fragment.rs)."""

    job: str
    actors: Dict[int, List[ActorPlacement]]      # fragment -> its actors
    root_worker: int

    def workers(self) -> List[int]:
        out: List[int] = []
        for acts in self.actors.values():
            for a in acts:
                if a.worker not in out:
                    out.append(a.worker)
        return sorted(out)

    def to_json(self) -> dict:
        return {
            "job": self.job,
            "root_worker": self.root_worker,
            "fragments": {
                str(fid): [dataclasses.asdict(a) for a in acts]
                for fid, acts in self.actors.items()
            },
        }

    @classmethod
    def from_json(cls, d: dict) -> "FragmentPlacement":
        return cls(
            job=d["job"],
            root_worker=int(d["root_worker"]),
            actors={int(fid): [ActorPlacement(**a) for a in acts]
                    for fid, acts in d["fragments"].items()},
        )


class FragmentScheduler:
    """Meta-side placement of span-graph fragments onto worker processes
    by vnode mapping (reference: the meta scheduler splitting the vnode
    ring across parallel units, src/meta/src/stream/scale.rs +
    docs/consistent-hash.md). Shardable hash fragments get one actor per
    assigned worker, each owning a contiguous vnode range — the SAME
    contiguous-range mapping ``vnode_to_shard`` applies on the dispatch
    path, so the persisted placement IS the routing function. Placement
    balances total owned vnodes per worker; singleton/source fragments
    own the whole ring on their one worker."""

    def __init__(self, vnode_count: Optional[int] = None):
        if vnode_count is None:
            from ..common.hashing import VNODE_COUNT
            vnode_count = VNODE_COUNT
        self.vnode_count = vnode_count

    def place(self, job: str, graph: SpanGraph, worker_ids: List[int],
              parallelism: int = 1) -> FragmentPlacement:
        if not worker_ids:
            raise ValueError("no live workers to place fragments on")
        vnodes_of: Dict[int, int] = {w: 0 for w in worker_ids}
        actors: Dict[int, List[ActorPlacement]] = {}

        def pick(exclude=()) -> int:
            free = [w for w in worker_ids if w not in exclude]
            return min(free, key=lambda w: (vnodes_of[w], w))

        for fid in sorted(graph.fragments):
            frag = graph.fragments[fid]
            n = 1
            if shardable(frag):
                n = max(1, min(parallelism, len(worker_ids)))
            per = self.vnode_count // n
            acts = []
            chosen: List[int] = []
            for a in range(n):
                w = pick(exclude=chosen)       # actors on distinct workers
                chosen.append(w)
                start = a * per
                end = self.vnode_count if a == n - 1 else (a + 1) * per
                vnodes_of[w] += end - start
                acts.append(ActorPlacement(fid, a, w, start, end))
            actors[fid] = acts
        return FragmentPlacement(job, actors,
                                 root_worker=actors[graph.root_id][0].worker)


class FragmentManager:
    """Registry of fragment graphs per streaming job (reference:
    FragmentManager, manager/catalog/fragment.rs)."""

    def __init__(self) -> None:
        self._graphs: Dict[str, FragmentGraph] = {}

    def register(self, job_name: str, graph: FragmentGraph) -> None:
        self._graphs[job_name] = graph

    def drop(self, job_name: str) -> None:
        self._graphs.pop(job_name, None)

    def get(self, job_name: str) -> Optional[FragmentGraph]:
        return self._graphs.get(job_name)

    def all_jobs(self) -> List[str]:
        return sorted(self._graphs)
