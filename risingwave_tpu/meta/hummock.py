"""HummockManager: the meta-side LSM version manager.

Counterpart of the reference's Hummock manager on the meta node
(reference: src/meta/src/hummock/manager/ — ``commit_epoch`` version
bumps, ``pin_version``/``unpin`` leases for consistent snapshot reads,
``get_compact_task``/``report_compact_task`` driving stateless compactor
workers, and the vacuum that deletes SSTs no version references;
versioning.rs for the pinned-version safety rule).

The manager owns exactly one mutable thing: the CURRENT
``HummockVersion``, published to the object store via ``atomic_put`` so
readers see the old manifest or the new one, never a torn mix. Every
other structure here (pins, in-flight compact tasks, stale-object
bookkeeping) exists to answer one question safely: *which SST objects
may vacuum delete?*

Safety rule (the invariant every test leans on):

    an object may be deleted iff it is referenced by
      - no current version,
      - no pinned version,
      - no in-flight compaction task (inputs still being read,
        outputs not yet committed).

Pins are process-local leases (the reference's are worker leases on the
meta node — same lifetime: a crashed process's pins vanish with it, and
its reads vanish too).
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional, Set

from ..storage.hummock import (
    SST_PREFIX, VERSION_KEY, CompactTask, HummockVersion,
)
from ..storage.object_store import ObjectStore


class HummockManager:
    """Version manager over one ObjectStore namespace."""

    #: L0 runs that trigger a compaction task (bounds read amplification
    #: the same way CheckpointLog.COMPACT_AFTER bounds segment counts)
    L0_COMPACT_TRIGGER = 8

    def __init__(self, store: ObjectStore,
                 l0_compact_trigger: Optional[int] = None):
        self.store = store
        if l0_compact_trigger is not None:
            self.L0_COMPACT_TRIGGER = l0_compact_trigger
        self._lock = threading.RLock()
        self._version = self._load_or_init()
        self._pins: Dict[int, HummockVersion] = {}
        self._pin_ids = itertools.count(1)
        self._task_ids = itertools.count(1)
        self._inflight: Dict[int, CompactTask] = {}
        # SSTs PUT but not yet referenced by a published version: the
        # barrier path uploads the L0 object first and commits the
        # version second, so a concurrently running vacuum would see an
        # orphan about to become referenced — registering the upload
        # closes that window (reference: vacuum's SST-id watermark)
        self._pending_uploads: Set[str] = set()
        # optional provider of EXTRA referenced SSTs: remote reader
        # sessions report their pinned runs through the meta control
        # plane, and the writer installs a hook here so vacuum treats
        # them like local pins (docs/control-plane.md)
        self.external_refs: Optional[Callable[[], Set[str]]] = None
        # observability counters (surfaced via Session.metrics()["storage"]
        # and the Prometheus exposition)
        self.stats = {
            "version_id": self._version.vid,
            "commits": 0,
            "l0_runs": len(self._version.l0),
            "l1_runs": len(self._version.l1),
            "compact_tasks_scheduled": 0,
            "compact_tasks_completed": 0,
            "compact_tasks_failed": 0,
            "ssts_vacuumed": 0,
            "vacuum_runs": 0,
        }

    # -- version plumbing ------------------------------------------------------

    def _load_or_init(self) -> HummockVersion:
        raw = self.store.get(VERSION_KEY)
        if raw is None:
            return HummockVersion.initial()
        return HummockVersion.from_bytes(raw)

    def _publish(self, v: HummockVersion) -> None:
        from ..common.failpoint import fail_point
        fail_point("hummock.version.publish")
        self.store.atomic_put(VERSION_KEY, v.to_bytes())
        self._version = v
        self.stats["version_id"] = v.vid
        self.stats["l0_runs"] = len(v.l0)
        self.stats["l1_runs"] = len(v.l1)

    @property
    def version(self) -> HummockVersion:
        """The current version (immutable snapshot; safe to hold)."""
        with self._lock:
            return self._version

    def reload(self) -> HummockVersion:
        """Adopt the PUBLISHED version (reader processes: another
        process's manager is the committer — our in-memory copy only
        chases it). Pins keep the snapshots they leased."""
        with self._lock:
            v = self._load_or_init()
            self._version = v
            self.stats["version_id"] = v.vid
            self.stats["l0_runs"] = len(v.l0)
            self.stats["l1_runs"] = len(v.l1)
            return v

    def exists(self) -> bool:
        return self.store.exists(VERSION_KEY)

    # -- epoch commit ----------------------------------------------------------

    def begin_upload(self, name: str) -> None:
        """Register an SST about to be PUT: vacuum must not delete it in
        the window before the version referencing it publishes."""
        with self._lock:
            self._pending_uploads.add(name)

    def abort_upload(self, name: str) -> None:
        """The upload failed or its commit did: the object (if any
        landed) is a true orphan again — vacuum food."""
        with self._lock:
            self._pending_uploads.discard(name)

    def commit_epoch(self, epoch: int, sst_name: Optional[str]) -> None:
        """Publish a new version with ``sst_name`` as the newest L0 run
        (None = idle checkpoint: only the committed epoch advances).
        The SST object itself must already be durable — a crash between
        SST write and this publish leaves an orphan that vacuum sweeps,
        never a version referencing a missing object (the same write
        discipline as the segment log)."""
        with self._lock:
            v = self._version
            l0 = ((sst_name,) + v.l0) if sst_name else v.l0
            self._publish(v.replace(
                vid=v.vid + 1, committed_epoch=epoch, l0=l0))
            if sst_name:
                self._pending_uploads.discard(sst_name)
            self.stats["commits"] += 1

    # -- manifest duties shared with the segment log ---------------------------

    def log_ddl(self, sql: str) -> None:
        with self._lock:
            v = self._version
            self._publish(v.replace(vid=v.vid + 1, ddl=v.ddl + (sql,)))

    def ddl(self) -> List[str]:
        with self._lock:
            return list(self._version.ddl)

    def drop_table(self, table_id: int) -> None:
        with self._lock:
            v = self._version
            if table_id in v.dropped_tables:
                return
            self._publish(v.replace(
                vid=v.vid + 1,
                dropped_tables=v.dropped_tables + (table_id,)))

    # -- pinning (consistent snapshot reads) -----------------------------------

    def pin_version(self) -> tuple[int, HummockVersion]:
        """Lease the current version: its SSTs outlive any concurrent
        compaction rewrite until ``unpin`` (reference:
        versioning.rs pin_version / HummockVersionSafePoint)."""
        with self._lock:
            pin_id = next(self._pin_ids)
            self._pins[pin_id] = self._version
            return pin_id, self._version

    def unpin_version(self, pin_id: int) -> None:
        with self._lock:
            self._pins.pop(pin_id, None)

    def pinned_versions(self) -> List[HummockVersion]:
        with self._lock:
            return list(self._pins.values())

    # -- compaction scheduling -------------------------------------------------

    def get_compact_task(self, force: bool = False) -> Optional[CompactTask]:
        """Hand out ONE merge task when L0 is deep enough: rewrite every
        L0 run plus the overlapping L1 runs into fresh sorted L1 runs.
        One task at a time — the version swap in ``report_compact_task``
        assumes its inputs are still current (the segment log's fold
        makes the same single-writer bet). ``force`` schedules regardless
        of depth (ctl / tests / post-DROP cleanup)."""
        with self._lock:
            if self._inflight:
                return None
            v = self._version
            if force:
                if not v.all_runs():
                    return None
            elif len(v.l0) < self.L0_COMPACT_TRIGGER:
                return None
            inputs = list(v.l0) + list(v.l1)
            task = CompactTask(
                task_id=next(self._task_ids),
                inputs=tuple(inputs),
                dropped_tables=v.dropped_tables,
                # every live run participates: tombstones and dropped
                # tables' rows can be discarded for good
                bottom=True,
                base_vid=v.vid)
            self._inflight[task.task_id] = task
            self.stats["compact_tasks_scheduled"] += 1
            return task

    def report_compact_task(self, task_id: int,
                            outputs: List[str]) -> bool:
        """Swap the task's inputs for its outputs in a new version.
        Returns False (and treats the outputs as orphans for vacuum) if
        the task is unknown/cancelled — a late report from a compactor
        the meta already gave up on must not corrupt the version."""
        with self._lock:
            task = self._inflight.pop(task_id, None)
            if task is None:
                self.stats["compact_tasks_failed"] += 1
                return False
            v = self._version
            inputs = set(task.inputs)
            # appends since the task snapshot stay; order is preserved
            l0 = tuple(s for s in v.l0 if s not in inputs)
            l1 = tuple(outputs) + tuple(
                s for s in v.l1 if s not in inputs)
            self._publish(v.replace(vid=v.vid + 1, l0=l0, l1=l1))
            self.stats["compact_tasks_completed"] += 1
            return True

    def cancel_compact_task(self, task_id: int) -> None:
        """Forget an in-flight task (compactor died / task failed): the
        version is untouched, a rescheduled task converges, and any
        half-written outputs become vacuum food."""
        with self._lock:
            if self._inflight.pop(task_id, None) is not None:
                self.stats["compact_tasks_failed"] += 1

    def inflight_tasks(self) -> List[CompactTask]:
        with self._lock:
            return list(self._inflight.values())

    # -- vacuum ----------------------------------------------------------------

    def referenced_ssts(self) -> Set[str]:
        with self._lock:
            refs: Set[str] = set()
            refs.update(self._version.all_runs())
            refs.update(self._pending_uploads)
            for v in self._pins.values():
                refs.update(v.all_runs())
            for t in self._inflight.values():
                refs.update(t.inputs)
            if self.external_refs is not None:
                refs.update(self.external_refs())
            return refs

    def _protected_prefixes(self) -> List[str]:
        """Output-name prefixes of in-flight tasks: a compactor (possibly
        another process) is writing ``c{task_id}-…`` objects that its
        report will reference — vacuum must not eat them mid-task."""
        return [f"{SST_PREFIX}c{t.task_id:06d}-"
                for t in self._inflight.values()]

    def vacuum(self, dry_run: bool = False) -> List[str]:
        """Delete every SST object unreferenced by the current version,
        any pinned version, any in-flight compaction (inputs AND not-yet-
        reported outputs), or any registered in-progress upload — orphans
        from torn publishes, cancelled tasks, and rewritten runs
        (reference: hummock/vacuum.rs full-scan GC). Returns the deleted
        names; ``dry_run`` only reports them (the offline ctl default)."""
        with self._lock:
            refs = self.referenced_ssts()
            protected = self._protected_prefixes()
            victims = [name for name in self.store.list(SST_PREFIX)
                       if name not in refs
                       and not any(name.startswith(p) for p in protected)]
            if dry_run:
                return victims
            self.stats["ssts_vacuumed"] += len(victims)
            self.stats["vacuum_runs"] += 1
        # deletes run OUTSIDE the lock: a checkpoint's commit_epoch must
        # not stall behind object-store IO. Safe: victims were already
        # unreferenced by every version/pin/task/upload at decision time,
        # new references only ever name NEW objects (uuid/task-id unique
        # names), so nothing can re-reference a victim meanwhile.
        for name in victims:
            self.store.delete(name)
        return victims
