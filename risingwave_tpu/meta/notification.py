"""NotificationManager: versioned metadata push to observers.

Counterpart of the reference's NotificationManager / ObserverManager
(reference: src/meta/src/manager/notification.rs:75-218;
src/common/common_service/src/observer_manager.rs:61). Observers (frontend
catalog caches, compute nodes, the dashboard) subscribe per channel and
receive ordered, versioned deltas; a late subscriber gets a snapshot
first — the same snapshot-then-deltas contract MV subscriptions use.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple


class NotificationManager:
    def __init__(self) -> None:
        self._version = 0
        self._log: List[Tuple[int, str, Any]] = []   # (version, channel, info)
        self._observers: Dict[str, List[Callable[[int, Any], None]]] = {}
        # wildcard observers see every channel: (version, channel, info).
        # The meta server's subscription push uses this — one remote
        # frontend is one observer, fanning out per-channel on its side.
        self._all_observers: List[Callable[[int, str, Any], None]] = []

    @property
    def current_version(self) -> int:
        return self._version

    def notify(self, channel: str, info: Any) -> int:
        """Publish one delta; returns its version."""
        self._version += 1
        self._log.append((self._version, channel, info))
        for fn in self._observers.get(channel, []):
            fn(self._version, info)
        for fn in list(self._all_observers):
            fn(self._version, channel, info)
        return self._version

    def subscribe(self, channel: str,
                  fn: Callable[[int, Any], None],
                  from_version: int = 0) -> int:
        """Register an observer; replays deltas after ``from_version``
        before live notifications (snapshot catch-up). Returns the version
        the observer is now current to."""
        for v, ch, info in self._log:
            if ch == channel and v > from_version:
                fn(v, info)
        self._observers.setdefault(channel, []).append(fn)
        return self._version

    def unsubscribe(self, channel: str, fn) -> None:
        obs = self._observers.get(channel, [])
        if fn in obs:
            obs.remove(fn)

    def subscribe_all(self, fn: Callable[[int, str, Any], None],
                      from_version: int = 0) -> int:
        """Register a wildcard observer; replays every channel's deltas
        after ``from_version`` first (snapshot catch-up), same contract
        as ``subscribe``. Returns the version the observer is current to."""
        for v, ch, info in self._log:
            if v > from_version:
                fn(v, ch, info)
        self._all_observers.append(fn)
        return self._version

    def unsubscribe_all(self, fn) -> None:
        if fn in self._all_observers:
            self._all_observers.remove(fn)
