from .cluster import ClusterManager, WorkerNode  # noqa: F401
from .fragment import Fragment, FragmentManager, fragment_plan  # noqa: F401
from .notification import NotificationManager  # noqa: F401
from .hummock import HummockManager  # noqa: F401
