"""MetaClient: the MetaService surface over a wire connection.

Drop-in for the in-process ``MetaService``: the Session constructs one
of these instead when given a ``meta_addr`` and every existing call
site — ``meta.store.put``, ``meta.job_heartbeat``, ``meta.publish_barrier``,
``MetaBackedCatalog`` write-throughs — works unchanged. Two sockets per
client (the reference frontend's pair of meta channels,
src/rpc_client/src/meta_client.rs):

* a **sync request channel** — strict request/reply frames under a lock
  (the CompactorClient idiom). Store ops re-raise ``TxnConflict``
  exactly as the local store does; lease-fenced publishes raise
  ``MetaFenced``.
* a **subscription channel** — a daemon reader thread that receives
  notification pushes and fans them out to locally registered
  observers through ``_NotificationRelay`` (same ``subscribe``/
  ``notify``/``current_version`` surface as ``NotificationManager``).

Reconnect story: a failed request retries once after re-dialing with
backoff (every mutation on this surface is idempotent — puts, deletes,
heartbeats, publishes). The ONE exception is the lease surface:
``lease.acquire``/``lease.renew`` are never retried, because a replayed
acquire after a competitor already won the CAS would hand two sessions
the same term — a split brain, not a transient (they surface
``MetaUnavailable`` instead and let the election layer re-evaluate with
a fresh term). The subscription thread re-dials forever until
``close()``; because the server's notification log is in-memory, a meta
restart resets versions, so after every re-subscribe the client fires
its registered **resync callbacks** — the session uses these to reload
the catalog from the (persisted) meta store, refresh its storage view,
re-assert the writer lease, and invalidate plan caches. Readers
therefore resume on the persisted meta store after a kill -9 without
operator involvement.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid
from typing import Any, Callable, List, Optional, Set, Tuple

from ..rpc.wire import read_frame_sync, write_frame_sync
from .service import MetaService
from .store import TxnConflict

#: chaos-plane link name for every client->meta frame (sim.py scenarios
#: inject drops/latency here the same way they do on exchange links)
META_LINK = "meta"


class MetaUnavailable(ConnectionError):
    """The meta server could not be reached within the reconnect budget."""


class MetaFenced(RuntimeError):
    """This writer's lease generation was superseded — it must stop
    conducting barriers and committing checkpoints immediately."""


class LeaseLost(RuntimeError):
    """A lease acquire/renew was refused: another session holds (or just
    won) the lease. Terminal for the caller's claim on that term — never
    retried, never mapped to ``TxnConflict``."""


class RemoteMetaStore:
    """``MetaStore`` surface over the sync request channel."""

    def __init__(self, client: "MetaClient"):
        self._client = client

    def get(self, key: str) -> Optional[str]:
        return self._client.call("store.get", {"key": key})

    def put(self, key: str, value: str) -> None:
        self._client.call("store.put", {"key": key, "value": value})

    def delete(self, key: str) -> None:
        self._client.call("store.delete", {"key": key})

    def list_prefix(self, prefix: str) -> List[Tuple[str, str]]:
        rows = self._client.call("store.list_prefix", {"prefix": prefix})
        return [(k, v) for k, v in rows]

    def txn(self, preconditions=None, ops=None) -> None:
        self._client.call("store.txn", {
            "preconditions": [[k, v] for k, v in (preconditions or [])],
            "ops": [list(op) for op in (ops or [])]})

    def compact(self) -> None:  # server-side concern; no-op remotely
        pass

    def close(self) -> None:
        pass


class _NotificationRelay:
    """Local observer registry fed by the subscription channel; mirrors
    the ``NotificationManager`` surface the session and catalog use."""

    def __init__(self, client: "MetaClient"):
        self._client = client
        self._lock = threading.Lock()
        self._version = 0
        self._log: List[Tuple[int, str, Any]] = []
        self._observers: dict = {}

    @property
    def current_version(self) -> int:
        return self._version

    def notify(self, channel: str, info: Any) -> int:
        """Publish through the server; local observers fire when the
        push comes back on the subscription channel (total order is the
        server's, not the caller's)."""
        return self._client.call("notify", {"channel": channel,
                                            "info": info})

    def subscribe(self, channel: str, fn: Callable[[int, Any], None],
                  from_version: int = 0) -> int:
        with self._lock:
            replay = [(v, ch, info) for v, ch, info in self._log
                      if ch == channel and v > from_version]
            self._observers.setdefault(channel, []).append(fn)
            version = self._version
        for v, _ch, info in replay:
            fn(v, info)
        return version

    def unsubscribe(self, channel: str, fn) -> None:
        with self._lock:
            obs = self._observers.get(channel, [])
            if fn in obs:
                obs.remove(fn)

    # -- fed by the subscription reader thread --------------------------------

    def _deliver(self, version: int, channel: str, info: Any) -> None:
        with self._lock:
            self._version = max(self._version, version)
            self._log.append((version, channel, info))
            observers = list(self._observers.get(channel, []))
        for fn in observers:
            try:
                fn(version, info)
            except Exception:
                pass

    def _reset(self) -> None:
        """Server restarted: its in-memory log (and versions) reset."""
        with self._lock:
            self._log = []
            self._version = 0


class MetaClient:
    """One frontend's attachment to a remote meta control plane."""

    HEARTBEAT_TTL_EPOCHS = MetaService.HEARTBEAT_TTL_EPOCHS

    #: give up on the sync channel after this long without a connection
    RECONNECT_TIMEOUT_S = 10.0
    _BACKOFF_S = (0.02, 0.05, 0.1, 0.2, 0.5, 1.0)

    def __init__(self, addr: str, session_id: Optional[str] = None,
                 reconnect_timeout_s: Optional[float] = None):
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.addr = addr
        self.session_id = session_id or uuid.uuid4().hex[:12]
        if reconnect_timeout_s is not None:
            self.RECONNECT_TIMEOUT_S = reconnect_timeout_s
        #: the writer session's fencing token (None for serving sessions)
        self.generation: Optional[int] = None
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._had_conn = False
        self._closed = False
        self._failure_fns: List[Callable[[str], None]] = []
        self._resync_fns: List[Callable[[], None]] = []
        self._reported_pins: Set[str] = set()
        self._hb_stop: Optional[threading.Event] = None
        self._hb_thread: Optional[threading.Thread] = None
        self.stats = {"reconnects": 0, "resyncs": 0, "requests": 0,
                      "heartbeats": 0, "lease_lost": 0}
        self.store = RemoteMetaStore(self)
        self.notifications = _NotificationRelay(self)
        self._dial()  # fail fast on a bad address
        self._sub_thread = threading.Thread(
            target=self._subscription_loop, name="meta-subscriber",
            daemon=True)
        self._sub_thread.start()

    # -- sync request channel --------------------------------------------------

    def _dial(self) -> socket.socket:
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=10.0)
                if self._had_conn:
                    # EVERY re-dial counts and re-reports, no matter
                    # which caller noticed the broken socket first (the
                    # heartbeat thread absorbs connection errors without
                    # retrying, so _reconnect is not the only path here)
                    self.stats["reconnects"] += 1
                    if self._reported_pins:
                        try:
                            self._request(
                                "pins.report",
                                {"ssts": sorted(self._reported_pins)})
                        except Exception:  # noqa: BLE001 - resync re-reports
                            pass
                self._had_conn = True
            return self._sock

    def _drop_conn(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _reconnect(self) -> None:
        deadline = time.monotonic() + self.RECONNECT_TIMEOUT_S
        for i in range(10 ** 6):
            if self._closed:
                raise MetaUnavailable("meta client closed")
            try:
                # _dial counts the reconnect and re-reports pins (a new
                # meta process does not know them)
                self._dial()
                return
            except OSError:
                self._drop_conn()
                if time.monotonic() >= deadline:
                    raise MetaUnavailable(
                        f"meta at {self.addr} unreachable for "
                        f"{self.RECONNECT_TIMEOUT_S:.0f}s")
                time.sleep(self._BACKOFF_S[min(i, len(self._BACKOFF_S) - 1)])

    #: methods the retry-once path must NEVER replay: a second acquire
    #: after a competitor won the CAS would be a split brain, and a
    #: replayed renew could resurrect a lease the TTL already expired
    _LEASE_METHODS = frozenset({"lease.acquire", "lease.renew"})

    def _request(self, method: str, params: Optional[dict]) -> Any:
        frame = {"method": method, "params": params or {},
                 # frame type for chaos-plane `types=[...]` rules
                 "type": method}
        if method.startswith("lease."):
            # own chaos stream (META_LINK + "#clease"): heartbeats are
            # wall-clock-driven, so they must not consume seqs from the
            # deterministic store/publish frame stream
            frame["chan"] = "lease"
        elif method == "pins.report":
            # same reasoning: serving sessions re-report pins when
            # checkpoint NOTIFICATIONS land (a wall-clock thread), so a
            # pin report racing a main-thread RPC must not perturb the
            # deterministic stream's seq numbering
            frame["chan"] = "pins"
        with self._lock:
            sock = self._dial()
            write_frame_sync(sock, frame, link=META_LINK)
            reply = read_frame_sync(sock)
        if reply is None:
            raise ConnectionError("meta connection closed mid-request")
        if reply.get("ok"):
            return reply.get("result")
        error = reply.get("error")
        message = reply.get("message", "")
        if error == "txn_conflict":
            raise TxnConflict(message)
        if error == "fenced":
            raise MetaFenced(message)
        if error == "lease_lost":
            raise LeaseLost(message)
        raise RuntimeError(f"meta {method} failed: {message}")

    def call(self, method: str, params: Optional[dict] = None) -> Any:
        """One request/reply; on a broken connection, re-dial with
        backoff and retry once (all meta mutations are idempotent —
        EXCEPT the lease surface, which is never retried: see
        ``_LEASE_METHODS``)."""
        if self._closed:
            raise MetaUnavailable("meta client closed")
        self.stats["requests"] += 1
        with self._lock:
            try:
                return self._request(method, params)
            except (ConnectionError, OSError) as e:
                if isinstance(e, MetaUnavailable):
                    raise
                self._drop_conn()
                if method in self._LEASE_METHODS:
                    raise MetaUnavailable(
                        f"meta unreachable during {method} "
                        f"(not retried: non-idempotent): {e}") from e
                self._reconnect()
                return self._request(method, params)

    def ping(self) -> dict:
        return self.call("ping")

    # -- MetaService surface ---------------------------------------------------

    def register_job(self, name: str) -> int:
        return self.call("register_job", {"name": name})

    def deregister_job(self, name: str) -> None:
        self.call("deregister_job", {"name": name})

    def job_heartbeat(self, name: str) -> None:
        self.call("job_heartbeat", {"name": name})

    def sync_jobs(self, names) -> None:
        self.call("sync_jobs", {"names": list(names)})

    def advance_epoch_clock(self, epoch: int) -> None:
        self.call("advance_epoch_clock", {"epoch": epoch})

    def check_job_failures(self) -> list:
        failed = self.call("check_job_failures") or []
        for name in failed:
            for fn in list(self._failure_fns):
                fn(name)
        return failed

    def on_job_failure(self, fn: Callable[[str], None]) -> None:
        self._failure_fns.append(fn)

    def register_compute(self, worker_id: int, host: str, port: int,
                         parallelism: int = 1) -> None:
        self.call("register_compute", {
            "worker_id": worker_id, "host": host, "port": port,
            "parallelism": parallelism})

    def save_placement(self, placement) -> None:
        self.call("save_placement", {"placement": placement.to_json()})

    def load_placement(self, job: str):
        from .fragment import FragmentPlacement
        raw = self.call("load_placement", {"job": job})
        return None if raw is None else FragmentPlacement.from_json(raw)

    def drop_placement(self, job: str) -> None:
        self.call("drop_placement", {"job": job})

    def all_placements(self) -> dict:
        from .fragment import FragmentPlacement
        out = {}
        for job, raw in (self.call("all_placements") or {}).items():
            out[job] = FragmentPlacement.from_json(raw)
        return out

    def publish_barrier(self, epoch: int, checkpoint: bool) -> None:
        self.call("publish_barrier", {
            "epoch": epoch, "checkpoint": checkpoint,
            "generation": self.generation})

    def publish_checkpoint(self, committed_epoch: int) -> None:
        self.call("publish_checkpoint", {
            "committed_epoch": committed_epoch,
            "generation": self.generation})

    # -- leader lease ----------------------------------------------------------

    def acquire_leader(self, generation: int,
                       reason: Optional[str] = None) -> int:
        """Claim the writer lease at this term (== generation). The
        server CAS admits a strictly newer term or the holder re-arming;
        a refused claim raises ``LeaseLost`` and this client's term
        stays unset — a losing election candidate remains a clean
        serving session."""
        params = {"session": self.session_id,
                  "generation": int(generation), "term": int(generation)}
        if reason is not None:
            params["reason"] = reason
        term = int(self.call("lease.acquire", params))
        self.generation = term
        return term

    def renew_leader(self) -> float:
        """Heartbeat the held lease; returns the new server deadline.
        ``LeaseLost`` means another session took the term: stop
        heartbeating and let the fencing path demote us."""
        return self.call("lease.renew", {
            "session": self.session_id, "term": self.generation,
            "generation": self.generation})

    def assert_leader(self) -> None:
        """Raise ``MetaFenced`` if this client no longer holds the lease."""
        self.call("lease.assert", {"generation": self.generation})

    def lease_info(self) -> dict:
        """Holder/term/TTL/failover-count snapshot (``ctl meta leader``,
        the system catalog, and the split-brain probe read this)."""
        return self.call("lease.info") or {}

    def start_heartbeat(self, interval_s: float,
                        on_lost: Optional[Callable[[Exception], None]]
                        = None) -> None:
        """Run a daemon renewal loop for the held lease. Transient link
        trouble is ignored — the server-side TTL is the sole judge of
        liveness; ``LeaseLost`` fires ``on_lost`` once and stops the
        loop (the session demotes via the MetaFenced path)."""
        self.stop_heartbeat()
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval_s):
                if self._closed or stop.is_set():
                    break
                try:
                    self.renew_leader()
                    self.stats["heartbeats"] += 1
                except LeaseLost as e:
                    self.stats["lease_lost"] += 1
                    if on_lost is not None:
                        try:
                            on_lost(e)
                        except Exception:
                            pass
                    break
                except Exception:
                    # unreachable/slow meta: keep trying on schedule —
                    # if we really are dead to the server, the TTL
                    # expires and a successor fences us on reconnect
                    continue

        self._hb_stop = stop
        self._hb_thread = threading.Thread(
            target=loop, name="lease-heartbeat", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        stop, thread = self._hb_stop, self._hb_thread
        self._hb_stop = None
        self._hb_thread = None
        if stop is not None:
            stop.set()
        if (thread is not None and thread.is_alive()
                and thread is not threading.current_thread()):
            thread.join(timeout=2.0)

    # -- remote pin registry ---------------------------------------------------

    def report_pins(self, ssts) -> None:
        self._reported_pins = set(ssts)
        self.call("pins.report", {"ssts": sorted(self._reported_pins)})

    def pins_union(self) -> Set[str]:
        return set(self.call("pins.union") or [])

    # -- subscription channel --------------------------------------------------

    def on_resync(self, fn: Callable[[], None]) -> None:
        """Register a callback fired after every (re)subscription —
        i.e. at attach and after a meta restart/reconnect. The session
        hooks catalog reload, store refresh, and lease re-assertion here."""
        self._resync_fns.append(fn)

    def _subscription_loop(self) -> None:
        first = True
        while not self._closed:
            sock = None
            try:
                sock = socket.create_connection(self._addr, timeout=10.0)
                # chan: the subscribe handshake is sent from THIS
                # daemon thread while the main thread keeps issuing
                # sync RPCs — its frame must ride its own chaos stream
                # or the dial race would perturb the deterministic
                # stream's seq numbering run to run
                write_frame_sync(sock, {"method": "subscribe",
                                        "type": "subscribe",
                                        "chan": "sub",
                                        "params": {"from_version": 0}},
                                 link=META_LINK)
                if not first:
                    # server may have restarted: mirror log is stale,
                    # and registered resync callbacks re-read durable
                    # state (initial attach does that work inline)
                    self.notifications._reset()
                    self._fire_resync()
                first = False
                while not self._closed:
                    frame = read_frame_sync(sock)
                    if frame is None:
                        break
                    self.notifications._deliver(
                        frame["version"], frame["channel"], frame["info"])
            except (ConnectionError, OSError):
                pass
            finally:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if not self._closed:
                time.sleep(0.1)

    def _fire_resync(self) -> None:
        self.stats["resyncs"] += 1
        for fn in list(self._resync_fns):
            try:
                fn()
            except Exception:
                pass

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        self.stop_heartbeat()
        self._drop_conn()
        if self._sub_thread.is_alive():
            self._sub_thread.join(timeout=2.0)


def leader_record(session: str, generation: int) -> str:
    """The JSON the leader lease key holds (kept next to the client so
    tests and ctl can decode it without importing the server)."""
    return json.dumps({"session": session, "generation": generation,
                       "term": generation})
