"""ClusterManager: worker registry + heartbeat failure detector.

Counterpart of the reference's ClusterManager
(reference: src/meta/src/manager/cluster.rs:64 registration/parallel
units, :300 heartbeat, :320-344 ``start_heartbeat_checker`` TTL expiry).
The clock is injectable so the deterministic sim can drive expiry without
wall time (reference: madsim virtual time).

Failure flow mirrors §3.4: on expiry the manager marks the worker DOWN and
invokes the registered failure listeners (the barrier conductor's recovery
hook in a full deployment; the sim harness in tests).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional


@dataclasses.dataclass
class WorkerNode:
    worker_id: int
    host: str
    parallelism: int                  # parallel units (device count)
    state: str = "RUNNING"            # RUNNING | DOWN
    last_heartbeat: float = 0.0


@dataclasses.dataclass
class ComputeNode:
    """One worker PROCESS the fragment scheduler may place fragments on
    (reference: the compute-node entries of cluster.rs:64 — here kept
    separate from the per-JOB heartbeat registry above, which predates
    multi-worker placement and measures liveness per job)."""

    worker_id: int
    host: str
    port: int                        # exchange/control socket port
    parallelism: int = 1
    state: str = "RUNNING"           # RUNNING | DOWN


class ClusterManager:
    def __init__(self, heartbeat_ttl_s: float = 30.0,
                 clock: Optional[Callable[[], float]] = None):
        self.heartbeat_ttl_s = heartbeat_ttl_s
        self.clock = clock or time.monotonic
        self.workers: Dict[int, WorkerNode] = {}
        self.compute_nodes: Dict[int, ComputeNode] = {}
        self._next_id = 1
        self._failure_listeners: List[Callable[[WorkerNode], None]] = []

    # -- compute-node registry (fragment placement targets) --------------------

    def register_compute(self, worker_id: int, host: str, port: int,
                         parallelism: int = 1) -> ComputeNode:
        """Idempotent upsert: a respawned worker re-registers under the
        same id with its NEW port (ephemeral ports change across kills),
        so persisted placements keep naming a stable worker id while the
        live address is always current."""
        node = ComputeNode(worker_id, host, port, parallelism)
        self.compute_nodes[worker_id] = node
        return node

    def set_compute_state(self, worker_id: int, state: str) -> None:
        node = self.compute_nodes.get(worker_id)
        if node is not None:
            node.state = state

    def live_compute_nodes(self) -> List[ComputeNode]:
        return [n for n in sorted(self.compute_nodes.values(),
                                  key=lambda n: n.worker_id)
                if n.state == "RUNNING"]

    def add_worker(self, host: str, parallelism: int) -> WorkerNode:
        w = WorkerNode(self._next_id, host, parallelism,
                       last_heartbeat=self.clock())
        self._next_id += 1
        self.workers[w.worker_id] = w
        return w

    def delete_worker(self, worker_id: int) -> None:
        self.workers.pop(worker_id, None)

    def heartbeat(self, worker_id: int) -> None:
        w = self.workers.get(worker_id)
        if w is None:
            raise KeyError(f"unknown worker {worker_id}")
        w.last_heartbeat = self.clock()
        if w.state == "DOWN":
            w.state = "RUNNING"       # rejoin after transient expiry

    def on_failure(self, fn: Callable[[WorkerNode], None]) -> None:
        self._failure_listeners.append(fn)

    def check_heartbeats(self) -> List[WorkerNode]:
        """One detector sweep; returns newly-expired workers (reference:
        the periodic checker task, cluster.rs:320-344)."""
        now = self.clock()
        expired = []
        for w in self.workers.values():
            if (w.state == "RUNNING"
                    and now - w.last_heartbeat > self.heartbeat_ttl_s):
                w.state = "DOWN"
                expired.append(w)
        for w in expired:
            for fn in self._failure_listeners:
                fn(w)
        return expired

    def live_workers(self) -> List[WorkerNode]:
        return [w for w in self.workers.values() if w.state == "RUNNING"]

    @property
    def total_parallelism(self) -> int:
        return sum(w.parallelism for w in self.live_workers())
