"""Backlog-driven autoscaler: the decision core of the elastic scaling
plane.

Counterpart of what the reference leaves to operators + external
controllers (its scale controller executes *requested* reschedules,
scale.rs:657; cloud deployments close the loop outside the kernel). Here
the loop closes inside the meta tier: the Session feeds one observation
per barrier tick — the job's per-edge exchange pressure (``backlog``
queued chunks, ``permits_waited`` growth: rpc/exchange.py EdgeStats,
federated via worker stats) and slow-epoch detections (common/tracing.py)
— and this class answers with a target parallelism when the policy says
to act. The Session then executes the decision as a LIVE vnode migration
(frontend/session.py ``rescale`` over meta/rescale.py plans).

The class is deliberately pure-state (no Session reference, no clock):
hysteresis, cooldown, and scale-in laziness are unit-testable against
synthetic signal streams (tests/test_rescale_live.py), and the same
instance serves the deterministic sim's traffic-spike scenario
(sim.py run_traffic_spike).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..common.config import AutoscalerConfig


@dataclasses.dataclass
class _JobState:
    high_streak: int = 0
    low_streak: int = 0
    cooldown: int = 0
    observations: int = 0
    last_signals: Optional[dict] = None
    last_error: Optional[str] = None


class Autoscaler:
    """Hysteresis + cooldown policy over per-job load signals.

    ``observe`` returns the target parallelism when a decision fires,
    else None. Anti-flap contract (pinned by tests): no decision while a
    cooldown runs (streaks do not even accumulate), scale-out needs
    ``hysteresis`` CONSECUTIVE high observations, scale-in needs
    ``scale_in_after`` consecutive all-quiet ones — so load oscillating
    faster than the hysteresis window produces no decisions at all, and
    a spike followed by quiet produces exactly one scale-out."""

    def __init__(self, cfg: Optional[AutoscalerConfig] = None):
        self.cfg = cfg or AutoscalerConfig()
        self.jobs: Dict[str, _JobState] = {}
        self.decisions: List[dict] = []
        self.decisions_total = 0  # monotonic (decisions is a capped ring)

    def _state(self, job: str) -> _JobState:
        return self.jobs.setdefault(job, _JobState())

    def observe(self, job: str, parallelism: int, backlog: int = 0,
                permits_waited: int = 0, slow_epochs: int = 0,
                live_workers: Optional[int] = None) -> Optional[int]:
        cfg = self.cfg
        # a spanning rescale needs `target` DISTINCT live workers
        # (meta/rescale.py plan_rescale refuses otherwise): cap the
        # reachable parallelism so the policy never decides a migration
        # the cluster cannot execute — an uncapped decision would burn a
        # cooldown on a guaranteed RescaleUnsupported every window
        max_par = (cfg.max_parallelism if live_workers is None
                   else min(cfg.max_parallelism, live_workers))
        st = self._state(job)
        st.observations += 1
        st.last_signals = {"backlog": int(backlog),
                           "permits_waited": int(permits_waited),
                           "slow_epochs": int(slow_epochs),
                           "parallelism": int(parallelism)}
        if st.cooldown > 0:
            # anti-flap: inside the cooldown window signals are recorded
            # but never accumulate toward a decision
            st.cooldown -= 1
            st.high_streak = st.low_streak = 0
            return None
        high = (backlog >= cfg.high_backlog
                or permits_waited >= cfg.high_permits_waited
                or slow_epochs >= cfg.high_slow_epochs)
        low = (backlog <= cfg.low_backlog
               and permits_waited <= cfg.low_permits_waited
               and slow_epochs == 0)
        if high:
            st.high_streak += 1
            st.low_streak = 0
        elif low:
            st.low_streak += 1
            st.high_streak = 0
        else:
            st.high_streak = st.low_streak = 0
        target: Optional[int] = None
        reason = None
        if (st.high_streak >= cfg.hysteresis
                and parallelism < max_par):
            target = min(max_par, max(parallelism * 2,
                                      parallelism + 1))
            reason = "scale-out"
        elif (st.low_streak >= cfg.scale_in_after
                and parallelism > cfg.min_parallelism):
            target = max(cfg.min_parallelism, parallelism // 2)
            reason = "scale-in"
        if target is None or target == parallelism:
            return None
        st.cooldown = cfg.cooldown
        st.high_streak = st.low_streak = 0
        self.decisions_total += 1
        self.decisions.append({
            "job": job, "reason": reason, "from": int(parallelism),
            "to": int(target), "at_observation": st.observations,
            "signals": dict(st.last_signals)})
        del self.decisions[:-64]
        return target

    def note_failed(self, job: str, error: str) -> None:
        """A decided rescale failed to execute (rolled back): remember
        the error and hold the full cooldown before retrying, so a
        persistently failing migration cannot busy-loop."""
        st = self._state(job)
        st.last_error = error
        st.cooldown = max(st.cooldown, self.cfg.cooldown)

    def status(self) -> dict:
        """Policy-state dump for metrics()/`ctl cluster autoscaler`."""
        return {
            "decisions": list(self.decisions),
            "decisions_total": self.decisions_total,
            "last_trigger": self.decisions[-1] if self.decisions else None,
            "jobs": {
                job: {
                    "high_streak": st.high_streak,
                    "low_streak": st.low_streak,
                    "cooldown": st.cooldown,
                    "observations": st.observations,
                    "signals": st.last_signals,
                    "last_error": st.last_error,
                }
                for job, st in sorted(self.jobs.items())
            },
        }
