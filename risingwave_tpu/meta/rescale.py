"""Elastic scaling plane: placement diffing for live vnode migration.

Counterpart of the reference's scale controller
(reference: src/meta/src/stream/scale.rs:657 — a Reschedule command
computes, per fragment, which vnode-bitmap ranges change owner and
rebuilds only the affected actors, shipping state as shared-storage
references instead of replaying sources). This module is the PURE math
half of that controller: given a deployed ``FragmentPlacement`` and a
target parallelism it produces a new placement whose actor ranges still
equal the ``vnode_to_shard`` contiguous mapping (the routing function —
placement and routing can never diverge) while moving the MINIMAL set of
vnode ranges, plus the explicit ``VnodeMove`` list the migration protocol
executes (frontend/session.py ``rescale``; worker state-ref handoff in
worker/host.py).

This module is also the single write path for placement mutations:
``commit_placement`` is the only caller of ``MetaService.save_placement``
outside the service itself (scripts/check.sh lints this), so every
``placement/<job>`` meta-store write is attributable to either job
creation or an executed rescale plan.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .fragment import ActorPlacement, FragmentPlacement, SpanGraph, shardable


class RescaleUnsupported(ValueError):
    """A rescale request the scaling plane cannot execute (documented in
    docs/scaling.md): whole-job remote placements have no vnode-mapped
    fragments to migrate, and a spanning rescale needs at least
    ``parallelism`` live workers."""


@dataclasses.dataclass(frozen=True)
class VnodeMove:
    """One contiguous vnode range of one fragment changing owner."""

    fragment_id: int
    vnode_start: int
    vnode_end: int
    from_worker: int
    from_actor: int
    to_worker: int
    to_actor: int

    @property
    def width(self) -> int:
        return self.vnode_end - self.vnode_start


@dataclasses.dataclass
class RescalePlan:
    job: str
    old: FragmentPlacement
    new: FragmentPlacement
    moves: List[VnodeMove]

    @property
    def moved_vnodes(self) -> int:
        return sum(m.width for m in self.moves)

    def moves_by_source(self) -> Dict[Tuple[int, int], List[VnodeMove]]:
        """Moves grouped by (from_worker, fragment) — one export request
        per group (the source worker writes one handoff segment per
        moving range)."""
        out: Dict[Tuple[int, int], List[VnodeMove]] = {}
        for m in self.moves:
            out.setdefault((m.from_worker, m.fragment_id), []).append(m)
        return out

    def summary(self) -> dict:
        return {
            "job": self.job,
            "moves": [dataclasses.asdict(m) for m in self.moves],
            "moved_vnodes": self.moved_vnodes,
            "workers_before": self.old.workers(),
            "workers_after": self.new.workers(),
        }


def actor_ranges(vnode_count: int, n: int) -> List[Tuple[int, int]]:
    """The contiguous per-actor vnode ranges for ``n`` actors — EXACTLY
    the ``vnode_to_shard`` mapping (common/hashing.py): actor ``a`` owns
    ``[a*per, (a+1)*per)`` with the last actor absorbing the remainder,
    so the persisted placement IS the routing function."""
    if n < 1:
        raise ValueError("parallelism must be >= 1")
    per = vnode_count // n
    if per == 0:
        raise ValueError(f"parallelism {n} exceeds vnode count {vnode_count}")
    return [(a * per, vnode_count if a == n - 1 else (a + 1) * per)
            for a in range(n)]


def _overlap(a: Tuple[int, int], b: Tuple[int, int]) -> int:
    return max(0, min(a[1], b[1]) - max(a[0], b[0]))


def plan_rescale(job: str, graph: SpanGraph, old: FragmentPlacement,
                 worker_ids: List[int], parallelism: int,
                 vnode_count: Optional[int] = None) -> RescalePlan:
    """Compute the minimal-movement placement for a new parallelism.

    Shardable fragments (meta/fragment.py ``shardable``) change actor
    count; each NEW contiguous range is assigned to the distinct worker
    owning the LARGEST slice of it under the old placement (both
    partitions are contiguous and ordered, so this greedy assignment
    moves only the ranges whose owner must change — for 2→4 over one
    fragment exactly half the ring moves, for 4→2 likewise). Singleton
    and source fragments keep their placement verbatim: nothing of
    theirs moves. Raises ``RescaleUnsupported`` when fewer than
    ``parallelism`` distinct live workers exist."""
    if vnode_count is None:
        from ..common.hashing import VNODE_COUNT
        vnode_count = VNODE_COUNT
    if parallelism < 1:
        raise RescaleUnsupported(f"parallelism must be >= 1, got "
                                 f"{parallelism}")
    if not worker_ids:
        raise RescaleUnsupported("no live workers")
    actors: Dict[int, List[ActorPlacement]] = {}
    # global vnode balance across fragments for overlap-free assignments
    vnodes_of: Dict[int, int] = {w: 0 for w in worker_ids}
    for fid in sorted(graph.fragments):
        frag = graph.fragments[fid]
        old_acts = old.actors[fid]
        if not shardable(frag):
            kept = [dataclasses.replace(a) for a in old_acts]
            for a in kept:
                if a.worker in vnodes_of:
                    vnodes_of[a.worker] += a.vnode_end - a.vnode_start
            actors[fid] = kept
            continue
        n = parallelism
        if n > len(worker_ids):
            raise RescaleUnsupported(
                f"fragment {fid} needs {n} distinct workers, "
                f"only {len(worker_ids)} are live")
        ranges = actor_ranges(vnode_count, n)
        assign: List[Optional[int]] = [None] * n
        taken: set = set()
        # pass 1: keep ranges with their largest old owner, biggest
        # overlaps first — burning a worker on a zero-overlap range
        # while it still owns another range would move vnodes for free
        pairs = []
        for a, rng in enumerate(ranges):
            for oa in old_acts:
                if oa.worker not in vnodes_of:
                    continue
                ov = _overlap(rng, (oa.vnode_start, oa.vnode_end))
                if ov > 0:
                    pairs.append((-ov, a, oa.worker))
        for neg_ov, a, w in sorted(pairs):
            if assign[a] is None and w not in taken:
                assign[a] = w
                taken.add(w)
        # pass 2: genuinely new ranges go to the least-loaded free worker
        acts: List[ActorPlacement] = []
        for a, (start, end) in enumerate(ranges):
            w = assign[a]
            if w is None:
                free = [x for x in worker_ids if x not in taken]
                if not free:
                    raise RescaleUnsupported(
                        f"fragment {fid} needs {n} distinct workers")
                w = min(free, key=lambda x: (vnodes_of[x], x))
                taken.add(w)
            vnodes_of[w] += end - start
            acts.append(ActorPlacement(fid, a, w, start, end))
        actors[fid] = acts
    new = FragmentPlacement(job, actors,
                            root_worker=actors[graph.root_id][0].worker)
    return RescalePlan(job, old, new, diff_placements(old, new))


def diff_placements(old: FragmentPlacement,
                    new: FragmentPlacement) -> List[VnodeMove]:
    """The vnode ranges whose OWNER changes between two placements of
    the same fragment graph — the only state the migration protocol
    touches (everything else stays in place on its worker). Ranges are
    split at every old/new actor boundary so each move names exactly one
    (source actor, destination actor) pair."""
    moves: List[VnodeMove] = []
    for fid in sorted(new.actors):
        old_acts = old.actors.get(fid, [])
        cuts = sorted({a.vnode_start for a in old_acts}
                      | {a.vnode_end for a in old_acts}
                      | {a.vnode_start for a in new.actors[fid]}
                      | {a.vnode_end for a in new.actors[fid]})
        for s, e in zip(cuts, cuts[1:]):
            src = next((a for a in old_acts
                        if a.vnode_start <= s and e <= a.vnode_end), None)
            dst = next((a for a in new.actors[fid]
                        if a.vnode_start <= s and e <= a.vnode_end), None)
            if src is None or dst is None or src.worker == dst.worker:
                continue
            prev = moves[-1] if moves else None
            if (prev is not None and prev.fragment_id == fid
                    and prev.vnode_end == s
                    and prev.from_worker == src.worker
                    and prev.from_actor == src.actor
                    and prev.to_worker == dst.worker
                    and prev.to_actor == dst.actor):
                moves[-1] = dataclasses.replace(prev, vnode_end=e)
            else:
                moves.append(VnodeMove(fid, s, e, src.worker, src.actor,
                                       dst.worker, dst.actor))
    return moves


def commit_placement(meta, placement: FragmentPlacement) -> None:
    """Persist a placement mutation. The ONLY sanctioned write path for
    ``placement/<job>`` outside MetaService itself (and the lint in
    scripts/check.sh keeps it that way): job creation and executed
    rescale plans both commit through here, so the durable mapping is
    always one the scheduler or the scaling plane produced."""
    meta.save_placement(placement)
