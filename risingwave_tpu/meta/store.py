"""MetaStore: transactional KV for cluster metadata.

Counterpart of the reference's meta storage
(reference: src/meta/src/storage/ — etcd-backed (or in-memory)
transactional KV under every meta manager; docs/meta-service.md:21-27).
Two backends: in-memory (playground/tests) and an append-only JSONL file
log (durable single-node). Transactions are compare-and-swap batches:
all preconditions checked against the current snapshot, then all ops
applied atomically — the same primitive the reference's managers build
catalogs and fragment maps on.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple


class TxnConflict(Exception):
    pass


class MetaStore:
    def __init__(self) -> None:
        self._kv: Dict[str, str] = {}

    # -- plain ops ------------------------------------------------------------

    def get(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def put(self, key: str, value: str) -> None:
        self.txn([], [("put", key, value)])

    def delete(self, key: str) -> None:
        self.txn([], [("del", key, "")])

    def list_prefix(self, prefix: str) -> List[Tuple[str, str]]:
        return sorted(
            (k, v) for k, v in self._kv.items() if k.startswith(prefix))

    # -- transactions ---------------------------------------------------------

    def txn(self, preconditions: List[Tuple[str, Optional[str]]],
            ops: List[Tuple[str, str, str]]) -> None:
        """``preconditions``: (key, expected_value_or_None-for-absent).
        ``ops``: ("put"|"del", key, value). All-or-nothing."""
        for key, expected in preconditions:
            if self._kv.get(key) != expected:
                raise TxnConflict(
                    f"precondition failed on {key!r}: "
                    f"expected {expected!r}, found {self._kv.get(key)!r}")
        for op, _k, _v in ops:
            if op not in ("put", "del"):
                raise ValueError(f"unknown op {op!r}")
        # durability first: if the log append fails, memory must not hold
        # values the disk never saw (the all-or-nothing contract)
        self._persist(ops)
        for op, key, value in ops:
            if op == "put":
                self._kv[key] = value
            else:
                self._kv.pop(key, None)

    def _persist(self, ops) -> None:
        pass


class FileMetaStore(MetaStore):
    """Durable backend: committed txns append to a JSONL log, replayed at
    open (the etcd stand-in for single-node deployments)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if os.path.exists(path):
            size = os.path.getsize(path)
            good = 0
            last_line_open = False   # last replayed line lacked its '\n'
            with open(path, "rb") as f:
                while True:
                    line = f.readline()
                    if not line:
                        break
                    try:
                        # strict: _persist writes ASCII json; any invalid
                        # byte is corruption, same contract as the JSON
                        # parse below
                        text = line.decode("utf-8").strip()
                        txn = json.loads(text) if text else None
                    except (UnicodeDecodeError, json.JSONDecodeError):
                        # a torn TAIL is the normal crash-mid-append case
                        # (truncated below); torn MIDDLE lines are real
                        # corruption — never eat those
                        if f.read().strip():
                            raise
                        break
                    if txn is not None:
                        for op, key, value in txn:
                            if op == "put":
                                self._kv[key] = value
                            else:
                                self._kv.pop(key, None)
                    good += len(line)
                    last_line_open = not line.endswith(b"\n")
            if good < size:
                os.truncate(path, good)
            if last_line_open and good > 0:
                # a valid line torn exactly before its newline: appending
                # directly would CONCATENATE the next txn onto it and a
                # later replay would truncate both — close the line first
                with open(path, "a", encoding="utf-8") as f:
                    f.write("\n")
        self._f = open(path, "a", encoding="utf-8")

    def _persist(self, ops) -> None:
        if not ops:
            return
        # injectable meta-store IO: the failpoint registry's exact-site
        # faults and the network fault plane's "meta" link both land
        # here, BEFORE the append — a failed txn leaves memory and disk
        # agreeing (the all-or-nothing contract the caller relies on)
        from ..common.failpoint import fail_point
        from ..rpc.faults import meta_io
        fail_point("meta.store.txn")
        meta_io("txn", ops[0][1] if ops else "")
        self._f.write(json.dumps(list(ops)) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def compact(self) -> None:
        """Rewrite the log as one snapshot txn."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            snap = [["put", k, v] for k, v in sorted(self._kv.items())]
            f.write(json.dumps(snap) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        self._f.close()
