"""Playground CLI: one-process cluster behind a Postgres port.

Counterpart of the reference's all-in-one binary
(reference: src/cmd_all/src/bin/risingwave.rs:118 ``playground`` mode and
the node binaries under src/cmd/src/bin/). Usage:

    python -m risingwave_tpu playground [--port 4566] [--data-dir DIR]
    python -m risingwave_tpu sql "CREATE TABLE ..." [--data-dir DIR]
    python -m risingwave_tpu sql-file script.sql [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import sys


def _build_session(args):
    from .frontend.session import Session
    kwargs = {}
    if args.data_dir:
        kwargs["data_dir"] = args.data_dir
    if getattr(args, "checkpoint_frequency", None):
        kwargs["checkpoint_frequency"] = args.checkpoint_frequency
    if getattr(args, "workers", 0):
        kwargs["workers"] = args.workers
    if getattr(args, "state_store", None):
        kwargs["state_store"] = args.state_store
    if getattr(args, "compactors", 0):
        kwargs["compactors"] = args.compactors
    if getattr(args, "meta_addr", None):
        kwargs["meta_addr"] = args.meta_addr
    if getattr(args, "role", None):
        kwargs["role"] = args.role
    fp = getattr(args, "fragment_parallelism", 1)
    mesh_n = getattr(args, "mesh", 0)
    if (fp and fp != 1) or mesh_n:
        from .frontend.build import BuildConfig
        mesh = None
        if mesh_n:
            # refuses loudly (MeshUnavailableError) when the process has
            # fewer devices than asked for — see [streaming] mesh_shape
            from .parallel.sharded_agg import make_mesh
            mesh = make_mesh(mesh_n)
        kwargs["config"] = BuildConfig(fragment_parallelism=fp, mesh=mesh)
    return Session(**kwargs)


#: one default shared by every session-building subcommand, so a durable
#: data dir deployed from any of them recovers under the same topology
#: (the library default, BuildConfig/StreamingConfig fragment_parallelism
#: = 1, stays single-actor for embedded/API use)
FRAGMENT_PARALLELISM_DEFAULT = 2


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="risingwave_tpu")
    sub = p.add_subparsers(dest="command", required=True)

    # shared by playground / sql / sql-file / ctl via parents=[...]
    fp_arg = argparse.ArgumentParser(add_help=False)
    fp_arg.add_argument(
        "--fragment-parallelism", type=int,
        default=FRAGMENT_PARALLELISM_DEFAULT,
        help="parallel actors per fragmentable operator (grouped aggs / "
        "joins run as multi-fragment jobs with hash-dispatch exchanges; "
        "1 = single actor; must match the value a durable data dir was "
        "deployed with so recovery and `ctl fragments` reflect the live "
        "topology; reference: streaming.default_parallelism)")
    fp_arg.add_argument(
        "--mesh", type=int, default=0, metavar="N",
        help="shard operator state across an N-device mesh "
        "(BuildConfig.mesh / [streaming] mesh_shape): grouped aggs and "
        "joins run the mesh-sharded executors, and eligible fused MVs "
        "tick as one dispatch per epoch across all chips. Refuses "
        "loudly when the process has fewer than N devices (on CPU set "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N); 0 = "
        "single-chip")
    fp_arg.add_argument(
        "--meta-addr", default=None, metavar="HOST:PORT",
        help="attach to a standalone meta server (`ctl meta serve`) "
        "instead of running the control plane in-process — the "
        "multi-tenant deployment shape: one meta + one shared state "
        "dir, one writer session, N serving frontends "
        "(docs/control-plane.md); also settable via [meta] addr")

    pg = sub.add_parser("playground", parents=[fp_arg],
                        help="serve SQL over the Postgres wire protocol")
    pg.add_argument("--host", default="127.0.0.1")
    pg.add_argument("--port", type=int, default=4566)
    pg.add_argument("--data-dir", default=None,
                    help="durable state directory (RAM-only if absent)")
    pg.add_argument("--checkpoint-frequency", type=int, default=10)
    pg.add_argument("--tick-interval-ms", type=int, default=1000,
                    help="barrier interval (reference default 1000ms)")
    pg.add_argument("--workers", type=int, default=0,
                    help="worker PROCESSES hosting MV jobs (reference: "
                    "compute nodes; 0 = everything in-process)")
    pg.add_argument("--state-store", default=None,
                    choices=["segment", "hummock"],
                    help="durable tier for a NEW data dir: epoch-delta "
                    "segment log, or Hummock-lite L0 SSTs under a "
                    "versioned manifest (recovery auto-detects)")
    pg.add_argument("--compactors", type=int, default=0,
                    help="dedicated compactor worker PROCESSES "
                    "(hummock tier; 0 = in-process background fold)")
    pg.add_argument("--user", default="root",
                    help="user name for password auth (with --password)")
    pg.add_argument("--password", default=None,
                    help="enable md5 password authentication "
                    "(default: trust, like the reference playground)")
    pg.add_argument("--dashboard-port", type=int, default=None,
                    help="serve the meta dashboard (cluster / fragment "
                    "graphs / await-tree) on this port")
    pg.add_argument("--role", default=None,
                    choices=["writer", "serving", "standby"],
                    help="session role when attached to a standalone "
                    "meta (--meta-addr): the single 'writer' conducts "
                    "barriers and owns DDL; 'serving' frontends are "
                    "read-mostly replicas sharing the writer's state "
                    "dir; 'standby' serves reads AND races the "
                    "election when the writer's lease expires, "
                    "promoting in place (docs/control-plane.md)")

    q = sub.add_parser("sql", parents=[fp_arg],
                       help="run SQL statements and print results")
    q.add_argument("statement")
    q.add_argument("--data-dir", default=None)

    qf = sub.add_parser("sql-file", parents=[fp_arg],
                        help="run a SQL script file")
    qf.add_argument("path")
    qf.add_argument("--data-dir", default=None)

    ctl = sub.add_parser(
        "ctl", parents=[fp_arg],
        help="admin inspection of a durable data dir "
             "(reference: risectl)")
    ctl.add_argument("what", choices=["jobs", "parameters", "fragments",
                                      "metrics", "trace", "backup",
                                      "restore", "backup-info",
                                      "hummock", "vacuum", "cluster",
                                      "profile", "bench", "udf", "meta"])
    ctl.add_argument("sub", nargs="?", default=None,
                     help="subcommand for `ctl cluster` "
                     "(fragments — dump the persisted fragment→worker "
                     "placement and per-edge permit state of spanning "
                     "jobs; rescale — live-migrate one spanning job to "
                     "a new parallelism; autoscaler — dump the scaling "
                     "plane's policy state and executed migrations), "
                     "`ctl profile` (roofline — AOT cost/memory "
                     "analysis of every registered fused surface "
                     "against the chip roofline, chip-free) and `ctl bench` "
                     "(trend — per-field trend with regression flags "
                     "over the checked-in BENCH_r*.json records), and "
                     "`ctl udf` (serve — run a standalone out-of-process "
                     "UDF server in the foreground; sessions attach via "
                     "[udf] addr = \"host:port\" — docs/robustness.md), "
                     "and `ctl trace` (barrier — the barrier "
                     "observatory's per-epoch waterfall history and "
                     "stage percentiles; add --inflight for live "
                     "stuck-barrier blame — docs/observability.md), "
                     "and `ctl meta` (serve — run a standalone meta "
                     "server in the foreground over --data-dir; "
                     "sessions attach with --meta-addr / [meta] addr; "
                     "leader — who holds the lease: session, term, TTL "
                     "remaining, failover count and term history, read "
                     "live over --meta-addr or offline from --data-dir "
                     "— docs/control-plane.md)")
    ctl.add_argument("job", nargs="?", default=None,
                     help="job name for `ctl cluster rescale`")
    ctl.add_argument("--parallelism", type=int, default=None,
                     help="target fragment parallelism for "
                     "`ctl cluster rescale` (docs/scaling.md)")
    ctl.add_argument("--data-dir", default=None,
                     help="durable data dir (required for every ctl "
                     "command except `profile`, `bench` and `udf`, "
                     "which read no cluster state)")
    ctl.add_argument("--port", type=int, default=0,
                     help="udf serve: listen port (0 = ephemeral, "
                     "printed as UDF_READY <port>)")
    ctl.add_argument("--json", action="store_true",
                     help="profile/bench/trace barrier: emit the full "
                     "JSON report instead of the table")
    ctl.add_argument("--inflight", action="store_true",
                     help="trace barrier: walk the LIVE in-flight "
                     "barrier accounting and name the actors/links "
                     "that have not acked (stuck-barrier blame)")
    ctl.add_argument("--peak-flops", type=float, default=None,
                     help="profile roofline: chip peak FLOP/s "
                     "(default [observability] chip_peak_flops)")
    ctl.add_argument("--peak-bandwidth", type=float, default=None,
                     help="profile roofline: chip HBM bandwidth in "
                     "bytes/s (default [observability] "
                     "chip_peak_bandwidth)")
    ctl.add_argument("--surface", default=None,
                     help="profile roofline: analyze ONE registered "
                     "fused surface (e.g. source_session, "
                     "sharded:group_agg) instead of the whole ladder")
    ctl.add_argument("--tolerance", type=float, default=0.2,
                     help="bench trend: relative move off the best "
                     "prior value that flags a regression")
    ctl.add_argument("--bench-dir", default=".",
                     help="bench trend: directory holding "
                     "BENCH_r*.json / BENCH_partial.json")
    ctl.add_argument("--backup-dir",
                     help="backup location for backup/restore/backup-info")
    ctl.add_argument("--workers", type=int, default=0,
                     help="worker processes to recover the cluster with "
                     "(metrics/trace/cluster over a data dir deployed "
                     "with --workers N needs the same N; `cluster "
                     "fragments` infers it from the persisted placement "
                     "when omitted)")
    ctl.add_argument("--force", action="store_true",
                     help="vacuum: actually delete (default is a dry "
                     "run; only safe with no live session on the dir)")
    ctl.add_argument("--lease-ttl", type=float, default=None,
                     help="meta serve: leader lease TTL in seconds — a "
                     "writer that misses heartbeats for this long is "
                     "declared down and standbys race the election "
                     "(default 2.0; docs/control-plane.md)")

    comp = sub.add_parser(
        "compactor",
        help="run a dedicated Hummock-lite compaction worker against a "
             "shared object-store root (reference: the standalone "
             "compactor node)")
    comp.add_argument("--data-dir", required=True)
    comp.add_argument("--worker-id", type=int, default=0)
    comp.add_argument("--port", type=int, default=0)

    args = p.parse_args(argv)

    if args.command == "playground":
        return _playground(args)
    if args.command == "ctl":
        return _ctl(args)
    if args.command == "compactor":
        from .worker.compactor import main as compactor_main
        compactor_main(["--data-dir", args.data_dir,
                        "--worker-id", str(args.worker_id),
                        "--port", str(args.port)])
        return 0
    session = _build_session(args)
    sql = (args.statement if args.command == "sql"
           else open(args.path, "r", encoding="utf-8").read())
    rows = session.run_sql(sql)
    for row in rows:
        print("\t".join("" if v is None else str(v) for v in row))
    return 0


def _ctl(args) -> int:
    """risectl-lite: recover a session from the data dir and inspect it
    (reference: src/ctl/src/lib.rs:48-75 — cluster-info, table scan,
    trace, profile; meta backup/restore:
    src/meta/src/backup_restore/backup_manager.rs)."""
    import json as _json
    if args.what == "profile":
        if args.sub != "roofline":
            raise SystemExit("usage: ctl profile roofline "
                             "[--peak-flops F --peak-bandwidth B --json]")
        return _ctl_profile_roofline(args, _json)
    if args.what == "bench":
        if args.sub != "trend":
            raise SystemExit("usage: ctl bench trend "
                             "[--bench-dir DIR --tolerance T --json]")
        return _ctl_bench_trend(args, _json)
    if args.what == "udf":
        if args.sub != "serve":
            raise SystemExit("usage: ctl udf serve [--port N]")
        # a PERSISTENT operator-managed server: clients come and go,
        # registrations outlive any one of them (auto-spawned servers
        # are one-client; udf/server.py)
        from .udf.server import main as udf_server_main
        udf_server_main(["--port", str(args.port), "--persistent"])
        return 0
    if args.what == "meta":
        if args.sub == "leader":
            return _ctl_meta_leader(args, _json)
        if args.sub != "serve":
            raise SystemExit("usage: ctl meta serve --data-dir DIR "
                             "[--port N --lease-ttl S] | "
                             "ctl meta leader (--meta-addr HOST:PORT | "
                             "--data-dir DIR) [--json]")
        if not args.data_dir:
            raise SystemExit("--data-dir is required (the meta store "
                             "lives under DIR/meta)")
        # the standalone control plane (docs/control-plane.md): serves
        # the MetaService surface over the wire protocol; prints
        # "META_READY host:port" once listening. The store lives under
        # DIR/meta — the SAME path an in-process session over DIR uses,
        # so `ctl cluster fragments` etc. keep reading it offline.
        import os as _os
        from .meta.server import main as meta_server_main
        argv = ["--data-dir", _os.path.join(args.data_dir, "meta"),
                "--port", str(args.port)]
        if args.lease_ttl is not None:
            argv += ["--lease-ttl", str(args.lease_ttl)]
        meta_server_main(argv)
        return 0
    if not args.data_dir:
        raise SystemExit("--data-dir is required")
    if args.what in ("backup", "restore", "backup-info"):
        from .storage.backup import (
            create_backup, list_backup, restore_backup,
        )
        if not args.backup_dir:
            raise SystemExit("--backup-dir is required")
        if args.what == "backup":
            desc = create_backup(args.data_dir, args.backup_dir)
        elif args.what == "restore":
            desc = restore_backup(args.backup_dir, args.data_dir)
        else:
            desc = list_backup(args.backup_dir)
        print(_json.dumps(desc, indent=2))
        return 0
    if args.what == "cluster":
        if args.sub == "fragments":
            return _ctl_cluster_fragments(args, _json)
        if args.sub == "rescale":
            return _ctl_cluster_rescale(args, _json)
        if args.sub == "autoscaler":
            return _ctl_cluster_autoscaler(args, _json)
        raise SystemExit(
            "usage: ctl cluster fragments|rescale|autoscaler "
            "--data-dir DIR [JOB --parallelism N]")
    if args.what in ("hummock", "vacuum"):
        # storage-only inspection: no session (and no job recovery) —
        # read the version manifest straight off the object store
        from .meta.hummock import HummockManager
        from .storage.object_store import open_object_store
        mgr = HummockManager(open_object_store(args.data_dir))
        if not mgr.exists():
            raise SystemExit(
                f"{args.data_dir!r} holds no hummock version manifest")
        if args.what == "vacuum":
            # OFFLINE-ONLY: pins, in-progress uploads, and in-flight
            # compaction tasks live in the OWNING session's memory — a
            # fresh manager cannot see them, so vacuuming under a live
            # session could delete objects it is about to reference. The
            # live path is the session's own vacuum (the compaction pump
            # runs it after every task). Default is therefore a DRY RUN;
            # --force performs the deletes and is the operator's
            # assertion that no session is running over this dir.
            if args.force:
                deleted = mgr.vacuum()
                print(_json.dumps({"deleted": deleted}, indent=2))
            else:
                victims = mgr.vacuum(dry_run=True)
                print(_json.dumps({
                    "would_delete": victims,
                    "note": "dry run — pass --force only when NO live "
                            "session is using this data dir (a live "
                            "cluster vacuums itself)"}, indent=2))
        else:
            print(_json.dumps({"version": mgr.version.summary(),
                               "stats": mgr.stats}, indent=2))
        return 0
    session = _build_session(args)
    try:
        _ctl_dispatch(args, session, _json)
    finally:
        session.close()
    return 0


def _roofline_surfaces() -> dict:
    """The full fused ladder for chip-free AOT analysis: one lazy
    builder per registered surface — every ``EPOCH_BUILDERS`` entry
    (q5/q7/q8/q3), the co-scheduled multi-job epoch, and every
    ``SHARDED_EPOCH_BUILDERS`` entry (sharded q5/q7/q8/q3, the generic
    equi-join, the K×S group) — at bench-like shapes. Each builder
    returns ``(callable, args)``; nothing is executed (AOT
    lower+compile only), so this works with no chip attached. Sharded
    surfaces build over the widest mesh THIS process hosts (force a
    virtual mesh with XLA_FLAGS=--xla_force_host_platform_device_count
    for multi-shard analysis on CPU)."""
    import jax
    import jax.numpy as jnp
    from .common import INT64, TIMESTAMP
    from .common.types import Field, Schema
    from .connector import NexmarkConfig
    from .connector.nexmark import DeviceBidGenerator
    from .connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from .expr import Literal, call, col
    from .expr.agg import count_star
    from .ops.fused_epoch import EPOCH_BUILDERS
    from .ops.fused_multi import build_group_epoch, stack_states
    from .ops.fused_sharded import SHARDED_EPOCH_BUILDERS
    from .ops.grouped_agg import AggCore
    from .ops.interval_join import IntervalJoinCore
    from .ops.session_window import SessionWindowCore
    from .ops.stream_q3 import Q3Core
    from .parallel.sharded_agg import make_mesh

    cap, k, window_us, jobs = 1024, 8, 10_000_000, 8
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    start, key = jnp.int64(0), jax.random.PRNGKey(0)

    def q5_parts():
        exprs = [call("tumble_start", col(5, TIMESTAMP),
                      Literal(window_us, INT64)), col(0, INT64)]
        core = AggCore((INT64, INT64), (0, 1), [count_star()],
                       table_capacity=1 << 16, out_capacity=cap)
        return exprs, core

    def q7_parts():
        exprs = [call("tumble_start", col(5, TIMESTAMP),
                      Literal(window_us, INT64)),
                 col(0, INT64), col(2, INT64)]
        core = IntervalJoinCore(
            Schema((Field("window_start", TIMESTAMP),
                    Field("auction", INT64), Field("price", INT64))),
            ts_col=0, val_col=2, window_us=window_us,
            n_buckets=1 << 12, lane_width=16)
        return exprs, core

    def q8_parts():
        exprs = [col(1, INT64), col(5, TIMESTAMP)]
        core = SessionWindowCore(
            Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
            key_col=0, ts_col=1, gap_us=500_000,
            capacity=1 << 16, closed_capacity=1 << 16)
        return exprs, core

    def q3_parts():
        core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 16,
                      agg_capacity=1 << 16)
        return DeviceQ3Generator(TpchQ3Config(chunk_capacity=cap)), core

    def mesh_and_states(core):
        mesh = make_mesh(min(len(jax.devices()), 8))
        n = mesh.devices.size
        return mesh, stack_states([core.init_state() for _ in range(n)])

    def t_q5():
        exprs, core = q5_parts()
        fn = EPOCH_BUILDERS["source_agg"](gen.chunk_fn(), exprs, core, cap)
        return fn, (core.init_state(), start, key, k)

    def t_q7():
        exprs, core = q7_parts()
        fn = EPOCH_BUILDERS["source_join"](gen.chunk_fn(), exprs, core,
                                           cap)
        return fn, (core.init_state(), start, key, k)

    def t_q8():
        exprs, core = q8_parts()
        fn = EPOCH_BUILDERS["source_session"](gen.chunk_fn(), exprs, core,
                                              cap)
        return fn, (core.init_state(), start, key, k, jnp.int64(0))

    def t_q3():
        q3gen, core = q3_parts()
        fn = EPOCH_BUILDERS["source_q3"](q3gen.chunk_fn(), core, cap)
        return fn, (core.init_state(), start, key, k)

    def t_multi():
        exprs, core = q5_parts()
        fn = build_group_epoch("agg", gen.chunk_fn(), exprs, core, cap)
        stacked = stack_states([core.init_state() for _ in range(jobs)])
        starts = jnp.zeros(jobs, jnp.int64)
        keys = jnp.stack([jax.random.PRNGKey(j) for j in range(jobs)])
        nos = jnp.zeros(jobs, jnp.int64)
        return fn, (stacked, starts, keys, nos, k)

    def t_sharded_q5():
        exprs, core = q5_parts()
        mesh, stacked = mesh_and_states(core)
        fn = SHARDED_EPOCH_BUILDERS["source_agg"](
            gen.chunk_fn(), exprs, core, cap, mesh)
        return fn, (stacked, start, key, k)

    def t_sharded_q7():
        exprs, core = q7_parts()
        mesh, stacked = mesh_and_states(core)
        fn = SHARDED_EPOCH_BUILDERS["source_join"](
            gen.chunk_fn(), exprs, core, cap, mesh)
        return fn, (stacked, start, key, k)

    def t_sharded_q8():
        exprs, core = q8_parts()
        mesh, stacked = mesh_and_states(core)
        fn = SHARDED_EPOCH_BUILDERS["source_session"](
            gen.chunk_fn(), exprs, core, cap, mesh)
        return fn, (stacked, start, key, k, jnp.int64(0))

    def t_sharded_q3():
        q3gen, core = q3_parts()
        mesh, stacked = mesh_and_states(core)
        fn = SHARDED_EPOCH_BUILDERS["source_q3"](
            q3gen.chunk_fn(), core, cap, mesh)
        return fn, (stacked, start, key, k)

    def t_equi_join():
        from .connector.nexmark import AUCTION_SCHEMA, BID_SCHEMA
        from .ops.join_state import JoinCore, JoinType
        core = JoinCore(BID_SCHEMA, AUCTION_SCHEMA, [0], [0],
                        JoinType.INNER, key_capacity=1 << 10,
                        bucket_width=8)
        mesh, stacked = mesh_and_states(core)
        n = mesh.devices.size
        fn = SHARDED_EPOCH_BUILDERS["equi_join"](core, mesh, [0], [0])

        def zero_chunk():
            from .common.chunk import Column, StreamChunk
            cols = tuple(
                Column(jnp.zeros((n, k, cap), f.type.dtype),
                       jnp.zeros((n, k, cap), jnp.bool_))
                for f in BID_SCHEMA)
            return StreamChunk(jnp.zeros((n, k, cap), jnp.int8),
                               jnp.zeros((n, k, cap), jnp.bool_), cols)

        return fn, (stacked, zero_chunk(), "left")

    def t_sharded_group():
        exprs, core = q5_parts()
        mesh = make_mesh(min(len(jax.devices()), 8))
        n = mesh.devices.size
        fn = SHARDED_EPOCH_BUILDERS["group_agg"](
            gen.chunk_fn(), exprs, core, cap, mesh)
        per_job = [stack_states([core.init_state() for _ in range(n)])
                   for _ in range(jobs)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=1), *per_job)
        starts = jnp.zeros(jobs, jnp.int64)
        keys = jnp.stack([jax.random.PRNGKey(j) for j in range(jobs)])
        nos = jnp.zeros(jobs, jnp.int64)
        return fn, (stacked, starts, keys, nos, k)

    def t_hetero_padded():
        import numpy as np
        from .ops.fused_hetero import HETERO_EPOCH_BUILDERS
        from .stream.tick_compiler import skeletonize_exprs
        exprs, core = q5_parts()
        skel, hole_types, params = skeletonize_exprs(tuple(exprs), 7)
        fn = HETERO_EPOCH_BUILDERS["padded_agg"](
            gen.chunk_fn(), skel, core, cap)
        stacked = stack_states([core.init_state() for _ in range(jobs)])
        starts = jnp.zeros(jobs, jnp.int64)
        keys = jnp.stack([jax.random.PRNGKey(j) for j in range(jobs)])
        nos = jnp.zeros(jobs, jnp.int64)
        ps = tuple(jnp.asarray(np.full(jobs, params[h], t.np_dtype))
                   for h, t in enumerate(hole_types))
        return fn, (stacked, starts, keys, nos, ps, k)

    def t_hetero_mega():
        from .expr.agg import agg
        from .ops.fused_hetero import HETERO_EPOCH_BUILDERS
        from .stream.coschedule import FusedJobSpec

        def spec_of(exprs, core):
            return FusedJobSpec(
                kind="agg", signature=("roofline",),
                chunk_fn=gen.chunk_fn(), exprs=tuple(exprs),
                core=core, rows_per_chunk=cap, seed=0)

        exprs1, core1 = q5_parts()
        exprs2 = [col(0, INT64), col(2, INT64)]
        core2 = AggCore((INT64,), (0,),
                        [count_star(), agg("sum", 1, INT64)],
                        table_capacity=1 << 14, out_capacity=cap)
        fn = HETERO_EPOCH_BUILDERS["mega_agg"](
            [spec_of(exprs1, core1), spec_of(exprs2, core2)])
        states = (core1.init_state(), core2.init_state())
        starts = jnp.zeros(2, jnp.int64)
        keys = jnp.stack([jax.random.PRNGKey(j) for j in range(2)])
        nos = jnp.zeros(2, jnp.int64)
        return fn, (states, starts, keys, nos, k)

    return {
        "source_agg": t_q5, "source_join": t_q7,
        "source_session": t_q8, "source_q3": t_q3,
        "multi_agg": t_multi,
        "hetero:padded_agg": t_hetero_padded,
        "hetero:mega_agg": t_hetero_mega,
        "sharded:source_agg": t_sharded_q5,
        "sharded:source_join": t_sharded_q7,
        "sharded:source_session": t_sharded_q8,
        "sharded:source_q3": t_sharded_q3,
        "sharded:equi_join": t_equi_join,
        "sharded:group_agg": t_sharded_group,
    }


def _ctl_profile_roofline(args, _json) -> int:
    """`ctl profile roofline`: AOT-``lower().compile()`` EVERY
    registered fused surface — the four solo epochs, the co-scheduled
    multi-job epoch, and all six sharded surfaces — and print each
    kernel's flops / bytes accessed / arithmetic intensity / %-of-peak
    against the chip roofline: the measured-roofline artifact ROADMAP
    item 1 demands, available chip-free (docs/performance.md).
    ``--surface NAME`` restricts the (expensive) AOT compile to one
    surface."""
    from .common.config import ObservabilityConfig
    from .common.profiling import (
        aot_analysis, render_roofline_table, roofline_report,
    )
    obs = ObservabilityConfig()
    peak_flops = args.peak_flops or obs.chip_peak_flops
    peak_bw = args.peak_bandwidth or obs.chip_peak_bandwidth
    surfaces = _roofline_surfaces()
    pick = getattr(args, "surface", None)
    if pick is not None:
        if pick not in surfaces:
            raise SystemExit(
                f"unknown surface {pick!r}; choose from: "
                + ", ".join(sorted(surfaces)))
        surfaces = {pick: surfaces[pick]}
    analyses = {}
    for name, build in surfaces.items():
        # report keys = the dispatch qualnames common/dispatch_count.py,
        # the profiler, and Session.metrics()["dispatch"] all share
        # (unique per surface); the surface name is the selector only
        try:
            fn, fn_args = build()
            analyses[getattr(fn, "__qualname__", name)] = \
                aot_analysis(fn, *fn_args)
        except Exception as e:  # noqa: BLE001 - per-surface attribution
            analyses[name] = {"error": f"{type(e).__name__}: {e}"}
    report = roofline_report(analyses, peak_flops, peak_bw)
    if args.json:
        print(_json.dumps(report, indent=2))
    else:
        print(render_roofline_table(report))
    return 0


def _ctl_bench_trend(args, _json) -> int:
    """`ctl bench trend`: fold every checked-in BENCH_r*.json round and
    BENCH_partial.json phase record into a per-field trend, flagging
    fields whose latest value regressed past ``--tolerance`` off the
    best prior value — ROADMAP item 5's "regressions in ANY plane show
    up as a trend"."""
    from .common.profiling import (
        bench_trend, load_bench_history, render_trend_table,
    )
    history = load_bench_history(args.bench_dir)
    if not history:
        raise SystemExit(
            f"no BENCH_r*.json / BENCH_partial.json under "
            f"{args.bench_dir!r}")
    trend = bench_trend(history, tolerance=args.tolerance)
    if args.json:
        print(_json.dumps(trend, indent=2))
    else:
        print(render_trend_table(trend))
    return 0


def _ctl_meta_leader(args, _json) -> int:
    """`ctl meta leader`: who holds the leader lease — session, term,
    TTL remaining, how it was acquired, failover count, and the term
    history. Live over ``--meta-addr`` (asks the server, which owns the
    in-memory deadline), or offline from ``--data-dir`` (reads the
    persisted lease record; TTL remaining is server memory and shows as
    unknown — docs/control-plane.md "Election")."""
    import os
    if getattr(args, "meta_addr", None):
        from .meta.client import MetaClient
        client = MetaClient(args.meta_addr, session_id="ctl-leader")
        try:
            info = client.lease_info()
        finally:
            client.close()
    elif args.data_dir:
        from .meta.service import MetaService
        path = os.path.join(args.data_dir, "meta", "meta.jsonl")
        if not os.path.exists(path):
            raise SystemExit(f"{args.data_dir!r} holds no meta store")
        meta = MetaService(data_dir=os.path.join(args.data_dir, "meta"))
        try:
            store = meta.store
            info = {"holder": None, "term": None, "acquired_at": None,
                    "reason": None, "lease_ttl_s": None,
                    "ttl_remaining_s": None, "expired": None,
                    "failovers": int(store.get("leader_failovers")
                                     or "0"),
                    "history": _json.loads(
                        store.get("leader_history") or "[]")}
            raw = store.get("leader")
            if raw is not None:
                holder = _json.loads(raw)
                info["holder"] = holder.get("session")
                info["term"] = int(holder.get(
                    "term", holder.get("generation", 0)))
                info["acquired_at"] = holder.get("acquired_at")
                info["reason"] = holder.get("reason")
        finally:
            meta.store.close()
    else:
        raise SystemExit("ctl meta leader needs --meta-addr HOST:PORT "
                         "(live) or --data-dir DIR (offline)")
    if args.json:
        print(_json.dumps(info, indent=2))
        return 0
    if info.get("holder") is None:
        print("leader: (none)")
    else:
        ttl = info.get("ttl_remaining_s")
        ttl_s = "unknown (offline)" if ttl is None else f"{ttl:.3f}s"
        print(f"leader:    {info['holder']}")
        print(f"term:      {info['term']}")
        print(f"reason:    {info.get('reason') or '-'}")
        print(f"ttl left:  {ttl_s}"
              + ("  [EXPIRED]" if info.get("expired") else ""))
    print(f"failovers: {info.get('failovers', 0)}")
    history = info.get("history") or []
    if history:
        print("term\tholder\treason\tleaderless_s")
        for h in history:
            gap = h.get("leaderless_s")
            print(f"{h.get('term')}\t{h.get('holder')}\t"
                  f"{h.get('reason')}\t"
                  f"{'' if gap is None else f'{gap:.3f}'}")
    return 0


def _ctl_cluster_fragments(args, _json) -> int:
    """`ctl cluster fragments`: where each spanning job ACTUALLY runs.
    Reads the persisted fragment→worker placement straight off the meta
    store (offline-safe, no job recovery), then — when the cluster can
    be brought up (--workers, or inferred from the placements) — attaches
    live per-edge permit state from the workers' exchange counters."""
    import os
    from .meta.service import MetaService
    path = os.path.join(args.data_dir, "meta", "meta.jsonl")
    if not os.path.exists(path):
        raise SystemExit(f"{args.data_dir!r} holds no meta store")
    meta = MetaService(data_dir=os.path.join(args.data_dir, "meta"))
    placements = meta.all_placements()
    meta.store.close()
    for job, p in sorted(placements.items()):
        print(f"-- {job} (root worker {p.root_worker})")
        for fid in sorted(p.actors):
            for a in p.actors[fid]:
                print(f"Fragment {fid} actor {a.actor}: "
                      f"worker {a.worker} "
                      f"vnodes [{a.vnode_start}, {a.vnode_end})")
    if not placements:
        print("(no spanning jobs placed)")
        return 0
    # live per-edge permit state: recover the cluster and scrape the
    # workers' exchange counters (skipped if bring-up fails — the
    # persisted placement above is still authoritative for WHERE)
    args.workers = _infer_workers(args)
    try:
        session = _build_session(args)
    except Exception as e:  # noqa: BLE001 - offline dump already printed
        print(f"(live edge state unavailable: {type(e).__name__}: {e})")
        return 0
    try:
        edges = session.metrics().get("exchange") or []
        print("-- live exchange edges")
        if not edges:
            print("(none reported)")
        for e in edges:
            print(f"{e.get('edge')} [{e.get('dir')}] worker {e.get('worker')}"
                  f" -> peer {e.get('peer_worker')}: chunks={e.get('chunks')}"
                  f" bytes={e.get('bytes')}"
                  f" permits_waited={e.get('permits_waited')}"
                  f" backlog={e.get('backlog')}")
    finally:
        session.close()
    return 0


def _infer_workers(args) -> int:
    """Workers needed to bring the persisted cluster up: the explicit
    --workers, raised to cover every worker any persisted placement
    names (a spanning job must find its per-worker stores)."""
    import os
    from .meta.service import MetaService
    n_workers = args.workers
    path = os.path.join(args.data_dir, "meta", "meta.jsonl")
    if os.path.exists(path):
        meta = MetaService(data_dir=os.path.join(args.data_dir, "meta"))
        for p in meta.all_placements().values():
            n_workers = max(n_workers, max(p.workers()) + 1)
        meta.store.close()
    return n_workers


def _ctl_cluster_rescale(args, _json) -> int:
    """`ctl cluster rescale JOB --parallelism N`: recover the cluster
    from the durable dir, run the LIVE vnode migration (only the vnode
    ranges whose owner changes move, as handoff refs — docs/scaling.md),
    persist the new placement, and report what moved. Offline-safe in
    the sense that it owns the cluster for the duration; a deployment
    with its own live session must issue Session.rescale there instead."""
    if not args.job or not args.parallelism:
        raise SystemExit(
            "usage: ctl cluster rescale JOB --parallelism N --data-dir DIR")
    args.workers = max(_infer_workers(args), args.parallelism)
    session = _build_session(args)
    try:
        out = session.rescale(args.job, args.parallelism)
        session.flush()
        print(_json.dumps(out, indent=2, default=str))
    finally:
        session.close()
    return 0


def _ctl_cluster_autoscaler(args, _json) -> int:
    """`ctl cluster autoscaler`: dump the scaling plane's state —
    policy streaks/cooldowns per job, decisions taken, executed
    migrations and their moved vnode ranges (metrics()["autoscaler"])."""
    args.workers = _infer_workers(args)
    session = _build_session(args)
    try:
        print(_json.dumps(session.metrics().get("autoscaler", {}),
                          indent=2, default=str))
    finally:
        session.close()
    return 0


def _ctl_dispatch(args, session, _json) -> None:
    if args.what == "jobs":
        for kind, reg in (("TABLE", session.catalog.tables),
                          ("MV", session.catalog.mvs),
                          ("SOURCE", session.catalog.sources),
                          ("SINK", session.catalog.sinks)):
            for name in sorted(reg):
                print(f"{kind}\t{name}")
    elif args.what == "parameters":
        for k, v in session.parameters():
            print(f"{k}\t{v}")
    elif args.what == "fragments":
        from .meta.fragment import fragment_plan
        for name, mv in sorted(session.catalog.mvs.items()):
            ast = getattr(mv, "query_ast", None)
            if ast is None:
                continue
            # the SAME frontend pipeline the job was built with — the
            # printed topology must match the deployed executors
            plan = session._plan(ast)
            print(f"-- {name}")
            print(fragment_plan(plan).explain())
    elif args.what == "metrics":
        print(_json.dumps(session.metrics(), indent=2, default=str))
    elif args.what == "trace":
        if args.sub == "barrier":
            _ctl_trace_barrier(args, session, _json)
            return
        # await_tree() federates worker-hosted jobs' trees (and takes the
        # API lock) — a bare dump_session would print them as
        # "<remote; no stats snapshot yet>"
        print(session.await_tree())


def _ctl_trace_barrier(args, session, _json) -> None:
    """`ctl trace barrier [--inflight] [--json]`: the barrier
    observatory over a live session — waterfall history + per-stage
    percentiles, or (--inflight) live stuck-barrier blame naming the
    exact actors/links that have not acked (docs/observability.md)."""
    from .common.barrier_ledger import ALL_STAGES
    ledger = session._barrier_ledger
    if args.json:
        out = {"history": ledger.history(),
               "stages": ledger.stage_percentiles(),
               "summary": ledger.summary()}
        if args.inflight:
            out["inflight"] = session.barrier_blame()
        print(_json.dumps(out, indent=2, default=str))
        return
    if args.inflight:
        findings = session.barrier_blame()
        if not findings:
            print("no in-flight barriers (nothing to blame)")
            return
        print("epoch\tage_ms\tkind\tjob\tworker\tactor\tlink\treason")
        for f in findings:
            age = "" if f["age_ms"] is None else f"{f['age_ms']:.1f}"
            actor = "" if f["actor"] is None else \
                f"f{f['fragment']}a{f['actor']}"
            print(f"{f['epoch']}\t{age}\t{f['kind']}\t"
                  f"{f['job'] or ''}\t{f['worker']}\t{actor}\t"
                  f"{f['link'] or ''}\t{f['reason']}")
        return
    history = ledger.history()
    if not history:
        print("no completed barriers in the history ring")
        return
    print("epoch\tckpt\tresult\ttotal_ms\t"
          + "\t".join(f"{s}_ms" for s in ALL_STAGES))
    for rec in history:
        stages = rec["stages"]
        cells = "\t".join(
            f"{stages[s]:.2f}" if s in stages else "-"
            for s in ALL_STAGES)
        print(f"{rec['epoch']}\t{'y' if rec['checkpoint'] else 'n'}\t"
              f"{rec['result']}\t{rec['total_ms']:.2f}\t{cells}")
    print()
    print("stage\tp50_ms\tp99_ms\tn")
    percentiles = ledger.stage_percentiles()
    for stage in ALL_STAGES:
        pct = percentiles.get(stage)
        if pct is None:
            continue
        print(f"{stage}\t{pct['p50_ms']}\t{pct['p99_ms']}\t{pct['n']}")


def _playground(args) -> int:
    import asyncio
    from .frontend.pgwire import PgWireServer

    session = _build_session(args)

    async def run():
        auth = ({args.user: args.password}
                if getattr(args, "password", None) else None)
        server = PgWireServer(session, args.host, args.port, auth=auth)
        await server.start()
        print(f"risingwave_tpu playground listening on "
              f"{args.host}:{args.port}", flush=True)
        if getattr(args, "dashboard_port", None) is not None:
            from .frontend.dashboard import serve_dashboard
            dash = serve_dashboard(session, args.host, args.dashboard_port)
            print(f"dashboard on http://{args.host}:{dash.port}/",
                  flush=True)

        session.barrier_interval_ms = args.tick_interval_ms

        async def ticker():
            # the meta barrier tick (reference: GlobalBarrierManager
            # barrier_interval_ms, src/common/src/config.rs:595). Reads the
            # interval live so SET barrier_interval_ms takes effect; a tick
            # failure is logged and retried, never silently fatal.
            while True:
                await asyncio.sleep(session.barrier_interval_ms / 1000)
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        server._executor,
                        lambda: session.jobs and session.tick())
                except Exception as e:  # noqa: BLE001
                    print(f"barrier tick failed: {e}", file=sys.stderr,
                          flush=True)

        tick_task = asyncio.ensure_future(ticker())
        try:
            await server.serve_forever()
        finally:
            tick_task.cancel()
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
