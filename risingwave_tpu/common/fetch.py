"""Async device→host fetch futures — the pipelined tick's transfer seam.

JAX dispatch is asynchronous: a jitted epoch call returns immediately
with futures for its outputs while the device (or the CPU backend's
thread pool) keeps computing. The host tick loop used to throw that
overlap away by calling ``jax.device_get`` the moment an epoch's packed
stats existed — a blocking round trip that serializes host decode
behind device compute. This module is the one blessed crossing:

* ``async_fetch(tree)`` starts the device→host copy *now*
  (``jax.Array.copy_to_host_async``) and returns a ``FetchFuture``;
  the copy streams over DMA/PCIe while Python runs other work (another
  engine's dispatch, gather decode, checkpoint encode).
* ``FetchFuture.result()`` resolves to host numpy values — by the time
  a well-ordered tick calls it, the copy has usually already landed,
  so resolution costs a cache read instead of a round trip.
* ``fetch(tree)`` = ``async_fetch(tree).result()`` — the blocking form
  for call sites with no work to overlap; routing them through here
  keeps the tick path uniform and lets the ``sync-fetch-discipline``
  rwlint rule reason about exactly one module instead of every
  ``device_get`` spelling in the tree.

Profiler honesty rides along: a dispatch's wall time measured at
*enqueue* reads near-zero under async dispatch, so callers pass the
dispatch qualname (``dispatch=``) and ``result()`` reports the
enqueue→host-visible completion latency back to
``common/profiling.GLOBAL_PROFILER`` (``complete_seconds``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

__all__ = ["FetchFuture", "PendingFlush", "async_fetch", "fetch"]


def _start_copy(tree: Any) -> None:
    """Kick off the non-blocking device→host copy on every array leaf.
    Leaves without the async-copy surface (host numpy, scalars, older
    jax versions) simply resolve synchronously at ``result()``."""
    import jax

    def start(x):
        fn = getattr(x, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except RuntimeError:
                pass        # deleted/donated buffer: result() will raise
        return x

    jax.tree_util.tree_map(start, tree)


class FetchFuture:
    """One in-flight device→host copy of a pytree of arrays."""

    __slots__ = ("_tree", "_result", "_done", "_dispatch")

    def __init__(self, tree: Any, dispatch: Optional[str] = None):
        self._tree = tree
        self._result: Any = None
        self._done = False
        self._dispatch = dispatch
        _start_copy(tree)

    def done(self) -> bool:
        """True when every leaf's producing computation (and copy) has
        finished — never blocks."""
        if self._done:
            return True
        import jax
        ready = True
        for leaf in jax.tree_util.tree_leaves(self._tree):
            is_ready = getattr(leaf, "is_ready", None)
            if is_ready is not None and not is_ready():
                ready = False
                break
        return ready

    def result(self) -> Any:
        """Host numpy values (blocks until the copy lands; idempotent).
        The one legitimate device_get on the tick path lives here."""
        if not self._done:
            import jax
            self._result = jax.device_get(self._tree)
            self._done = True
            self._tree = None            # release device references
            if self._dispatch is not None:
                from .profiling import GLOBAL_PROFILER
                GLOBAL_PROFILER.note_complete(self._dispatch)
        return self._result


@dataclasses.dataclass
class PendingFlush:
    """One fused epoch's in-flight barrier flush — the handle both the
    co-scheduled (stream/coschedule.CoGroup) and the K×S sharded
    (parallel/fused.ShardedCoGroup) engines defer across ticks. The
    probe ran, its packed stats are streaming host-ward (``fetch``),
    and the gathers wait on the resolved counts against ``stacked`` —
    the PRE-finish state, kept alive here so the next epoch's
    (possibly donating) dispatch can launch against the separately
    allocated finished buffer while this flush is still pending."""

    stacked: object
    packed: object
    ranks: object
    fetch: FetchFuture


def async_fetch(tree: Any, dispatch: Optional[str] = None) -> FetchFuture:
    """Start fetching ``tree`` to the host; resolve later with
    ``.result()``. ``dispatch`` names the producing dispatch's profiler
    qualname so completion latency lands in its record."""
    return FetchFuture(tree, dispatch=dispatch)


def fetch(tree: Any, dispatch: Optional[str] = None) -> Any:
    """Blocking fetch through the async helper (start + resolve): the
    uniform spelling for tick-path sites with nothing to overlap."""
    return FetchFuture(tree, dispatch=dispatch).result()
