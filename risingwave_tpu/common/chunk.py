"""Columnar chunk format — the unit of dataflow.

TPU-first re-design of the reference's ``DataChunk``/``StreamChunk``
(reference: src/common/src/array/data_chunk.rs:59,
src/common/src/array/stream_chunk.rs:37-76): a chunk is a struct-of-arrays of
**fixed-capacity** device buffers plus a visibility mask, so every operator
step compiles once per (schema, capacity) and never again, regardless of how
many rows actually arrived (SURVEY.md §7 "Dynamic shapes vs XLA static
shapes").

Layout per chunk of capacity C:
  * ``ops``  int8[C]   — Insert / Delete / UpdateDelete / UpdateInsert
  * ``vis``  bool[C]   — row visibility (capacity padding ⇒ False)
  * per column: ``data`` dtype[C] and ``mask`` bool[C] (True = non-null)

UpdateDelete/UpdateInsert adjacency carries the same meaning as the
reference's stream-chunk op pairs (array/stream_chunk.rs:37-45): an update is
two adjacent rows with the same key.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .types import DataType, Schema

# Op codes (match the reference's Op enum order, array/stream_chunk.rs:37).
OP_INSERT = 0
OP_DELETE = 1
OP_UPDATE_DELETE = 2
OP_UPDATE_INSERT = 3

DEFAULT_CHUNK_CAPACITY = 1024


@struct.dataclass
class Column:
    data: jax.Array  # dtype[C]
    mask: jax.Array  # bool[C]; True = non-null


@struct.dataclass
class StreamChunk:
    """A batch of row-level change events (+ visibility padding)."""

    ops: jax.Array  # int8[C]
    vis: jax.Array  # bool[C]
    columns: tuple[Column, ...]

    # -- static views ---------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.ops.shape[0]

    def cardinality(self) -> jax.Array:
        """Number of visible rows (traced value)."""
        return jnp.sum(self.vis)

    # -- functional updates ---------------------------------------------------

    def with_vis(self, vis: jax.Array) -> "StreamChunk":
        return self.replace(vis=vis)

    def mask_vis(self, keep: jax.Array) -> "StreamChunk":
        return self.replace(vis=self.vis & keep)

    def project(self, indices: Sequence[int]) -> "StreamChunk":
        return self.replace(columns=tuple(self.columns[i] for i in indices))

    def with_columns(self, columns: Sequence[Column]) -> "StreamChunk":
        return self.replace(columns=tuple(columns))

    def append_columns(self, columns: Sequence[Column]) -> "StreamChunk":
        return self.replace(columns=self.columns + tuple(columns))

    # Insert/delete sign per row: +1 for Insert/UpdateInsert, -1 for
    # Delete/UpdateDelete, 0 for invisible. The universal "delta weight" used
    # by aggregation and materialization.
    def signs(self) -> jax.Array:
        pos = (self.ops == OP_INSERT) | (self.ops == OP_UPDATE_INSERT)
        return jnp.where(self.vis, jnp.where(pos, 1, -1).astype(jnp.int32), 0)


@struct.dataclass
class ChunkBatch:
    """K stacked StreamChunks — every array carries a leading [K] axis.

    The dispatch-amortization unit: one host→device dispatch covers K chunks
    (a ``lax.scan`` over the leading axis inside the consuming executor's
    jitted step), instead of K round-trips. Matters enormously when the
    device is reached over a network tunnel where each dispatch costs
    milliseconds. Stateless executors transform the whole batch with one
    vmapped step; executors without a batched path fall back to per-chunk
    iteration (``at``)."""

    chunk: StreamChunk  # arrays: [K, C, ...]

    @property
    def num_chunks(self) -> int:
        return self.chunk.ops.shape[0]

    @property
    def chunk_capacity(self) -> int:
        return self.chunk.ops.shape[1]

    def at(self, i: int) -> StreamChunk:
        return jax.tree_util.tree_map(lambda x: x[i], self.chunk)


def stack_chunks(chunks: Sequence[StreamChunk]) -> ChunkBatch:
    return ChunkBatch(jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *chunks))


def make_chunk(
    schema: Schema,
    rows: Sequence[Sequence[Any]],
    ops: Optional[Sequence[int]] = None,
    capacity: int = DEFAULT_CHUNK_CAPACITY,
    physical: bool = False,
) -> StreamChunk:
    """Host constructor: python rows → padded device chunk.

    ``physical=True`` takes raw physical values (state-table storage form)
    and skips logical encoding — the recovery-reload fast path."""
    n = len(rows)
    if n > capacity:
        raise ValueError(f"{n} rows > capacity {capacity}")
    if ops is None:
        ops = [OP_INSERT] * n
    ops_arr = np.zeros(capacity, np.int8)
    ops_arr[:n] = np.asarray(list(ops), np.int8)
    vis = np.zeros(capacity, bool)
    vis[:n] = True
    cols = []
    for ci, field in enumerate(schema):
        t = field.type
        data = np.full(capacity, t.null_sentinel(), t.np_dtype)
        mask = np.zeros(capacity, bool)
        for ri, row in enumerate(rows):
            v = row[ci]
            if v is not None:
                data[ri] = v if physical else t.to_physical(v)
                mask[ri] = True
        cols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
    return StreamChunk(jnp.asarray(ops_arr), jnp.asarray(vis), tuple(cols))


def empty_chunk(schema: Schema, capacity: int = DEFAULT_CHUNK_CAPACITY) -> StreamChunk:
    return make_chunk(schema, [], capacity=capacity)


def physical_chunk(schema: Schema, rows: Sequence[Sequence[Any]],
                   capacity: int) -> StreamChunk:
    """Rows of raw *physical* values → chunk (see make_chunk(physical=True))."""
    return make_chunk(schema, rows, capacity=capacity, physical=True)


def chunk_to_rows(
    chunk: StreamChunk, schema: Schema, with_ops: bool = False,
    physical: bool = False,
) -> list:
    """Device chunk → visible python rows (host sync; tests & egress only).

    ``physical=True`` skips logical decoding (dictionary lookups, decimal
    descaling) and returns raw physical scalars — the fast path for writing
    into state tables, which store physical values."""
    ops = np.asarray(chunk.ops)
    vis = np.asarray(chunk.vis)
    datas = [np.asarray(c.data) for c in chunk.columns]
    masks = [np.asarray(c.mask) for c in chunk.columns]
    out = []
    for i in range(chunk.capacity):
        if not vis[i]:
            continue
        if physical:
            row = tuple(
                datas[ci][i].item() if masks[ci][i] else None
                for ci in range(len(schema))
            )
        else:
            row = tuple(
                schema[ci].type.to_python(datas[ci][i]) if masks[ci][i] else None
                for ci in range(len(schema))
            )
        out.append((int(ops[i]), row) if with_ops else row)
    return out


def compact_chunk_host(chunk: StreamChunk) -> StreamChunk:
    """Pack visible rows to the front (host-side; not for jitted paths)."""
    vis = np.asarray(chunk.vis)
    idx = np.nonzero(vis)[0]
    cap = chunk.capacity
    pad = np.zeros(cap - len(idx), np.int64)
    sel = np.concatenate([idx, pad]).astype(np.int64)
    new_vis = np.zeros(cap, bool)
    new_vis[: len(idx)] = True
    return StreamChunk(
        jnp.asarray(np.asarray(chunk.ops)[sel]),
        jnp.asarray(new_vis),
        tuple(
            Column(jnp.asarray(np.asarray(c.data)[sel]), jnp.asarray(np.asarray(c.mask)[sel]))
            for c in chunk.columns
        ),
    )


def _update_units(chunk: StreamChunk):
    """Rows grouped into emission units: a visible U- immediately followed by
    a visible U+ forms one 2-row unit (the reference's chunk builder reserves
    two slots so update pairs never split across chunks,
    src/common/src/array/stream_chunk.rs:37-45); every other visible row is a
    1-row unit. Returns (unit_index int64[C], attached bool[C], unit_start)."""
    ops, vis = chunk.ops, chunk.vis
    prev_ud = jnp.concatenate([
        jnp.zeros(1, jnp.bool_),
        (ops[:-1] == OP_UPDATE_DELETE) & vis[:-1],
    ])
    attached = vis & (ops == OP_UPDATE_INSERT) & prev_ud
    unit_start = vis & ~attached
    unit_index = jnp.cumsum(unit_start) - 1  # valid where vis
    return unit_index, attached, unit_start


def count_units(chunk: StreamChunk) -> jax.Array:
    """Number of emission units in the chunk (jit-friendly scalar)."""
    _, _, unit_start = _update_units(chunk)
    return jnp.sum(unit_start)


def gather_units_window(chunk: StreamChunk, lo: jax.Array, out_capacity: int) -> StreamChunk:
    """Pack the units with index in [lo, lo + out_capacity//2) into a fresh
    chunk of ``out_capacity`` rows (2 slots per unit; vis masks the gaps).

    Pure and shape-static: drive from the host as
    ``for lo in range(0, int(count_units(c)), out_capacity//2)``."""
    G = out_capacity // 2
    C = out_capacity
    unit_index, attached, _ = _update_units(chunk)
    in_win = chunk.vis & (unit_index >= lo) & (unit_index < lo + G)
    pos = jnp.where(
        in_win, 2 * (unit_index - lo) + attached.astype(jnp.int64), C
    ).astype(jnp.int32)
    ops = jnp.zeros(C, jnp.int8).at[pos].set(chunk.ops, mode="drop")
    vis = jnp.zeros(C, jnp.bool_).at[pos].set(True, mode="drop")
    cols = tuple(
        Column(
            jnp.zeros(C, c.data.dtype).at[pos].set(c.data, mode="drop"),
            jnp.zeros(C, jnp.bool_).at[pos].set(c.mask, mode="drop"),
        )
        for c in chunk.columns
    )
    return StreamChunk(ops, vis, cols)


def flatten_shards(chunk: StreamChunk) -> StreamChunk:
    """A shard-batched chunk ([n, cap, ...] arrays) → ONE chunk of
    n*cap rows (row-major concat; vis already masks invalid rows). The
    sharded executors' egress path: one device op replaces the per-shard
    host slicing loop (VERDICT r3 item 9)."""
    def f(x):
        return x.reshape((-1,) + x.shape[2:])
    return jax.tree_util.tree_map(f, chunk)


def pad_chunk(chunk: StreamChunk, new_capacity: int) -> StreamChunk:
    """Grow a chunk's capacity with invisible padding rows (no-op if already
    at least ``new_capacity``)."""
    cap = chunk.capacity
    if cap >= new_capacity:
        return chunk
    extra = new_capacity - cap

    def pad(a):
        return jnp.concatenate([a, jnp.zeros((extra,) + a.shape[1:], a.dtype)])

    return StreamChunk(
        pad(chunk.ops), pad(chunk.vis),
        tuple(Column(pad(c.data), pad(c.mask)) for c in chunk.columns),
    )


def concat_rows(chunks: Iterable[StreamChunk], schema: Schema) -> list:
    rows = []
    for c in chunks:
        rows.extend(chunk_to_rows(c, schema))
    return rows
