"""Device-side hashing and vnode partitioning.

The reference partitions rows by ``Crc32(dist_key) % 256`` virtual nodes
(reference: src/common/src/hash/consistent_hash/vnode.rs:34,54-56) and builds
vectorized hash keys for group-by/join (src/common/src/hash/key.rs:293). Here
both are pure jnp functions over column arrays so they fuse into the operator
step: a 64-bit mix (splitmix64 finalizer) combined across key columns, then
reduced to a vnode index. Exact CRC32 compatibility is not needed — vnode
assignment only has to be deterministic within this system.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .chunk import Column

VNODE_COUNT = 256  # reference: vnode.rs:54 (2^8 vnodes)

_U64 = jnp.uint64


def _splitmix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer — cheap, high-quality 64-bit mixer (public domain)."""
    x = x.astype(_U64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15)) & jnp.uint64(0xFFFFFFFFFFFFFFFF)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return x


def hash_column(data: jax.Array, mask: jax.Array) -> jax.Array:
    """uint64 hash of one column; nulls hash to a fixed tag."""
    if data.dtype == jnp.bool_:
        raw = data.astype(jnp.uint64)
    elif jnp.issubdtype(data.dtype, jnp.floating):
        # Hash the bit pattern; normalize -0.0 to 0.0 first so they collide.
        f = jnp.where(data == 0, jnp.zeros_like(data), data)
        bits = jax.lax.bitcast_convert_type(
            f.astype(jnp.float32), jnp.uint32
        ).astype(jnp.uint64)
        raw = bits
    else:
        raw = data.astype(jnp.int64).astype(jnp.uint64)
    h = _splitmix64(raw)
    null_h = jnp.uint64(0xA5A5A5A55A5A5A5A)
    return jnp.where(mask, h, null_h)


def hash_columns(cols: Sequence[Column]) -> jax.Array:
    """Combine per-column hashes into one uint64 key hash per row."""
    h = jnp.uint64(0x243F6A8885A308D3)  # pi fraction seed
    for c in cols:
        hc = hash_column(c.data, c.mask)
        h = _splitmix64(h ^ hc)
    return h


def vnode_of(cols: Sequence[Column]) -> jax.Array:
    """Per-row vnode in [0, VNODE_COUNT) from the distribution-key columns."""
    return (hash_columns(cols) % jnp.uint64(VNODE_COUNT)).astype(jnp.int32)


def vnode_to_shard(vnode: jax.Array, num_shards: int) -> jax.Array:
    """vnode → parallel shard. Contiguous range mapping, the same scheme the
    reference's meta scheduler uses to hand vnode ranges to parallel units
    (docs/consistent-hash.md)."""
    per = VNODE_COUNT // num_shards
    return jnp.minimum(vnode // per, num_shards - 1).astype(jnp.int32)


def vnodes_of_rows(key_types: Sequence, key_rows: Sequence) -> list:
    """Host-side per-row vnode of key-value tuples, computed with the
    SAME device hash every dispatch path routes with (``vnode_of``), so
    migration filters, reload filters, and live routing can never
    disagree. ``key_rows`` holds just the distribution-key values, in
    key order."""
    import numpy as np

    key_rows = list(key_rows)
    out: list = []
    nk = len(key_types)
    bs = 1024
    for i in range(0, len(key_rows), bs):
        batch = key_rows[i:i + bs]
        cols = []
        for c in range(nk):
            vals = [r[c] for r in batch]
            data = np.array([v if v is not None else 0 for v in vals],
                            dtype=key_types[c].np_dtype)
            mask = np.array([v is not None for v in vals])
            cols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
        out.extend(int(v) for v in np.asarray(vnode_of(cols)))
    return out


def filter_rows_vnodes(key_types: Sequence, rows: Sequence,
                       vnode_start: int, vnode_end: int,
                       key_indices: Sequence[int] = None) -> list:
    """Keep rows whose distribution key hashes into ``[vnode_start,
    vnode_end)`` — the live-migration row filter (meta/rescale.py moves,
    HashAggExecutor ``load_vnodes`` reload, worker root-scan slices).
    ``key_indices`` names the key columns inside each row (default: the
    first ``len(key_types)`` columns)."""
    rows = list(rows)
    if vnode_start <= 0 and vnode_end >= VNODE_COUNT:
        return rows
    idx = (list(range(len(key_types))) if key_indices is None
           else list(key_indices))
    vns = vnodes_of_rows(key_types, [[r[i] for i in idx] for r in rows])
    return [r for r, vn in zip(rows, vns)
            if vnode_start <= vn < vnode_end]


def shard_rows(key_types: Sequence, rows: Sequence, n_shards: int) -> list:
    """Host-side partition of key-prefixed rows by the SAME vnode mapping
    the device paths route with (``vnode_of → vnode_to_shard``): returns
    ``n_shards`` row lists. Shared by every reload/re-shard surface
    (stream/hash_agg.py shard filtering, parallel/fused.py recovery) so
    durable-row placement can never diverge from live routing."""
    rows = list(rows)
    out: list[list] = [[] for _ in range(n_shards)]
    per = VNODE_COUNT // n_shards  # == vnode_to_shard's contiguous map
    for r, vn in zip(rows, vnodes_of_rows(key_types, rows)):
        out[min(vn // per, n_shards - 1)].append(r)
    return out
