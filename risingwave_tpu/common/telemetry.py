"""Telemetry: anonymous usage reporting (disabled by default).

Counterpart of the reference's telemetry subsystem
(reference: src/common/src/telemetry/ — manager.rs collects node/system
stats on an interval and report.rs posts them; per-node impls e.g.
src/meta/src/telemetry.rs). This build collects the same shape of report
but never transmits anywhere: there is no egress in the target
environment, so ``TelemetryManager.report()`` hands the dict to an
injectable sink (default: in-memory list) — the transmission layer is the
deployment's concern.
"""

from __future__ import annotations

import dataclasses
import platform
import time
import uuid
from typing import Any, Callable, List, Optional


@dataclasses.dataclass
class TelemetryReport:
    tracking_id: str
    session_id: str
    up_time_s: float
    system: dict
    job_counts: dict

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class TelemetryManager:
    def __init__(self, enabled: bool = False,
                 sink: Optional[Callable[[dict], None]] = None):
        self.enabled = enabled
        self.tracking_id = str(uuid.uuid4())
        self.session_id = str(uuid.uuid4())
        self.started_at = time.time()
        self.reports: List[dict] = []
        self._sink = sink or self.reports.append

    def report(self, session=None) -> Optional[dict]:
        """Collect one report and hand it to the sink; None if disabled."""
        if not self.enabled:
            return None
        job_counts: dict = {}
        if session is not None:
            job_counts = {
                "tables": len(session.catalog.tables),
                "materialized_views": len(session.catalog.mvs),
                "sources": len(session.catalog.sources),
                "sinks": len(session.catalog.sinks),
            }
        r = TelemetryReport(
            tracking_id=self.tracking_id,
            session_id=self.session_id,
            up_time_s=time.time() - self.started_at,
            system={
                "python": platform.python_version(),
                "machine": platform.machine(),
            },
            job_counts=job_counts,
        ).as_dict()
        self._sink(r)
        return r
