"""Device profiling plane: per-dispatch cost/memory telemetry.

Counterpart of the reference's compute-node profiling surface
(reference: src/compute/src/rpc/service/monitor_service.rs profiling
handlers + src/common/src/estimate_size/ feeding eviction decisions).
The TPU-native variant is XLA-shaped: the unit of work is a *dispatch*
(one jitted epoch callable entering XLA), so the plane hangs off the
same qualnames ``common/dispatch_count.py`` and the
``EPOCH_BUILDERS``/``SHARDED_EPOCH_BUILDERS`` registries already key —

* ``DispatchProfiler`` / ``GLOBAL_PROFILER``: every builder in
  ops/fused_epoch.py, ops/fused_multi.py, ops/fused_sharded.py and the
  barrier-step jits in parallel/fused.py returns its jitted callable
  through ``profile_dispatch(jitted, qualname)``. The wrapper is pure
  host Python — it adds ZERO dispatches (the same reason
  count_dispatches' wrapper counts correctly) — and records per call:
  wall seconds (cumulative device-occupancy proxy on the synchronous
  CPU stand-in; enqueue latency on an async TPU backend), a
  jit-cache-miss/recompile event when the underlying executable cache
  grew during the call (compile seconds = that call's wall time), and
  a ``cat="dispatch"`` span into the PR-1 Chrome trace ring tagged
  with the current epoch — a slow epoch attributes to the dispatch
  that caused it.
* AOT cost/memory analysis: the first call through a wrapper snapshots
  the argument *avals* (ShapeDtypeStructs — no device buffers are
  retained), so ``analyze()`` can later ``.lower().compile()`` the
  already-traced callable and read XLA's static ``cost_analysis()``
  flops / bytes-accessed and ``memory_analysis()`` temp/arg/output
  bytes — chip-free on the CPU stand-in, for-real on TPU.
* ``hbm_ledger``: the cluster-wide memory ledger — per-job/per-executor
  state bytes (common/memory.py walks, federated from workers through
  the existing stats frame) summed with the analyzed peak temp bytes
  against ``[observability] hbm_capacity_bytes``, reporting headroom
  and flagging jobs approaching eviction-budget territory.
* ``roofline_report``: arithmetic intensity (flops / bytes accessed)
  of each analyzed kernel against configurable chip peak flops and
  HBM bandwidth — the artifact ROADMAP item 1's "measured roofline
  analysis" demands (``ctl profile roofline``).
* ``load_bench_history`` / ``bench_trend``: fold the checked-in
  BENCH_r*.json + BENCH_partial.json records into a per-field trend
  with regression flags (``ctl bench trend``) — ROADMAP item 5's
  "regressions in ANY plane show up as a trend".
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import threading
import time
from typing import Any, Callable, Optional

from .tracing import CAT_DISPATCH, GLOBAL_TRACE, Span


class DispatchRecord:
    """Telemetry for one dispatch qualname (mutated lock-free on the
    hot path — single attribute bumps under the GIL).

    Two clocks per dispatch (profiler honesty under async dispatch):
    ``total_s``/``last_s``/``max_s`` time the ENQUEUE call — on an
    asynchronous backend (TPU always; the CPU stand-in's thread pool
    mostly) that is dispatch-submission latency and reads near-zero
    under pipelining. ``complete_s`` is the enqueue→host-visible wall
    time, resolved when a ``common/fetch.py`` future over the
    dispatch's outputs lands — an upper bound on device latency that
    includes any host think-time the pipeline deliberately overlapped.
    """

    __slots__ = ("name", "calls", "total_s", "last_s", "max_s",
                 "compiles", "compile_s", "complete_calls", "complete_s",
                 "complete_last_s", "inflight")

    #: enqueue timestamps awaiting a completion callback; bounded so
    #: dispatches whose outputs are never fetched cannot grow it
    INFLIGHT_CAP = 8

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.total_s = 0.0
        self.last_s = 0.0
        self.max_s = 0.0
        self.compiles = 0
        self.compile_s = 0.0
        self.complete_calls = 0
        self.complete_s = 0.0
        self.complete_last_s = 0.0
        self.inflight: list = []

    def to_dict(self) -> dict:
        d = {"calls": self.calls,
             "total_s": round(self.total_s, 6),
             "last_ms": round(self.last_s * 1e3, 4),
             "max_ms": round(self.max_s * 1e3, 4),
             "mean_ms": round(self.total_s / self.calls * 1e3, 4)
             if self.calls else 0.0,
             "compiles": self.compiles,
             "compile_s": round(self.compile_s, 4)}
        if self.complete_calls:
            d["complete_calls"] = self.complete_calls
            d["complete_s"] = round(self.complete_s, 6)
            d["complete_last_ms"] = round(self.complete_last_s * 1e3, 4)
            d["complete_mean_ms"] = round(
                self.complete_s / self.complete_calls * 1e3, 4)
        return d


def _aval(x: Any) -> Any:
    """Arg → ShapeDtypeStruct for AOT lowering (device buffers must not
    be retained by the profiler); non-array args (static ints, None)
    pass through for static_argnums."""
    if hasattr(x, "shape") and hasattr(x, "dtype") \
            and not isinstance(x, (bool, int, float)):
        import jax
        sharding = getattr(x, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=sharding)
        except Exception:  # noqa: BLE001 - e.g. committed=False shardings
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


class DispatchProfiler:
    """Process-global dispatch telemetry registry.

    Enabled by default: the hot path per dispatch is one enabled check,
    two ``perf_counter`` reads, an executable-cache-size probe and a
    handful of attribute bumps — microseconds against a dispatch that
    crosses into XLA. ``[observability] profiling = false`` turns the
    wrapper into a single-attribute-check passthrough."""

    def __init__(self):
        self.enabled = True
        #: dispatch spans shorter than this skip the trace ring
        #: ([observability] dispatch_span_min_ms)
        self.span_min_ms = 0.0
        #: current epoch tag for dispatch spans (set by Session.tick)
        self.epoch: Optional[int] = None
        self._records: dict[str, DispatchRecord] = {}
        #: qualname -> (lowerable, arg avals, kwarg avals) for AOT
        self._lowerable: dict[str, tuple] = {}
        self._analyses: dict[str, dict] = {}
        self._lock = threading.Lock()
        #: async-pipeline occupancy: completions observed via
        #: note_complete, and the max number of enqueued-but-unresolved
        #: dispatches of one qualname seen at a resolve (a depth-2
        #: pipeline reads 2 here while the synchronous path reads 1)
        self.completions = 0
        self.max_inflight = 0

    # -- hot path --------------------------------------------------------------

    def wrap(self, jitted: Callable, name: Optional[str] = None) -> Callable:
        """Instrument one jitted callable. The wrapper forwards the AOT
        surface (``.lower``/``.trace``) exactly like count_dispatches'
        wrapper, so the two compose in either order and
        tests/test_pallas_compile.py keeps lowering through it."""
        name = name or getattr(jitted, "__qualname__",
                               getattr(jitted, "__name__", repr(jitted)))
        # the executable cache lives on the innermost real jit object
        # (wrap may sit on top of a count_dispatches wrapper)
        inner = jitted
        while hasattr(inner, "__wrapped_jit__"):
            inner = inner.__wrapped_jit__
        cache_size = getattr(inner, "_cache_size", None)
        profiler = self

        def wrapper(*args, **kwargs):
            if not profiler.enabled:
                return jitted(*args, **kwargs)
            rec = profiler._records.get(name)
            if rec is None:
                rec = profiler._record(name)
            if name not in profiler._lowerable:
                profiler._remember_aval(name, jitted, args, kwargs)
            before = cache_size() if cache_size is not None else None
            ts = time.time()
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            dt = time.perf_counter() - t0
            rec.calls += 1
            # enqueue timestamp for completion latency (resolved when a
            # fetch future over this dispatch's outputs lands)
            if len(rec.inflight) < DispatchRecord.INFLIGHT_CAP:
                rec.inflight.append(t0)
            rec.total_s += dt
            rec.last_s = dt
            if dt > rec.max_s:
                rec.max_s = dt
            if before is not None and cache_size() > before:
                rec.compiles += 1
                rec.compile_s += dt
            elif before is None and rec.calls == 1:
                rec.compiles += 1       # no cache probe: first call compiles
                rec.compile_s += dt
            if dt * 1e3 >= profiler.span_min_ms:
                GLOBAL_TRACE.record(Span(
                    name, CAT_DISPATCH, ts, dt, epoch=profiler.epoch,
                    tid="dispatch"))
            return out

        wrapper.__qualname__ = name
        wrapper.__name__ = name.rsplit(".", 1)[-1]
        wrapper.lower = getattr(jitted, "lower", None)
        wrapper.trace = getattr(jitted, "trace", None)
        wrapper.__wrapped_jit__ = jitted
        return wrapper

    def _record(self, name: str) -> DispatchRecord:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = DispatchRecord(name)
            return rec

    def note_complete(self, name: str) -> None:
        """A fetch future over ``name``'s outputs just resolved: record
        enqueue→host-visible latency against the OLDEST outstanding
        enqueue (FIFO matches the per-qualname dispatch order) and the
        pipeline occupancy at resolve time (common/fetch.py calls this;
        attribute bumps only, safe under the GIL)."""
        if not self.enabled:
            return
        rec = self._records.get(name)
        if rec is None or not rec.inflight:
            return
        depth = len(rec.inflight)
        dt = time.perf_counter() - rec.inflight.pop(0)
        rec.complete_calls += 1
        rec.complete_s += dt
        rec.complete_last_s = dt
        self.completions += 1
        if depth > self.max_inflight:
            self.max_inflight = depth

    def pipeline_stats(self) -> dict:
        """Occupancy snapshot for the async epoch pipeline."""
        return {"completions": self.completions,
                "max_inflight": self.max_inflight}

    def _remember_aval(self, name, jitted, args, kwargs) -> None:
        """Snapshot abstract arg shapes for later AOT analysis. No
        device buffers are retained, and the callable itself is held
        only weakly — a dropped engine's compiled executables must not
        live forever in the profiler."""
        try:
            import weakref

            import jax
            ref = weakref.ref(jitted)
            a = jax.tree_util.tree_map(_aval, args)
            k = jax.tree_util.tree_map(_aval, kwargs)
        except Exception:  # noqa: BLE001 - telemetry must never fail a job
            return
        with self._lock:
            self._lowerable.setdefault(name, (ref, a, k))

    # -- AOT cost / memory analysis --------------------------------------------

    def analyze(self, name: Optional[str] = None,
                force: bool = False) -> dict:
        """AOT-``lower().compile()`` recorded callables and read XLA's
        static cost/memory analysis. Expensive (a fresh compile per
        qualname) — run on demand (``ctl profile roofline``,
        ``Session.profile_report()``), never on the barrier path.
        Results are cached per qualname."""
        names = [name] if name is not None else list(self._lowerable)
        out: dict = {}
        for n in names:
            if not force and n in self._analyses:
                out[n] = self._analyses[n]
                continue
            entry = self._lowerable.get(n)
            if entry is None:
                continue
            ref, args, kwargs = entry
            jitted = ref()
            if jitted is None:          # engine dropped since recording
                out[n] = {"error": "callable no longer alive"}
                continue
            try:
                out[n] = self._analyses[n] = aot_analysis(
                    jitted, *args, **kwargs)
            except Exception as e:  # noqa: BLE001 - analysis is best-effort
                out[n] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def analyses(self) -> dict:
        """Completed analyses only (no recompiles triggered)."""
        return dict(self._analyses)

    def peak_temp_bytes(self) -> int:
        """Largest analyzed per-dispatch temp allocation — the scratch
        HBM one in-flight epoch needs on top of resident state."""
        return max((a.get("memory", {}).get("temp_bytes", 0)
                    for a in self._analyses.values()
                    if isinstance(a, dict)), default=0)

    # -- snapshots -------------------------------------------------------------

    def counts(self) -> dict:
        """{qualname: calls} — the live twin of count_dispatches."""
        return {n: r.calls for n, r in self._records.items()}

    def snapshot(self) -> dict:
        """Full per-qualname telemetry + any completed analyses."""
        out = {}
        for n, r in sorted(self._records.items()):
            d = r.to_dict()
            a = self._analyses.get(n)
            if a is not None and "error" not in a:
                d["cost"] = a.get("cost")
                d["memory"] = a.get("memory")
            out[n] = d
        return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._lowerable.clear()
            self._analyses.clear()
            self.completions = 0
            self.max_inflight = 0


#: the process-global registry every profiled dispatch site records to
GLOBAL_PROFILER = DispatchProfiler()


def profile_dispatch(jitted: Callable,
                     name: Optional[str] = None) -> Callable:
    """Instrument a jitted epoch/barrier-step callable against the
    process-global profiler (the seam ops/ and parallel/ builders
    return through)."""
    return GLOBAL_PROFILER.wrap(jitted, name)


def aot_analysis(jitted: Callable, *args, **kwargs) -> dict:
    """``.lower().compile()`` an already-traced callable (args may be
    ShapeDtypeStructs) and extract XLA's static analyses:

    * ``cost`` — flops + bytes accessed (→ arithmetic intensity)
    * ``memory`` — argument/output/temp/generated-code bytes (the temp
      figure is the per-dispatch HBM scratch the ledger charges)
    """
    lower = getattr(jitted, "lower", None)
    if lower is None:
        raise TypeError(f"{jitted!r} has no .lower AOT surface")
    compiled = lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):       # older jax returns [dict]
        ca = ca[0] if ca else {}
    cost = {"flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0)}
    mem: dict = {}
    ma = compiled.memory_analysis()
    if ma is not None:
        mem = {
            "arg_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "out_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
            "code_bytes": int(
                getattr(ma, "generated_code_size_in_bytes", 0)),
        }
    return {"cost": cost, "memory": mem}


def per_job_attribution(total_seconds: float, weights: dict) -> dict:
    """Split one fused dispatch qualname's measured wall seconds over
    its member jobs (the tick compiler's padded supergroups and
    mega-epochs run MANY jobs inside one dispatch record, so per-job
    cost must be attributed, not measured).

    ``weights``: {job: weight} — the per-job work proxy carried in the
    extended [J, 3] packed-stats layout (cumulative flushed-group
    counts, packed slot 0). Jobs with zero observed weight across the
    board fall back to an equal split; the result is an ESTIMATE
    (proportional model), not a per-job measurement."""
    jobs = list(weights)
    if not jobs:
        return {}
    total_w = float(sum(weights.values()))
    if total_w <= 0:
        share = float(total_seconds) / len(jobs)
        return {j: round(share, 9) for j in jobs}
    return {j: round(float(total_seconds) * float(w) / total_w, 9)
            for j, w in weights.items()}


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------


def hbm_ledger(jobs: dict, capacity_bytes: int,
               peak_temp_bytes: int = 0,
               warn_fraction: float = 0.8) -> dict:
    """Cluster-wide HBM ledger. ``jobs``: {job: {"bytes": total,
    "executors": {ident: bytes}, "worker": wid-or-None}} — the federated
    per-job/per-executor state-bytes snapshot (common/memory.py walks,
    session + every worker). Resident state plus the analyzed peak
    per-dispatch temp bytes is charged against ``capacity_bytes``;
    a job whose own state + the peak temp reaches ``warn_fraction`` of
    capacity is flagged (eviction-budget territory: time to set
    agg_hbm_budget/join_hbm_budget or shard the job)."""
    capacity = int(capacity_bytes)
    state_total = sum(int(j.get("bytes", 0)) for j in jobs.values())
    used = state_total + int(peak_temp_bytes)
    flagged = sorted(
        name for name, j in jobs.items()
        if capacity > 0 and
        int(j.get("bytes", 0)) + peak_temp_bytes >= warn_fraction * capacity)
    return {
        "capacity_bytes": capacity,
        "state_bytes": state_total,
        "peak_temp_bytes": int(peak_temp_bytes),
        "used_bytes": used,
        "headroom_bytes": capacity - used,
        "utilization": round(used / capacity, 6) if capacity else 0.0,
        "warn_fraction": warn_fraction,
        "jobs": {name: dict(j) for name, j in sorted(jobs.items())},
        "flagged": flagged,
    }


# ---------------------------------------------------------------------------
# roofline
# ---------------------------------------------------------------------------


def roofline_report(analyses: dict, peak_flops: float,
                    peak_bandwidth: float) -> dict:
    """Place each analyzed kernel on the roofline: arithmetic intensity
    = flops / bytes accessed; attainable flops = min(peak,
    intensity · bandwidth); ``bound`` says which wall the kernel sits
    under. ``analyses``: {qualname: aot_analysis() result}."""
    critical = peak_flops / peak_bandwidth if peak_bandwidth else 0.0
    kernels: dict = {}
    for name, a in sorted(analyses.items()):
        if not isinstance(a, dict) or "error" in a:
            kernels[name] = {"error": (a or {}).get("error", "unanalyzed")}
            continue
        flops = a["cost"]["flops"]
        nbytes = a["cost"]["bytes_accessed"]
        intensity = flops / nbytes if nbytes else 0.0
        attainable = min(peak_flops, intensity * peak_bandwidth) \
            if peak_bandwidth else peak_flops
        kernels[name] = {
            "flops": flops,
            "bytes_accessed": nbytes,
            "intensity": round(intensity, 4),
            "bound": ("compute" if critical and intensity >= critical
                      else "memory"),
            "attainable_flops": attainable,
            "pct_of_peak_flops": round(100.0 * attainable / peak_flops, 3)
            if peak_flops else 0.0,
            "memory": a.get("memory", {}),
        }
    return {
        "peak_flops": peak_flops,
        "peak_bandwidth_bytes_per_s": peak_bandwidth,
        "critical_intensity": round(critical, 4),
        "kernels": kernels,
    }


def render_roofline_table(report: dict) -> str:
    rows = [("kernel", "gflops", "mbytes", "flops/byte", "bound",
             "% of peak")]
    for name, k in report["kernels"].items():
        if "error" in k:
            rows.append((name, "-", "-", "-", "error", k["error"]))
            continue
        rows.append((name,
                     f"{k['flops'] / 1e9:.3f}",
                     f"{k['bytes_accessed'] / 1e6:.3f}",
                     f"{k['intensity']:.3f}",
                     k["bound"],
                     f"{k['pct_of_peak_flops']:.3f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.append(
        f"(peak {report['peak_flops'] / 1e12:.1f} TFLOP/s, "
        f"{report['peak_bandwidth_bytes_per_s'] / 1e9:.0f} GB/s, "
        f"critical intensity {report['critical_intensity']:.1f} "
        "flops/byte)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# bench trend
# ---------------------------------------------------------------------------

#: substrings marking a field where LOWER is better (latency-like);
#: everything else numeric is treated as higher-is-better (rates)
_LOWER_BETTER = ("p50", "p90", "p99", "latency", "pause", "_ms",
                 "duration", "seconds")


def _lower_is_better(field: str) -> bool:
    f = field.lower()
    return any(m in f for m in _LOWER_BETTER)


def _numeric_fields(rec: dict, prefix: str = "") -> dict:
    out: dict = {}
    for k, v in rec.items():
        if isinstance(v, bool) or k in ("n", "rc"):
            continue
        if isinstance(v, (int, float)):
            out[prefix + k] = float(v)
        elif isinstance(v, dict):
            out.update(_numeric_fields(v, prefix + k + "."))
    return out


def load_bench_history(root: str = ".") -> list:
    """Checked-in bench records, oldest first: every BENCH_r*.json
    round (its ``parsed`` payload) plus every completed phase line in
    BENCH_partial.json. Each entry: {"label", "ok", "fields"}."""
    history: list = []
    for path in sorted(_glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") or {}
        history.append({
            "label": f"r{m.group(1)}" if m else os.path.basename(path),
            "ok": rec.get("rc") == 0,
            "fields": _numeric_fields(parsed) if isinstance(parsed, dict)
            else {},
        })
    partial = os.path.join(root, "BENCH_partial.json")
    if os.path.exists(partial):
        with open(partial) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                payload = rec.get("record") or {}
                history.append({
                    "label": f"partial:{rec.get('phase', i)}",
                    "ok": payload.get("rc", 0) in (0, None),
                    "fields": _numeric_fields(payload)
                    if isinstance(payload, dict) else {},
                })
    return history


def bench_trend(history: list, tolerance: float = 0.2) -> dict:
    """Per-field trend over the bench history with regression flags: the
    LAST reported value of a field is compared against the BEST earlier
    value; a >``tolerance`` relative move in the bad direction (down for
    rates, up for latencies) flags the field. Rounds that failed
    (``ok`` false) still contribute whatever fields they salvaged."""
    series: dict = {}
    for entry in history:
        for field, value in entry["fields"].items():
            series.setdefault(field, []).append((entry["label"], value))
    fields: dict = {}
    regressions: list = []
    for field, points in sorted(series.items()):
        values = [v for _, v in points]
        latest_label, latest = points[-1]
        lower_better = _lower_is_better(field)
        entry = {
            "points": [{"label": l, "value": v} for l, v in points],
            "latest": latest,
            "best": min(values) if lower_better else max(values),
            "lower_is_better": lower_better,
            "regressed": False,
        }
        if len(points) > 1:
            prior = values[:-1]
            best_prior = min(prior) if lower_better else max(prior)
            if lower_better:
                regressed = best_prior > 0 and \
                    latest > best_prior * (1 + tolerance)
            else:
                regressed = best_prior > 0 and \
                    latest < best_prior * (1 - tolerance)
            if regressed:
                entry["regressed"] = True
                entry["vs_best"] = round(latest / best_prior, 4)
                regressions.append(field)
        fields[field] = entry
    return {"rounds": [e["label"] for e in history],
            "tolerance": tolerance,
            "fields": fields,
            "regressions": regressions}


def render_trend_table(trend: dict) -> str:
    rows = [("field", "points", "best", "latest", "flag")]
    for field, e in trend["fields"].items():
        flag = "REGRESSED" if e["regressed"] else ""
        rows.append((field, str(len(e["points"])),
                     f"{e['best']:.6g}", f"{e['latest']:.6g}", flag))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    if trend["regressions"]:
        lines.append(f"regressions (> {trend['tolerance']:.0%} off best): "
                     + ", ".join(trend["regressions"]))
    else:
        lines.append("no regressions flagged")
    return "\n".join(lines)
