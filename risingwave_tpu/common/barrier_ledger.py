"""Barrier observatory: per-barrier lifecycle ledger + stage events.

The paper's consistency spine is the Chandy-Lamport barrier (inject at
the conductor, collect across actors, 2PC checkpoint commit), and this
module makes every injected barrier individually accountable: a
cluster-wide waterfall record per epoch — inject → per-worker collect →
checkpoint prepare/settle/commit → sink delivery — kept in a bounded
history ring with p50/p99 per-stage aggregates (reference: the barrier
manager's inflight tracking + rw_catalog barrier tables,
src/meta/src/barrier/mod.rs:152 and
src/frontend/src/catalog/system_catalog/rw_catalog/).

Two pieces:

* ``StageEventLog`` — a process-global, bounded log of epoch-stamped
  stage events, written at the 2PC sites (storage/checkpoint.py,
  worker/host.py handle_barrier, stream/sink.py). In worker processes
  the log is drained onto the existing ``stats`` reply (a
  ``barrier_stages`` key with the same seq/ack outbox discipline as the
  span outbox), so stage events ride frames the session already sends —
  zero added dispatches, zero extra RPCs, nothing on the critical tick
  path beyond a perf_counter delta and a list append.

* ``BarrierLedger`` — the session-owned history ring. The conductor
  records its own stages (inject / pending / collect / commit) directly
  with perf_counter deltas; storage, sink and worker stages fold in from
  the stage-event logs (the session's own, synchronously at barrier
  completion; the workers', via stats federation — late events find
  their record in the ring and attach there).

Stage vocabulary (stable: Prometheus labels, rw_catalog columns and
bench trend fields all key on it):

    inject            conductor: queue pushes + remote barrier frames
    pending           conductor: injected, waiting its turn to complete
    collect           conductor: awaiting every actor/worker ack
    commit            conductor: cluster checkpoint phase 2
    storage_prepare   any process: DurableStateStore.prepare (phase 1)
    storage_settle    any process: prepared→committed settle
    storage_commit    any process: segment append (epoch encode+publish)
    sink_deliver      sink executor: external delivery inside on_barrier
    worker_collect    worker conductor: its jobs' barrier collection
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

#: conductor-side stages whose sum reconciles with the epoch's total
#: barrier latency (inject is measured before the latency clock starts)
CONDUCTOR_STAGES = ("pending", "collect", "commit")

#: every stage the ledger may see, in waterfall order
ALL_STAGES = ("inject", "pending", "collect", "commit",
              "storage_prepare", "storage_settle", "storage_commit",
              "sink_deliver", "worker_collect")


class StageEventLog:
    """Process-global bounded log of ``{epoch, stage, ms}`` events with a
    seq/ack outbox for cross-process federation (mirrors the tracing-span
    outbox: a drained batch is retained until the session's next stats
    request acknowledges its sequence number)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._outbox: list = []
        self.seq = 0
        self._lock = threading.Lock()

    def record(self, epoch: int, stage: str, ms: float) -> None:
        with self._lock:
            self._events.append(
                {"epoch": int(epoch), "stage": stage, "ms": float(ms)})

    def drain(self) -> list:
        """Take-and-clear — the session consumes its own log this way at
        barrier completion."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def drain_outbox(self, ack: Optional[int] = None) -> tuple:
        """Worker side of federation: move fresh events into the retained
        outbox, clear it when ``ack`` matches the last shipped seq, and
        return ``(seq, events)`` for the stats reply."""
        with self._lock:
            if ack == self.seq:
                self._outbox = []
            fresh = list(self._events)
            self._events.clear()
            if fresh:
                self._outbox.extend(fresh)
                if len(self._outbox) > self.capacity:
                    del self._outbox[:-self.capacity]
                self.seq += 1
            return self.seq, list(self._outbox)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._outbox = []


#: the per-process stage-event log every 2PC site writes to
GLOBAL_STAGES = StageEventLog()


def record_stage(epoch: Optional[int], stage: str, ms: float) -> None:
    """Record one stage duration against an epoch (no-op without one —
    e.g. a store commit outside barrier conduction)."""
    if epoch is None or epoch <= 0:
        return
    GLOBAL_STAGES.record(epoch, stage, ms)


class timed_stage:
    """``with timed_stage(epoch, "storage_commit"):`` — perf_counter
    around the body, recorded into the process-global log."""

    def __init__(self, epoch: Optional[int], stage: str):
        self.epoch = epoch
        self.stage = stage

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_stage(self.epoch, self.stage,
                     (time.perf_counter() - self._t0) * 1e3)
        return False


class BarrierLedger:
    """Session-owned bounded history ring of per-barrier waterfall
    records, plus per-stage p50/p99 aggregates.

    A record::

        {"epoch": int, "checkpoint": bool, "injected_at": wall_ts,
         "total_ms": float, "result": "ok" | "failed",
         "stages": {stage: ms},              # summed across processes
         "workers": {wid: {stage: ms}}}      # per-process detail

    ``workers`` keys: -1 for the session process, worker_id otherwise.
    Late events (federated worker stages, deferred checkpoint encodes)
    find their record in the ring by epoch and attach there."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._open: dict[int, dict] = {}
        self._by_epoch: dict[int, dict] = {}
        self.total = {"ok": 0, "failed": 0}
        self._lock = threading.Lock()

    # -- assembly --------------------------------------------------------------

    def begin(self, epoch: int, checkpoint: bool, wall_ts: float) -> None:
        with self._lock:
            self._open[epoch] = {
                "epoch": int(epoch), "checkpoint": bool(checkpoint),
                "injected_at": wall_ts, "total_ms": None, "result": None,
                "stages": {}, "workers": {},
            }

    def _find(self, epoch: int) -> Optional[dict]:
        rec = self._open.get(epoch)
        if rec is None:
            rec = self._by_epoch.get(epoch)
        return rec

    def stage(self, epoch: int, stage: str, ms: float,
              worker: int = -1) -> None:
        """Accumulate one stage duration (summed on repeats: several
        storage commits or sinks in one epoch fold together)."""
        with self._lock:
            rec = self._find(epoch)
            if rec is None:
                return
            st = rec["stages"]
            st[stage] = st.get(stage, 0.0) + float(ms)
            per = rec["workers"].setdefault(int(worker), {})
            per[stage] = per.get(stage, 0.0) + float(ms)

    def ingest_events(self, events, worker: int = -1) -> None:
        """Fold a batch of stage-event dicts (a drained StageEventLog —
        the session's own, or one federated off a worker's stats
        reply)."""
        for ev in events or ():
            try:
                self.stage(int(ev["epoch"]), str(ev["stage"]),
                           float(ev["ms"]), worker=worker)
            except (KeyError, TypeError, ValueError):
                continue          # a malformed event must not fail stats

    def finish(self, epoch: int, total_ms: float,
               result: str = "ok") -> Optional[dict]:
        """Seal the epoch's record into the ring; returns the record."""
        with self._lock:
            rec = self._open.pop(epoch, None)
            if rec is None:
                return None
            rec["total_ms"] = round(float(total_ms), 3)
            rec["result"] = result
            self.total[result] = self.total.get(result, 0) + 1
            if len(self._ring) == self._ring.maxlen:
                old = self._ring[0]
                self._by_epoch.pop(old["epoch"], None)
            self._ring.append(rec)
            self._by_epoch[rec["epoch"]] = rec
            return rec

    def abandon(self, epoch: int) -> None:
        """Drop an open record (recovery discarded the epoch)."""
        with self._lock:
            self._open.pop(epoch, None)

    # -- readers ---------------------------------------------------------------

    def get(self, epoch: int) -> Optional[dict]:
        import copy
        with self._lock:
            rec = self._find(epoch)
            return copy.deepcopy(rec) if rec is not None else None

    def history(self) -> list:
        """Sealed records, oldest first (each a deep copy: callers may
        not mutate ring state)."""
        import copy
        with self._lock:
            return [copy.deepcopy(r) for r in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @staticmethod
    def _pct(sorted_vals: list, q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
        return sorted_vals[i]

    def stage_percentiles(self) -> dict:
        """{stage: {"p50_ms", "p99_ms", "n"}} over the ring (stages with
        no samples are omitted)."""
        with self._lock:
            samples: dict[str, list] = {}
            for rec in self._ring:
                for stage, ms in rec["stages"].items():
                    samples.setdefault(stage, []).append(ms)
        out = {}
        for stage, vals in samples.items():
            vals.sort()
            out[stage] = {"p50_ms": round(self._pct(vals, 0.5), 3),
                          "p99_ms": round(self._pct(vals, 0.99), 3),
                          "n": len(vals)}
        return out

    def summary(self) -> dict:
        """The metrics()/Prometheus section: result totals + per-stage
        percentiles + ring occupancy."""
        with self._lock:
            total = dict(self.total)
            n = len(self._ring)
        return {"total": total, "history_len": n,
                "history_capacity": self.capacity,
                "stages": self.stage_percentiles()}
