"""Host interchange: StreamChunk ↔ Arrow RecordBatch / numpy / DLPack.

Counterpart of the reference's Arrow bridge
(reference: src/common/src/array/arrow.rs:29-44 — bi-directional
DataChunk ↔ arrow RecordBatch, used by the UDF boundary and sinks) plus
the survey's DLPack note (SURVEY.md §2.1 Arrow-bridge row: "TPU
equivalent: zero-copy DLPack/jax.dlpack bridge").

Semantics at the boundary:
  * Arrow is a HOST logical format: VARCHAR ids decode to utf8, DECIMAL to
    decimal128, DATE/TIMESTAMP to date32/timestamp[us]; NULLs from masks.
  * DLPack is a DEVICE physical format: raw column buffers (dictionary ids
    included) move zero-copy into torch/numpy; masks travel alongside.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from .chunk import Column, StreamChunk, make_chunk
from .types import Schema, TypeKind


# -- Arrow -------------------------------------------------------------------

def _arrow_type(t, pa):
    k = t.kind
    if k == TypeKind.BOOL:
        return pa.bool_()
    if k == TypeKind.INT16:
        return pa.int16()
    if k == TypeKind.INT32:
        return pa.int32()
    if k in (TypeKind.INT64, TypeKind.SERIAL):
        return pa.int64()
    if k == TypeKind.FLOAT32:
        return pa.float32()
    if k == TypeKind.FLOAT64:
        return pa.float64()
    if k == TypeKind.DECIMAL:
        return pa.decimal128(38, t.scale)
    if k == TypeKind.DATE:
        return pa.date32()
    if k == TypeKind.TIME:
        return pa.time64("us")
    if k == TypeKind.TIMESTAMP:
        return pa.timestamp("us")
    if k == TypeKind.INTERVAL:
        return pa.duration("us")
    if k in (TypeKind.VARCHAR, TypeKind.BYTEA):
        return pa.string()
    raise TypeError(f"no arrow mapping for {k}")


def chunk_to_arrow(chunk: StreamChunk, schema: Schema,
                   with_ops: bool = False):
    """Visible rows of a chunk → pyarrow.RecordBatch (logical values)."""
    import pyarrow as pa
    import decimal as _dec
    vis = np.asarray(chunk.vis)
    idx = np.nonzero(vis)[0]
    arrays, fields = [], []
    if with_ops:
        ops = np.asarray(chunk.ops)[idx]
        arrays.append(pa.array(ops, pa.int8()))
        fields.append(pa.field("__op", pa.int8()))
    for f, col in zip(schema, chunk.columns):
        data = np.asarray(col.data)[idx]
        mask = np.asarray(col.mask)[idx]
        at = _arrow_type(f.type, pa)
        if f.type.is_string:
            vals = [f.type.to_python(v) if m else None
                    for v, m in zip(data, mask)]
            arrays.append(pa.array(vals, at))
        elif f.type.kind == TypeKind.DECIMAL:
            q = _dec.Decimal(1).scaleb(-f.type.scale)
            vals = [(_dec.Decimal(int(v)) * q) if m else None
                    for v, m in zip(data, mask)]
            arrays.append(pa.array(vals, at))
        else:
            arrays.append(pa.array(data, at, mask=~mask))
        fields.append(pa.field(f.name, at))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def arrow_to_chunk(batch, schema: Schema,
                   capacity: Optional[int] = None) -> StreamChunk:
    """pyarrow.RecordBatch → insert-op chunk (logical decode + intern)."""
    import datetime as _dt
    epoch_d = _dt.date(1970, 1, 1)
    epoch_ts = _dt.datetime(1970, 1, 1)
    rows: List[tuple] = []
    cols = [batch.column(f.name) for f in schema]
    for i in range(batch.num_rows):
        row = []
        for f, c in zip(schema, cols):
            v = c[i].as_py()
            if v is not None:
                k = f.type.kind
                if k == TypeKind.DECIMAL:
                    v = float(v)
                elif k == TypeKind.DATE:
                    v = (v - epoch_d).days
                elif k == TypeKind.TIMESTAMP:
                    v = (v.replace(tzinfo=None) - epoch_ts) \
                        // _dt.timedelta(microseconds=1)
                elif k == TypeKind.TIME:
                    v = ((v.hour * 60 + v.minute) * 60
                         + v.second) * 1_000_000 + v.microsecond
                elif k == TypeKind.INTERVAL:
                    v = v // _dt.timedelta(microseconds=1)
            row.append(v)
        rows.append(tuple(row))
    return make_chunk(schema, rows,
                      capacity=capacity or max(len(rows), 1))


# -- UDF boundary (columnar wire batches) ------------------------------------
#
# The out-of-process UDF plane (udf/client.py ↔ udf/server.py, ISSUE 15)
# moves argument/result batches over rpc/wire.py JSON frames in an
# Arrow-ish COLUMNAR encoding: fixed-width columns travel as raw
# little-endian buffers (base64 inside the frame), string columns as
# decoded utf-8 value lists (each process keeps its own dictionary — the
# same rule the worker wire uses), validity masks as raw bool buffers.
# NO pickle of user values ever crosses the wire; only the registration
# frame ships the function itself (udf/registry.py).

def _udf_wire_type(t) -> dict:
    return {"kind": t.kind.name, "scale": t.scale}


def udf_type_to_wire(t) -> dict:
    if t.is_list or t.is_struct:
        raise TypeError(
            f"{t.kind.name} cannot cross the UDF wire boundary (its "
            "values intern into a process-local dictionary); register "
            "the function under [udf] mode = \"inproc\" instead")
    return _udf_wire_type(t)


def udf_type_from_wire(d: dict):
    from .types import DataType, TypeKind
    return DataType(TypeKind[d["kind"]], d.get("scale", 0))


def udf_col_to_wire(data, mask, t) -> dict:
    """One LOGICAL host column → wire dict. ``data`` is a numpy array:
    object arrays carry already-decoded strings (str/None); any other
    dtype is the physical encoding — string-typed physical arrays
    (dictionary ids) are decoded here, masked-out slots to None."""
    import base64 as _b64
    mask = np.ascontiguousarray(np.asarray(mask, dtype=bool))
    out: dict = {"mask": _b64.b64encode(mask.tobytes()).decode()}
    data = np.asarray(data)
    if t.is_string:
        if data.dtype == object:
            vals = [v if (m and v is not None) else None
                    for v, m in zip(data, mask)]
        else:
            vals = [t.to_python(v) if m else None
                    for v, m in zip(data, mask)]
        out.update(enc="utf8", values=vals)
    else:
        buf = np.ascontiguousarray(data.astype(t.np_dtype, copy=False))
        out.update(enc="raw", dtype=buf.dtype.str,
                   data=_b64.b64encode(buf.tobytes()).decode())
    return out


def wire_to_udf_col(d: dict, t):
    """Wire dict → (data, mask) host column. String columns decode to
    object arrays of str/None; fixed-width to their physical dtype."""
    import base64 as _b64
    mask = np.frombuffer(_b64.b64decode(d["mask"]), dtype=bool).copy()
    if d["enc"] == "utf8":
        data = np.empty(len(mask), dtype=object)
        for i, v in enumerate(d["values"]):
            data[i] = v
        # a None value is a NULL regardless of what the mask said (the
        # server nulls rows whose function returned None)
        mask &= np.array([v is not None for v in d["values"]], dtype=bool)
    else:
        data = np.frombuffer(_b64.b64decode(d["data"]),
                             dtype=np.dtype(d["dtype"])).copy()
    return data, mask


def udf_batch_to_wire(datas: Sequence, masks: Sequence, types) -> dict:
    n = len(np.asarray(masks[0])) if masks else 0
    return {"n": n,
            "cols": [udf_col_to_wire(d, m, t)
                     for d, m, t in zip(datas, masks, types)]}


def wire_to_udf_batch(payload: dict, types):
    datas, masks = [], []
    for c, t in zip(payload["cols"], types):
        d, m = wire_to_udf_col(c, t)
        datas.append(d)
        masks.append(m)
    return datas, masks


# -- numpy / DLPack ----------------------------------------------------------

def chunk_to_numpy(chunk: StreamChunk) -> dict:
    """Physical host view: {'ops', 'vis', 'columns': [(data, mask), ...]}."""
    return {
        "ops": np.asarray(chunk.ops),
        "vis": np.asarray(chunk.vis),
        "columns": [(np.asarray(c.data), np.asarray(c.mask))
                    for c in chunk.columns],
    }


def column_to_dlpack(col: Column):
    """Zero-copy DLPack capsules for (data, mask) device buffers — consume
    with torch.utils.dlpack.from_dlpack / np.from_dlpack."""
    import jax
    return jax.dlpack.to_dlpack(col.data), jax.dlpack.to_dlpack(col.mask)


def column_to_torch(col: Column):
    """Column device buffers → torch tensors (zero-copy where the backend
    allows; TPU buffers transfer through host)."""
    import torch
    data = np.asarray(col.data)
    mask = np.asarray(col.mask)
    return torch.from_numpy(np.ascontiguousarray(data)), \
        torch.from_numpy(np.ascontiguousarray(mask))


def torch_to_column(data, mask=None) -> Column:
    import jax.numpy as jnp
    d = np.asarray(data.detach().cpu().numpy())
    m = (np.ones(d.shape, bool) if mask is None
         else np.asarray(mask.detach().cpu().numpy()).astype(bool))
    return Column(jnp.asarray(d), jnp.asarray(m))
