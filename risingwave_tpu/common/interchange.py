"""Host interchange: StreamChunk ↔ Arrow RecordBatch / numpy / DLPack.

Counterpart of the reference's Arrow bridge
(reference: src/common/src/array/arrow.rs:29-44 — bi-directional
DataChunk ↔ arrow RecordBatch, used by the UDF boundary and sinks) plus
the survey's DLPack note (SURVEY.md §2.1 Arrow-bridge row: "TPU
equivalent: zero-copy DLPack/jax.dlpack bridge").

Semantics at the boundary:
  * Arrow is a HOST logical format: VARCHAR ids decode to utf8, DECIMAL to
    decimal128, DATE/TIMESTAMP to date32/timestamp[us]; NULLs from masks.
  * DLPack is a DEVICE physical format: raw column buffers (dictionary ids
    included) move zero-copy into torch/numpy; masks travel alongside.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from .chunk import Column, StreamChunk, make_chunk
from .types import Schema, TypeKind


# -- Arrow -------------------------------------------------------------------

def _arrow_type(t, pa):
    k = t.kind
    if k == TypeKind.BOOL:
        return pa.bool_()
    if k == TypeKind.INT16:
        return pa.int16()
    if k == TypeKind.INT32:
        return pa.int32()
    if k in (TypeKind.INT64, TypeKind.SERIAL):
        return pa.int64()
    if k == TypeKind.FLOAT32:
        return pa.float32()
    if k == TypeKind.FLOAT64:
        return pa.float64()
    if k == TypeKind.DECIMAL:
        return pa.decimal128(38, t.scale)
    if k == TypeKind.DATE:
        return pa.date32()
    if k == TypeKind.TIME:
        return pa.time64("us")
    if k == TypeKind.TIMESTAMP:
        return pa.timestamp("us")
    if k == TypeKind.INTERVAL:
        return pa.duration("us")
    if k in (TypeKind.VARCHAR, TypeKind.BYTEA):
        return pa.string()
    raise TypeError(f"no arrow mapping for {k}")


def chunk_to_arrow(chunk: StreamChunk, schema: Schema,
                   with_ops: bool = False):
    """Visible rows of a chunk → pyarrow.RecordBatch (logical values)."""
    import pyarrow as pa
    import decimal as _dec
    vis = np.asarray(chunk.vis)
    idx = np.nonzero(vis)[0]
    arrays, fields = [], []
    if with_ops:
        ops = np.asarray(chunk.ops)[idx]
        arrays.append(pa.array(ops, pa.int8()))
        fields.append(pa.field("__op", pa.int8()))
    for f, col in zip(schema, chunk.columns):
        data = np.asarray(col.data)[idx]
        mask = np.asarray(col.mask)[idx]
        at = _arrow_type(f.type, pa)
        if f.type.is_string:
            vals = [f.type.to_python(v) if m else None
                    for v, m in zip(data, mask)]
            arrays.append(pa.array(vals, at))
        elif f.type.kind == TypeKind.DECIMAL:
            q = _dec.Decimal(1).scaleb(-f.type.scale)
            vals = [(_dec.Decimal(int(v)) * q) if m else None
                    for v, m in zip(data, mask)]
            arrays.append(pa.array(vals, at))
        else:
            arrays.append(pa.array(data, at, mask=~mask))
        fields.append(pa.field(f.name, at))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def arrow_to_chunk(batch, schema: Schema,
                   capacity: Optional[int] = None) -> StreamChunk:
    """pyarrow.RecordBatch → insert-op chunk (logical decode + intern)."""
    import datetime as _dt
    epoch_d = _dt.date(1970, 1, 1)
    epoch_ts = _dt.datetime(1970, 1, 1)
    rows: List[tuple] = []
    cols = [batch.column(f.name) for f in schema]
    for i in range(batch.num_rows):
        row = []
        for f, c in zip(schema, cols):
            v = c[i].as_py()
            if v is not None:
                k = f.type.kind
                if k == TypeKind.DECIMAL:
                    v = float(v)
                elif k == TypeKind.DATE:
                    v = (v - epoch_d).days
                elif k == TypeKind.TIMESTAMP:
                    v = (v.replace(tzinfo=None) - epoch_ts) \
                        // _dt.timedelta(microseconds=1)
                elif k == TypeKind.TIME:
                    v = ((v.hour * 60 + v.minute) * 60
                         + v.second) * 1_000_000 + v.microsecond
                elif k == TypeKind.INTERVAL:
                    v = v // _dt.timedelta(microseconds=1)
            row.append(v)
        rows.append(tuple(row))
    return make_chunk(schema, rows,
                      capacity=capacity or max(len(rows), 1))


# -- numpy / DLPack ----------------------------------------------------------

def chunk_to_numpy(chunk: StreamChunk) -> dict:
    """Physical host view: {'ops', 'vis', 'columns': [(data, mask), ...]}."""
    return {
        "ops": np.asarray(chunk.ops),
        "vis": np.asarray(chunk.vis),
        "columns": [(np.asarray(c.data), np.asarray(c.mask))
                    for c in chunk.columns],
    }


def column_to_dlpack(col: Column):
    """Zero-copy DLPack capsules for (data, mask) device buffers — consume
    with torch.utils.dlpack.from_dlpack / np.from_dlpack."""
    import jax
    return jax.dlpack.to_dlpack(col.data), jax.dlpack.to_dlpack(col.mask)


def column_to_torch(col: Column):
    """Column device buffers → torch tensors (zero-copy where the backend
    allows; TPU buffers transfer through host)."""
    import torch
    data = np.asarray(col.data)
    mask = np.asarray(col.mask)
    return torch.from_numpy(np.ascontiguousarray(data)), \
        torch.from_numpy(np.ascontiguousarray(mask))


def torch_to_column(data, mask=None) -> Column:
    import jax.numpy as jnp
    d = np.asarray(data.detach().cpu().numpy())
    m = (np.ones(d.shape, bool) if mask is None
         else np.asarray(mask.detach().cpu().numpy()).astype(bool))
    return Column(jnp.asarray(d), jnp.asarray(m))
