"""ConsistencyAuditor: machine-checked invariants after a chaos run.

FoundationDB's lesson is that seeded fault injection is only half of
deterministic simulation testing — the other half is INVARIANT CHECKING
strong enough that a run cannot "pass" by accident. After any chaos run
(network fault plane, crash-point sweep, sim kills) the auditor
cross-checks the cluster against the exactly-once contract:

* **exactly-once sink delivery** — every sink's delivered output equals
  the control run's, as a multiset of (op, row): an injected duplicate
  frame that slipped past seq-dedup, or a replayed epoch double-
  delivered after recovery, shows up as a dupe; a dropped frame that
  recovery failed to replay shows up as loss;
* **MV parity** — every MV bit-equal to the control session's;
* **per-edge barrier-epoch monotonicity** — no exchange edge ever
  delivered a barrier at or below its previous epoch
  (``EdgeStats.epoch_regressions == 0`` across every worker), the
  ordering invariant the Chandy-Lamport cut rests on;
* **storage pin/refcount leak-freedom** — on the Hummock tier, no
  version pins outlive their readers and every SST the object store
  holds is referenced by the current version, a pinned version, or an
  in-flight compaction output (a leak means chaos wedged a lease open
  or orphaned uncommitted uploads forever).

Usage::

    report = ConsistencyAuditor(session).audit(control=control)
    report.assert_ok()
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional


class AuditViolation(AssertionError):
    pass


@dataclasses.dataclass
class AuditReport:
    checks: Dict[str, dict]

    @property
    def ok(self) -> bool:
        return all(c.get("ok", False) for c in self.checks.values())

    def failed(self) -> List[str]:
        return [n for n, c in self.checks.items() if not c.get("ok")]

    def assert_ok(self) -> None:
        if not self.ok:
            detail = {n: self.checks[n] for n in self.failed()}
            raise AuditViolation(
                "consistency audit failed: "
                + json.dumps(detail, default=str, indent=2))


def fold_changelog(rows: List[tuple]):
    """Fold a delivered changelog into its net row multiset: inserts
    add, deletes remove. Exactly-once delivery into an upsert-style
    consumer is a contract on THIS folded state — epoch boundaries
    legitimately differ between a chaos run and its control (a recovery
    re-batches re-applied DML), changing U-/U+ granularity without
    changing the net effect; a duplicated or lost delivery, though,
    unbalances the fold and is caught. Negative counts (a delete whose
    insert was never delivered) are a violation on their own."""
    from collections import Counter
    net: Counter = Counter()
    for op, row in rows:
        if op in ("insert", "update_insert"):
            net[row] += 1
        else:
            net[row] -= 1
    return net


def sink_delivered_rows(session, name: str) -> Optional[List[tuple]]:
    """The rows a sink job actually DELIVERED, as (op, row-values)
    tuples, read back from the sink backend. FileSink (jsonl) is read
    from disk so the check covers the real external surface; sinks
    without a readable backend return None (skipped)."""
    sink = session.sink_of(name)
    if sink is None:
        return None
    path = getattr(sink, "path", None)
    if path is None or getattr(sink, "fmt", "jsonl") != "jsonl":
        return None
    if not os.path.exists(path):
        return []
    out: List[tuple] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            op = obj.pop("__op", "insert")
            out.append((op, tuple(sorted(obj.items()))))
    return out


class ConsistencyAuditor:
    def __init__(self, session):
        self.session = session

    # -- individual checks ----------------------------------------------------

    def check_mv_parity(self, control, mv_names=None) -> dict:
        names = mv_names or sorted(
            set(self.session.catalog.mvs) & set(control.catalog.mvs))
        diverged = {}
        for name in names:
            got = sorted(self.session.mv_rows(name))
            want = sorted(control.mv_rows(name))
            if got != want:
                diverged[name] = {"chaos": got[:5], "control": want[:5],
                                  "n_chaos": len(got),
                                  "n_control": len(want)}
        return {"ok": not diverged, "mvs": len(names),
                "diverged": diverged}

    def check_sink_exactly_once(self, control, sink_names=None) -> dict:
        names = sink_names or sorted(
            set(self.session.catalog.sinks) & set(control.catalog.sinks))
        bad, checked = {}, 0
        for name in names:
            got = sink_delivered_rows(self.session, name)
            want = sink_delivered_rows(control, name)
            if got is None or want is None:
                continue            # backend not readable: skip honestly
            checked += 1
            cg, cw = fold_changelog(got), fold_changelog(want)
            negative = {r: n for r, n in cg.items() if n < 0}
            if cg != cw or negative:
                bad[name] = {
                    "delivered": len(got), "expected": len(want),
                    "duplicated": sum((cg - cw).values()),
                    "lost": sum((cw - cg).values()),
                    "negative_rows": len(negative),
                }
        return {"ok": not bad, "sinks_checked": checked, "violations": bad}

    def check_barrier_monotonic(self) -> dict:
        """No exchange edge may ever deliver a barrier at or below its
        previous epoch (EdgeStats.saw_barrier counts regressions)."""
        m = self.session.metrics()
        bad = [e for e in m.get("exchange", ())
               if e.get("epoch_regressions", 0) > 0]
        return {"ok": not bad,
                "edges": len(m.get("exchange", ()) or ()),
                "regressions": bad}

    def check_storage_pins(self) -> dict:
        """Hummock tier: version-pin leases all released and no orphaned
        SSTs (listed but unreachable from version/pins/in-flight tasks).
        Non-hummock tiers pass trivially."""
        store = self.session.store
        mgr = getattr(store, "manager", None)
        if mgr is None:
            return {"ok": True, "tier": "non-hummock"}
        self.session.wait_compaction()
        pins = mgr.pinned_versions()
        # torn uploads / cancelled tasks legitimately leave orphans —
        # bounded garbage the vacuum must be able to EAT. The leak
        # invariant is that after one GC pass, every object the store
        # still lists is accounted for (version, pin, in-flight task,
        # registered upload): anything else means refcounting lost track
        mgr.vacuum()
        from ..storage.hummock import SST_PREFIX
        listed = set(store.object_store.list(SST_PREFIX))
        refs = set(mgr.referenced_ssts())
        protected = mgr._protected_prefixes()
        unaccounted = sorted(
            n for n in listed - refs
            if not any(n.startswith(p) for p in protected))
        return {"ok": not pins and not unaccounted,
                "tier": "hummock", "pins": len(pins),
                "unaccounted_ssts": unaccounted[:10]}

    # -- the full audit -------------------------------------------------------

    def audit(self, control=None, mv_names=None,
              sink_names=None) -> AuditReport:
        checks: Dict[str, dict] = {}
        if control is not None:
            self.session.flush()
            control.flush()
            checks["mv_parity"] = self.check_mv_parity(control, mv_names)
            checks["sink_exactly_once"] = self.check_sink_exactly_once(
                control, sink_names)
        checks["barrier_monotonic"] = self.check_barrier_monotonic()
        checks["storage_pins"] = self.check_storage_pins()
        return AuditReport(checks)
