"""Row representation and memcomparable key encoding (host tier).

Counterpart of the reference's row serde + memcomparable encoding
(reference: src/common/src/row/, src/common/src/util/memcmp_encoding.rs):
encoded keys compare bytewise in the same order as the logical values, which
is what gives the state store sorted iteration (TopN, range scans, prefix
scans by group key). Only physical scalars appear here — VARCHAR arrives as
dictionary ids but is encoded via its string bytes so lexicographic order is
preserved.
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Sequence

from .types import DataType, Schema, TypeKind, GLOBAL_STRING_DICT

_NULL_TAG = b"\x00"   # nulls sort first (reference: memcmp_encoding nulls-first default)
_VAL_TAG = b"\x01"


def _enc_int(v: int, bits: int) -> bytes:
    # flip sign bit => unsigned bytewise order matches signed order
    off = 1 << (bits - 1)
    return int(v + off).to_bytes(bits // 8, "big")


def _dec_int(b: bytes, bits: int) -> int:
    off = 1 << (bits - 1)
    return int.from_bytes(b, "big") - off


def _enc_float(v: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", float(v)))[0]
    if bits & (1 << 63):
        bits = ~bits & 0xFFFFFFFFFFFFFFFF   # negative: flip all
    else:
        bits |= 1 << 63                      # positive: flip sign
    return bits.to_bytes(8, "big")


def _dec_float(b: bytes) -> float:
    bits = int.from_bytes(b, "big")
    if bits & (1 << 63):
        bits &= ~(1 << 63) & 0xFFFFFFFFFFFFFFFF
    else:
        bits = ~bits & 0xFFFFFFFFFFFFFFFF
    return struct.unpack(">d", struct.pack(">Q", bits))[0]


def _enc_str(s: str) -> bytes:
    # escape embedded zero bytes, terminate with 0x00 0x00 so prefixes sort first
    raw = s.encode("utf-8").replace(b"\x00", b"\x00\xff")
    return raw + b"\x00\x00"


def encode_value(v: Optional[Any], t: DataType) -> bytes:
    """Physical scalar -> memcomparable bytes (nulls first)."""
    if v is None:
        return _NULL_TAG
    k = t.kind
    if k == TypeKind.BOOL:
        return _VAL_TAG + (b"\x01" if v else b"\x00")
    if t.is_string:
        return _VAL_TAG + _enc_str(GLOBAL_STRING_DICT.lookup(int(v)))
    if k == TypeKind.LIST:
        # element-wise: \x01 ++ elem-encoding per element, \x00 end — a
        # proper-prefix list sorts before its extensions, elements compare
        # in order (memcomparable for same-typed lists)
        from .types import GLOBAL_LIST_DICT
        et = t.elem_type
        parts = []
        for e in GLOBAL_LIST_DICT.lookup(int(v)):
            parts.append(b"\x01")
            parts.append(encode_value(
                None if e is None else et.to_physical(e), et))
        return _VAL_TAG + b"".join(parts) + b"\x00"
    if k == TypeKind.STRUCT:
        from .types import GLOBAL_LIST_DICT
        fields = GLOBAL_LIST_DICT.lookup(int(v))
        ftypes = [ft for _, ft in (t.struct_fields or ())]
        if len(fields) != len(ftypes):
            raise ValueError(
                f"struct value arity {len(fields)} != declared "
                f"{len(ftypes)}")
        return _VAL_TAG + b"".join(
            encode_value(None if e is None else ft.to_physical(e), ft)
            for e, ft in zip(fields, ftypes))
    if k in (TypeKind.FLOAT32, TypeKind.FLOAT64):
        return _VAL_TAG + _enc_float(float(v))
    if k in (TypeKind.INT16,):
        return _VAL_TAG + _enc_int(int(v), 16)
    if k in (TypeKind.INT32, TypeKind.DATE):
        return _VAL_TAG + _enc_int(int(v), 32)
    return _VAL_TAG + _enc_int(int(v), 64)


def encode_key(values: Sequence[Optional[Any]], types: Sequence[DataType]) -> bytes:
    """Physical row (already via DataType.to_physical) -> sortable key bytes."""
    return b"".join(encode_value(v, t) for v, t in zip(values, types))


# ---------------------------------------------------------------------------
# Value encoding (row serde) — the durable-tier row representation
# (reference: src/common/src/util/value_encoding/). Unlike key encoding it is
# NOT order-preserving; it is compact, self-delimiting per the schema, and
# process-independent: VARCHAR/BYTEA are stored as their string bytes, not as
# process-local dictionary ids, so a recovered process re-interns them.
# ---------------------------------------------------------------------------

_NULL = b"\x00"
_PRESENT = b"\x01"


def encode_value_row(row: Sequence[Optional[Any]],
                     types: Sequence[DataType]) -> bytes:
    """Physical row tuple -> durable bytes."""
    parts = []
    for v, t in zip(row, types):
        if v is None:
            parts.append(_NULL)
            continue
        parts.append(_PRESENT)
        k = t.kind
        if k == TypeKind.BOOL:
            parts.append(b"\x01" if v else b"\x00")
        elif t.is_string:
            raw = GLOBAL_STRING_DICT.lookup(int(v)).encode("utf-8")
            parts.append(struct.pack("<I", len(raw)))
            parts.append(raw)
        elif k == TypeKind.LIST:
            # lists persist by CONTENT (ids are process-local): element
            # count, then value-encoded PYTHON elements (ListDict holds
            # python values, not physical scalars)
            from .types import GLOBAL_LIST_DICT
            elems = GLOBAL_LIST_DICT.lookup(int(v))
            et = t.elem_type
            parts.append(struct.pack("<I", len(elems)))
            parts.append(encode_value_row(
                [None if e is None else et.to_physical(e) for e in elems],
                [et] * len(elems)))
        elif k == TypeKind.STRUCT:
            # fixed arity from the declared field types — no count prefix
            from .types import GLOBAL_LIST_DICT
            fields = GLOBAL_LIST_DICT.lookup(int(v))
            ftypes = [ft for _, ft in (t.struct_fields or ())]
            if len(fields) != len(ftypes):
                raise ValueError(
                    f"struct value arity {len(fields)} != declared "
                    f"{len(ftypes)}")
            parts.append(encode_value_row(
                [None if e is None else ft.to_physical(e)
                 for e, ft in zip(fields, ftypes)], ftypes))
        elif t.is_float:
            parts.append(struct.pack("<d", float(v)))
        else:
            parts.append(struct.pack("<q", int(v)))
    return b"".join(parts)


def _decode_values(data: bytes, pos: int,
                   types: Sequence[DataType]) -> tuple[list, int]:
    out: list = []
    for t in types:
        tag = data[pos]
        pos += 1
        if tag == 0:
            out.append(None)
            continue
        k = t.kind
        if k == TypeKind.BOOL:
            out.append(bool(data[pos]))
            pos += 1
        elif t.is_string:
            (n,) = struct.unpack_from("<I", data, pos)
            pos += 4
            s = data[pos:pos + n].decode("utf-8")
            pos += n
            out.append(GLOBAL_STRING_DICT.intern(s))
        elif k == TypeKind.LIST:
            from .types import GLOBAL_LIST_DICT
            (n,) = struct.unpack_from("<I", data, pos)
            pos += 4
            et = t.elem_type
            phys, pos = _decode_values(data, pos, [et] * n)
            elems = [None if e is None else et.to_python(e) for e in phys]
            out.append(GLOBAL_LIST_DICT.intern(elems))
        elif k == TypeKind.STRUCT:
            from .types import GLOBAL_LIST_DICT
            ftypes = [ft for _, ft in (t.struct_fields or ())]
            phys, pos = _decode_values(data, pos, ftypes)
            fields = [None if e is None else ft.to_python(e)
                      for e, ft in zip(phys, ftypes)]
            out.append(GLOBAL_LIST_DICT.intern(fields))
        elif t.is_float:
            (f,) = struct.unpack_from("<d", data, pos)
            pos += 8
            out.append(f)
        else:
            (i,) = struct.unpack_from("<q", data, pos)
            pos += 8
            out.append(i)
    return out, pos


def decode_value_row(data: bytes, types: Sequence[DataType]) -> tuple:
    """Durable bytes -> physical row tuple (strings/lists re-interned)."""
    out, _ = _decode_values(data, 0, types)
    return tuple(out)


def encode_vnode_key(vnode: int, values: Sequence, types: Sequence[DataType]) -> bytes:
    """vnode-prefixed key — the reference's table key layout
    ``table_id | vnode | key`` (docs/state-store-overview.md:96); table_id is
    the store-level namespace here."""
    return vnode.to_bytes(2, "big") + encode_key(values, types)
