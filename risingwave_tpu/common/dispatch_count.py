"""Jit-dispatch counting — the regression guard for the dispatch ladder.

The whole point of the fused epoch surfaces (ops/fused_epoch.py,
docs/performance.md) is that ONE jitted call covers an entire epoch of
ingest; the historical failure mode is an edit that quietly reintroduces a
per-chunk call ladder (k dispatches per epoch — each a host→device round
trip, ~1 RTT over a tunneled chip). XLA offers no portable "how many times
was an executable launched" hook across backends, so the counter sits one
level up, where the ladder actually manifests: every function produced by
``jax.jit`` is wrapped to count its *calls from host control flow* (calls
inside a trace never re-enter the Python wrapper, so fused inner steps
correctly count zero).

Usage::

    with count_dispatches() as c:
        ...build pipeline + run...
    assert c.counts["fused_source_agg_epoch.<locals>.epoch"] == n_epochs

Only functions jitted WHILE the context is active are counted — build the
pipeline inside the ``with`` block. Not thread-safe (patches ``jax.jit``);
tests only.
"""

from __future__ import annotations

import contextlib
import functools
from collections import Counter

import jax


class DispatchCounter:
    def __init__(self):
        self.counts: Counter = Counter()

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def reset(self) -> None:
        self.counts.clear()

    def record(self, name: str) -> None:
        self.counts[name] += 1


@contextlib.contextmanager
def count_dispatches():
    counter = DispatchCounter()
    orig_jit = jax.jit

    def counting_jit(fun=None, **kwargs):
        if fun is None:    # jax.jit(static_argnums=...) decorator form
            return functools.partial(counting_jit, **kwargs)
        jitted = orig_jit(fun, **kwargs)
        name = getattr(fun, "__qualname__",
                       getattr(fun, "__name__", repr(fun)))

        @functools.wraps(fun)
        def wrapper(*a, **k):
            counter.record(name)
            return jitted(*a, **k)

        # keep the AOT surface available through the wrapper
        wrapper.lower = jitted.lower
        wrapper.trace = getattr(jitted, "trace", None)
        wrapper.__wrapped_jit__ = jitted
        return wrapper

    jax.jit = counting_jit
    try:
        yield counter
    finally:
        jax.jit = orig_jit
