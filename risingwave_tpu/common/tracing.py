"""Epoch-aware structured tracing: bounded span ring + Chrome trace export.

Counterpart of the reference's tracing layer (reference:
src/utils/runtime/src/logger.rs tracing subscribers + the await-tree /
risectl trace surface, src/compute/src/rpc/service/monitor_service.rs:46).
Scaled to this build: every barrier cycle produces a small tree of spans —

    epoch <N>                      conductor: inject -> collect -> commit
      barrier.inject               source/table queue pushes + remote inject
      <Executor>.barrier           each executor's on_barrier work
      checkpoint.commit            store + worker phase-2 commit
      DurableStateStore.commit     segment append inside the store

captured into a bounded ring buffer (``TraceRecorder``) so the last few
hundred epochs are always inspectable post-hoc without any collector
infrastructure. ``to_chrome_trace`` renders spans as Chrome trace-event
JSON ("X" complete events) loadable in Perfetto / chrome://tracing: one
epoch shows as a timeline across executors.

Cross-process: worker processes record into their own per-process
``GLOBAL_TRACE``; the session's stats federation drains those rings over
the control socket and re-ingests the spans with the worker's pid, so a
single export covers the whole cluster. Span timestamps use the shared
wall clock (``time.time()``) so per-process timelines align; durations
are measured with ``perf_counter`` for precision.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import threading
import time
from typing import Iterable, Optional

#: span categories (Chrome trace "cat" field)
CAT_EPOCH = "epoch"          # whole-epoch + inject/collect conductor spans
CAT_BARRIER = "barrier"      # per-executor on_barrier work
CAT_STORAGE = "storage"      # state-table / store commit work
CAT_EXCHANGE = "exchange"    # cross-process data movement
CAT_DISPATCH = "dispatch"    # jitted-epoch dispatches (common/profiling.py)


@dataclasses.dataclass
class Span:
    """One completed span. ``ts`` is wall-clock seconds (shared across
    processes); ``dur`` is perf_counter-measured seconds."""

    name: str
    cat: str
    ts: float
    dur: float
    epoch: Optional[int] = None
    tid: str = "main"            # logical track: executor identity etc.
    pid: int = 0                 # 0 = session; worker_id + 1 = worker
    args: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class TraceRecorder:
    """Bounded, thread-safe ring of completed spans.

    Recording must stay cheap enough for the barrier hot path: one lock
    acquisition + deque append per span, no allocation beyond the Span."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self.capacity = capacity
            self._spans = collections.deque(self._spans, maxlen=capacity)

    def record(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = CAT_BARRIER,
             epoch: Optional[int] = None, tid: str = "main",
             pid: int = 0, **args):
        """Context manager recording one span around its body."""
        if not self.enabled:
            yield
            return
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(Span(name, cat, ts, time.perf_counter() - t0,
                             epoch=epoch, tid=tid, pid=pid, args=args))

    def snapshot(self, epoch: Optional[int] = None) -> list[Span]:
        """Copy of the ring, optionally filtered to one epoch's tree."""
        with self._lock:
            spans = list(self._spans)
        if epoch is not None:
            spans = [s for s in spans if s.epoch == epoch]
        return spans

    def drain(self) -> list[Span]:
        """Take-and-clear — the worker side of span federation (each
        session stats poll drains, so no span ships twice)."""
        with self._lock:
            spans = list(self._spans)
            self._spans.clear()
        return spans

    def ingest(self, dicts: Iterable[dict], pid: Optional[int] = None) -> None:
        """Re-record spans shipped from another process (stats reply)."""
        for d in dicts:
            s = Span.from_dict(d)
            if pid is not None:
                s.pid = pid
            self.record(s)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def epochs(self) -> list[int]:
        """Distinct epochs currently covered by the ring, ascending."""
        return sorted({s.epoch for s in self.snapshot()
                       if s.epoch is not None})


#: the per-process recorder every instrumentation seam writes to
GLOBAL_TRACE = TraceRecorder()


def trace_span(name: str, cat: str = CAT_BARRIER,
               epoch: Optional[int] = None, tid: str = "main", **args):
    """``with trace_span(...):`` against the process-global recorder."""
    return GLOBAL_TRACE.span(name, cat=cat, epoch=epoch, tid=tid, **args)


# -- Chrome trace-event export ------------------------------------------------

def to_chrome_trace(spans: Iterable[Span],
                    process_names: Optional[dict] = None,
                    barrier_records: Optional[Iterable[dict]] = None,
                    ) -> dict:
    """Spans → Chrome trace-event JSON object (Perfetto-loadable).

    Every span becomes a complete ("X") event; epoch spans live on the
    ``conductor`` track and executor spans on per-identity tracks, so one
    epoch renders as a timeline across executors. Timestamps are
    microseconds relative to the earliest span so the viewer opens at
    t=0. ``barrier_records`` (BarrierLedger waterfall records) render as
    flow events ("s"/"t"/"f", one flow id per epoch) arrowing each
    barrier from its conductor injection through every participating
    worker's collect back to completion."""
    spans = sorted(spans, key=lambda s: s.ts)
    base = spans[0].ts if spans else 0.0
    events: list[dict] = []
    names = {0: "session"}
    names.update(process_names or {})
    for s in spans:
        if s.pid not in names:
            names[s.pid] = f"worker-{s.pid - 1}"
        args = {"epoch": s.epoch, **s.args} if s.epoch is not None \
            else dict(s.args)
        events.append({
            "name": s.name, "cat": s.cat, "ph": "X",
            "ts": round((s.ts - base) * 1e6, 3),
            "dur": round(s.dur * 1e6, 3),
            "pid": s.pid, "tid": s.tid, "args": args,
        })
    events.extend(barrier_flow_events(barrier_records or (), base, names))
    meta: list[dict] = []
    for pid, pname in sorted(names.items()):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": "", "args": {"name": pname}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def barrier_flow_events(records: Iterable[dict], base: float,
                        names: Optional[dict] = None) -> list[dict]:
    """BarrierLedger waterfall records → Chrome flow events.

    One flow per barrier (id = epoch): start ("s") on the conductor
    track at injection, a step ("t") on each participating worker's
    conductor track at its collect, finish ("f") back on the conductor
    at completion — Perfetto draws the barrier's cluster-wide path as
    arrows across process lanes."""
    out: list[dict] = []
    for rec in records:
        t0 = rec.get("injected_at")
        total_ms = rec.get("total_ms")
        if t0 is None or total_ms is None:
            continue          # an in-flight record has no finish yet
        epoch = rec["epoch"]
        common = {"name": f"barrier {epoch}", "cat": CAT_EPOCH,
                  "id": epoch, "tid": "conductor"}
        out.append({**common, "ph": "s", "pid": 0,
                    "ts": round((t0 - base) * 1e6, 3),
                    "args": {"epoch": epoch,
                             "checkpoint": rec.get("checkpoint")}})
        for wid, stages in sorted(rec.get("workers", {}).items()):
            if int(wid) < 0:
                continue      # session-process detail stays on pid 0
            pid = int(wid) + 1
            if names is not None and pid not in names:
                names[pid] = f"worker-{wid}"
            wc = stages.get("worker_collect", 0.0)
            out.append({**common, "ph": "t", "pid": pid,
                        "ts": round((t0 - base) * 1e6 + wc * 1e3, 3),
                        "args": {"epoch": epoch}})
        out.append({**common, "ph": "f", "bp": "e", "pid": 0,
                    "ts": round((t0 - base) * 1e6 + total_ms * 1e3, 3),
                    "args": {"epoch": epoch, "result": rec.get("result")}})
    return out


def export_chrome_trace(spans: Iterable[Span],
                        path: Optional[str] = None, **kw) -> dict:
    """Render and optionally write the Chrome trace JSON."""
    obj = to_chrome_trace(spans, **kw)
    if path is not None:
        with open(path, "w") as f:
            json.dump(obj, f)
    return obj
