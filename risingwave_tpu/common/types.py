"""Logical type system for the data plane.

Mirrors the capability surface of the reference's 18-variant ``ArrayImpl``
(reference: src/common/src/array/mod.rs:334-376) but with a TPU-first physical
mapping: every logical type lowers to a fixed-width device dtype. Varlen types
(VARCHAR / BYTEA / JSONB) are dictionary-encoded at the ingest boundary — the
device sees int32 dictionary ids; the host keeps the dictionary (SURVEY.md §7
"Varlen strings on device").
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


class TypeKind(enum.Enum):
    BOOL = "boolean"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "real"
    FLOAT64 = "double"
    DECIMAL = "decimal"        # scaled int64 (fixed point)
    DATE = "date"              # int32 days since epoch
    TIME = "time"              # int64 microseconds since midnight
    TIMESTAMP = "timestamp"    # int64 microseconds since epoch
    INTERVAL = "interval"      # int64 microseconds (v1: no month component)
    VARCHAR = "varchar"        # int32 dictionary id
    BYTEA = "bytea"            # int32 dictionary id
    SERIAL = "serial"          # int64 row id (vnode-prefixed)
    LIST = "list"              # int32 list-dictionary id (value-interned)
    JSONB = "jsonb"            # int32 dictionary id (canonical JSON text)
    STRUCT = "struct"          # int32 list-dictionary id (field tuple)


_PHYSICAL: dict[TypeKind, Any] = {
    TypeKind.BOOL: jnp.bool_,
    TypeKind.INT16: jnp.int16,
    TypeKind.INT32: jnp.int32,
    TypeKind.INT64: jnp.int64,
    TypeKind.FLOAT32: jnp.float32,
    TypeKind.FLOAT64: jnp.float64,
    TypeKind.DECIMAL: jnp.int64,
    TypeKind.DATE: jnp.int32,
    TypeKind.TIME: jnp.int64,
    TypeKind.TIMESTAMP: jnp.int64,
    TypeKind.INTERVAL: jnp.int64,
    TypeKind.VARCHAR: jnp.int32,
    TypeKind.BYTEA: jnp.int32,
    TypeKind.SERIAL: jnp.int64,
    TypeKind.LIST: jnp.int32,
    TypeKind.JSONB: jnp.int32,
    TypeKind.STRUCT: jnp.int32,
}

_INTEGRAL = {
    TypeKind.INT16,
    TypeKind.INT32,
    TypeKind.INT64,
    TypeKind.SERIAL,
    TypeKind.DATE,
    TypeKind.TIME,
    TypeKind.TIMESTAMP,
    TypeKind.INTERVAL,
    TypeKind.DECIMAL,
}


class StringDict:
    """Host-side dictionary for a VARCHAR/BYTEA column family.

    Interning happens on the ingest path (source parsers) and decoding on the
    egress path (materialize / pgwire). Device code only ever compares,
    hashes, or shuffles the int32 ids. Id 0 is reserved for the empty string
    so zero-initialised buffers decode cleanly.

    Growth is bounded (``max_size``): an unbounded append-only dictionary
    would leak for high-cardinality string workloads — hitting the bound is
    a capacity-planning error surfaced loudly, not silent growth.
    """

    __slots__ = ("_to_id", "_to_str", "max_size", "_sorted", "_unsorted",
                 "_rank_version", "_ranks", "_device_version",
                 "_device_ranks", "_lock")

    DEFAULT_MAX = 1 << 22          # 4M distinct strings

    def __init__(self, max_size: int = DEFAULT_MAX) -> None:
        import threading
        self._lock = threading.Lock()
        self._to_id: dict[str, int] = {"": 0}
        self._to_str: list[str] = [""]
        self.max_size = max_size
        # sorted prefix + unsorted suffix: intern stays O(1) (append), and
        # a rank refresh merges the suffix in — O(n + k log k), never a
        # full re-sort, and no cost at all for workloads that never order
        self._sorted: list[str] = [""]
        self._unsorted: list[str] = []
        self._rank_version = -1
        self._ranks: Optional[np.ndarray] = None
        self._device_version = -1
        self._device_ranks = None

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is not None:
            return i
        # check-then-append must be atomic: observability endpoints
        # (dashboard/prometheus) run on threads and may intern while the
        # session does — two threads assigning one id to two strings
        # would corrupt every string column
        with self._lock:
            i = self._to_id.get(s)
            if i is None:
                if len(self._to_str) >= self.max_size:
                    raise RuntimeError(
                        f"string dictionary full ({self.max_size} distinct "
                        "values); raise StringDict.max_size or reduce "
                        "string cardinality (e.g. avoid interning "
                        "unbounded keys)")
                i = len(self._to_str)
                self._to_id[s] = i
                self._to_str.append(s)
                self._unsorted.append(s)
        return i

    def lookup(self, i: int) -> str:
        return self._to_str[i]

    def __len__(self) -> int:
        return len(self._to_str)

    # -- ordering --------------------------------------------------------------
    # Dictionary ids are insertion-ordered, so raw ids must never feed an
    # ordering operation (reference semantics: memcomparable order,
    # src/common/src/util/memcmp_encoding.rs). Every device ordering path
    # (comparisons, sort keys, MIN/MAX) maps id -> lexicographic rank through
    # this side table instead. State always STORES ids (stable under dict
    # growth); ranks are looked up fresh at comparison time, so a table
    # refresh never invalidates persisted state.

    @property
    def version(self) -> int:
        """Monotone counter: bumps exactly when a new string is interned."""
        return len(self._to_str)

    def ranks(self) -> np.ndarray:
        """int64[version] table: ranks()[id] = rank of string id in
        lexicographic (codepoint) order. Refresh merges the unsorted
        suffix of newly-interned strings into the sorted prefix
        (O(n + k log k)); cached per version in between; interning itself
        stays O(1)."""
        n = len(self._to_str)
        if self._rank_version != n:
            if self._unsorted:
                import heapq
                self._sorted = list(heapq.merge(
                    self._sorted, sorted(self._unsorted)))
                self._unsorted = []
            pos = {s: i for i, s in enumerate(self._sorted)}
            r = np.empty(n, np.int64)
            for i, s in enumerate(self._to_str):
                r[i] = pos[s]
            self._ranks = r
            self._rank_version = n
        return self._ranks

    def device_ranks(self):
        """Device-resident rank table, padded to a power of two (padding
        maps to rank == version, above every live rank) so jitted consumers
        retrace only on capacity doublings, not on every intern."""
        n = len(self._to_str)
        if self._device_version != n:
            import jax.numpy as jnp
            cap = 8
            while cap < n:
                cap *= 2
            t = np.full(cap, n, np.int64)
            t[:n] = self.ranks()
            self._device_ranks = jnp.asarray(t)
            self._device_version = n
        return self._device_ranks


# A single process-wide dictionary keeps VARCHAR ids comparable across
# operators and fragments without a coordination protocol. Sources intern,
# sinks look up. (A per-column dictionary would shrink ids but require id
# translation at every join on strings.)
#
# Process-locality contract (multi-host safety): raw ids may cross DEVICE
# boundaries within one process (mesh collectives share the host dict) but
# must NEVER cross PROCESS boundaries — every durable/remote edge
# (checkpoint value encoding in common/row.py, sink delivery, future DCN
# exchange) re-encodes ids as string bytes and re-interns on the far side.
GLOBAL_STRING_DICT = StringDict()


class ListDict:
    """Host-side dictionary for LIST columns — the varlen strategy of
    StringDict applied to arrays (reference array type:
    src/common/src/array/list_array.rs). A list VALUE is a python tuple of
    element values (None = NULL element); interning canonicalizes by value,
    so id equality on device == semantic list equality. Id 0 is the empty
    list so zero-initialised buffers decode cleanly. Device code only
    carries the int32 ids; element access / unnest / aggregation over
    contents are host-tier operations like every varlen function."""

    __slots__ = ("_to_id", "_to_list", "max_size", "_lock")

    DEFAULT_MAX = 1 << 22

    def __init__(self, max_size: int = DEFAULT_MAX):
        import threading
        self._lock = threading.Lock()
        self._to_id: dict = {(): 0}
        self._to_list: list = [()]
        self.max_size = max_size

    def intern(self, value) -> int:
        t = tuple(value)
        i = self._to_id.get(t)
        if i is not None:
            return i
        with self._lock:        # see StringDict.intern
            i = self._to_id.get(t)
            if i is None:
                if len(self._to_list) >= self.max_size:
                    raise RuntimeError(
                        f"list dictionary full ({self.max_size} entries)")
                i = len(self._to_list)
                self._to_id[t] = i
                self._to_list.append(t)
        return i

    def lookup(self, i: int) -> tuple:
        return self._to_list[i]

    def __len__(self) -> int:
        return len(self._to_list)


GLOBAL_LIST_DICT = ListDict()


@dataclasses.dataclass(frozen=True)
class DataType:
    """A logical column type. ``scale`` is only meaningful for DECIMAL;
    ``elem_kind`` only for LIST (the element type's kind);
    ``struct_fields`` only for STRUCT ((name, TypeKind) pairs —
    reference composite: src/common/src/array/struct_array.rs)."""

    kind: TypeKind
    scale: int = 0
    elem_kind: Optional[TypeKind] = None
    #: STRUCT only: ((name, DataType), …) — FULL types, so decimal scale
    #: and nested composites survive persistence and field access
    struct_fields: Optional[tuple] = None

    @property
    def dtype(self):
        return _PHYSICAL[self.kind]

    @property
    def np_dtype(self):
        return np.dtype(_PHYSICAL[self.kind])

    @property
    def is_integral(self) -> bool:
        return self.kind in _INTEGRAL

    @property
    def is_float(self) -> bool:
        return self.kind in (TypeKind.FLOAT32, TypeKind.FLOAT64)

    @property
    def is_string(self) -> bool:
        # JSONB is dictionary-encoded canonical JSON text: it rides every
        # varlen path (interning, content-addressed persistence, host
        # functions) exactly like VARCHAR
        return self.kind in (TypeKind.VARCHAR, TypeKind.BYTEA,
                             TypeKind.JSONB)

    @property
    def is_list(self) -> bool:
        return self.kind == TypeKind.LIST

    @property
    def is_struct(self) -> bool:
        return self.kind == TypeKind.STRUCT

    @property
    def elem_type(self) -> "DataType":
        assert self.kind == TypeKind.LIST and self.elem_kind is not None
        return DataType(self.elem_kind)

    def field_type(self, name: str) -> "DataType":
        assert self.kind == TypeKind.STRUCT and self.struct_fields
        for fname, ft in self.struct_fields:
            if fname == name:
                return ft
        raise KeyError(f"struct has no field {name!r}")

    def field_index(self, name: str) -> int:
        assert self.kind == TypeKind.STRUCT and self.struct_fields
        for i, (fname, _) in enumerate(self.struct_fields):
            if fname == name:
                return i
        raise KeyError(f"struct has no field {name!r}")

    # -- host <-> device value conversion -------------------------------------

    def to_physical(self, v: Any) -> Any:
        """Python value → physical scalar for device buffers."""
        if v is None:
            return self.null_sentinel()
        if self.kind == TypeKind.DECIMAL:
            return int(round(float(v) * 10**self.scale))
        if self.is_struct:
            fields = self.struct_fields or ()
            if len(tuple(v)) != len(fields):
                raise ValueError(
                    f"struct value has {len(tuple(v))} fields; type "
                    f"declares {len(fields)}")
            return GLOBAL_LIST_DICT.intern(v)
        if self.is_list:
            return GLOBAL_LIST_DICT.intern(v)
        if self.is_string:
            return GLOBAL_STRING_DICT.intern(v if isinstance(v, str) else v.decode())
        if self.kind == TypeKind.BOOL:
            return bool(v)
        if self.is_float:
            return float(v)
        return int(v)

    def to_python(self, v: Any) -> Any:
        """Physical scalar → Python value (for result rows / tests)."""
        if self.kind == TypeKind.DECIMAL:
            return int(v) / 10**self.scale if self.scale else int(v)
        if self.is_list or self.is_struct:
            return GLOBAL_LIST_DICT.lookup(int(v))
        if self.is_string:
            return GLOBAL_STRING_DICT.lookup(int(v))
        if self.kind == TypeKind.BOOL:
            return bool(v)
        if self.is_float:
            return float(v)
        return int(v)

    def null_sentinel(self) -> Any:
        """Filler for null/invisible slots. The validity mask is authoritative;
        the sentinel only needs to be a valid value of the physical dtype."""
        if self.kind == TypeKind.BOOL:
            return False
        if self.is_float:
            return 0.0
        return 0


# Convenience singletons.
BOOL = DataType(TypeKind.BOOL)
INT16 = DataType(TypeKind.INT16)
INT32 = DataType(TypeKind.INT32)
INT64 = DataType(TypeKind.INT64)
FLOAT32 = DataType(TypeKind.FLOAT32)
FLOAT64 = DataType(TypeKind.FLOAT64)
DATE = DataType(TypeKind.DATE)
TIME = DataType(TypeKind.TIME)
TIMESTAMP = DataType(TypeKind.TIMESTAMP)
INTERVAL = DataType(TypeKind.INTERVAL)
VARCHAR = DataType(TypeKind.VARCHAR)
BYTEA = DataType(TypeKind.BYTEA)
SERIAL = DataType(TypeKind.SERIAL)
JSONB = DataType(TypeKind.JSONB)


def decimal(scale: int = 2) -> DataType:
    return DataType(TypeKind.DECIMAL, scale=scale)


def list_of(elem: DataType) -> DataType:
    return DataType(TypeKind.LIST, elem_kind=elem.kind)


def struct_of(*fields) -> DataType:
    """struct_of(("a", INT64), ("b", VARCHAR)) — full field DataTypes
    (a bare TypeKind is wrapped for convenience)."""
    return DataType(TypeKind.STRUCT, struct_fields=tuple(
        (n, t if isinstance(t, DataType) else DataType(t))
        for n, t in fields))


@dataclasses.dataclass(frozen=True)
class Field:
    """A named, typed column in a schema."""

    name: str
    type: DataType


@dataclasses.dataclass(frozen=True)
class Schema:
    """Ordered column metadata for a chunk/table.

    Static (hashable) so it can live in jit-static args and plan nodes.
    Counterpart of the reference's ``Schema`` (src/common/src/catalog/schema.rs).
    """

    fields: tuple[Field, ...]

    def __post_init__(self):
        if not isinstance(self.fields, tuple):
            object.__setattr__(self, "fields", tuple(self.fields))

    @staticmethod
    def of(*cols: tuple[str, DataType]) -> "Schema":
        return Schema(tuple(Field(n, t) for n, t in cols))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    @property
    def types(self) -> tuple[DataType, ...]:
        return tuple(f.type for f in self.fields)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def select(self, indices) -> "Schema":
        return Schema(tuple(self.fields[i] for i in indices))

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)
