"""Memory accounting: EstimateSize for device state.

Counterpart of the reference's memory accounting
(reference: src/common/src/estimate_size/ ``EstimateSize`` trait +
src/utils/local_stats_alloc — cache-size accounting feeding eviction
decisions). Here the dominant budget is HBM: every stateful executor's
device state is a pytree of jax arrays, so sizes are exact (`nbytes`), not
estimated. ``executor_state_bytes`` walks an executor's known state
attributes; ``pipeline_state_bytes`` aggregates a whole job — surfaced via
``Session.metrics()`` for capacity planning against the chip's HBM.
"""

from __future__ import annotations

from typing import Any

#: executor attributes that may hold device-state pytrees
_STATE_ATTRS = ("state", "rows", "_state", "table_state")


def tree_device_bytes(tree: Any) -> int:
    """Total bytes of jax arrays in a pytree (0 for host-only objects)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None and hasattr(leaf, "dtype"):
            total += int(nbytes)
    return total


def executor_state_bytes(ex: Any) -> int:
    import jax
    total = 0
    seen: set = set()
    for attr in _STATE_ATTRS:
        v = getattr(ex, attr, None)
        if v is None or id(v) in seen:
            continue
        seen.add(id(v))
        try:
            total += tree_device_bytes(v)
        except Exception:   # noqa: BLE001 - non-pytree attribute
            continue
    return total


def pipeline_state_bytes(root: Any) -> dict:
    """{'<Identity>#<n>': bytes} over a pipeline; includes a '_total'."""
    from ..stream.metrics import iter_executors
    out: dict = {}
    counts: dict = {}
    total = 0
    for ex in iter_executors(root):
        b = executor_state_bytes(ex)
        if b == 0:
            continue
        ident = getattr(ex, "identity", type(ex).__name__)
        n = counts.get(ident, 0)
        counts[ident] = n + 1
        out[f"{ident}#{n}" if n else ident] = b
        total += b
    out["_total"] = total
    return out
