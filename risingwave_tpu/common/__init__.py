from .types import (  # noqa: F401
    BOOL, BYTEA, DATE, FLOAT32, FLOAT64, INT16, INT32, INT64, INTERVAL,
    SERIAL, TIME, TIMESTAMP, VARCHAR, DataType, Field, Schema, StringDict,
    TypeKind, decimal, GLOBAL_STRING_DICT,
)
from .chunk import (  # noqa: F401
    DEFAULT_CHUNK_CAPACITY, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, Column, StreamChunk, chunk_to_rows, compact_chunk_host,
    concat_rows, empty_chunk, make_chunk,
)
from .hashing import VNODE_COUNT, hash_columns, vnode_of, vnode_to_shard  # noqa: F401
