"""Layered configuration + runtime-mutable system parameters.

Counterpart of the reference's config system and system params
(reference: src/common/src/config.rs:128-634 — ``RwConfig`` sections with
defaults-in-code so absent keys stay version-stable;
src/common/src/system_param/mod.rs — cluster params mutable at runtime and
propagated to all nodes). Layering: defaults-in-code → TOML file →
explicit overrides; unknown keys are rejected loudly (the reference warns;
we fail fast since there is no compatibility surface yet).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


class MeshUnavailableError(RuntimeError):
    """A requested or persisted device-mesh topology needs more devices
    than the process has. Raised loudly instead of silently degrading to
    a single-chip layout (frontend/build.py config_from_json,
    parallel/sharded_agg.py make_mesh): recovering a mesh-sharded job
    without its mesh would quietly fall back to an unsharded plan. Either
    restart with enough devices (on CPU:
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``) or re-shard
    explicitly (``config_from_json(..., allow_reshard=True)`` — the
    sharded state re-shards by replaying the vnode mapping on load)."""


@dataclasses.dataclass
class StreamingConfig:
    # reference: config.rs streaming section + system params
    barrier_interval_ms: int = 1000
    checkpoint_frequency: int = 10
    in_flight_barrier_nums: int = 1
    chunk_capacity: int = 1024
    agg_table_capacity: int = 1 << 16
    join_key_capacity: int = 1 << 13
    join_bucket_width: int = 16
    topn_table_capacity: int = 1 << 16
    # actor parallelism per fragmentable operator (grouped aggs, joins):
    # >1 builds multi-fragment jobs with hash-dispatch exchanges
    # (frontend/fragments.py; reference: streaming.default_parallelism)
    fragment_parallelism: int = 1
    # epoch co-scheduling (stream/coschedule.py): eligible MVs (NEXmark
    # bid source → projection → grouped agg) created while this is true
    # are batched into ONE fused XLA dispatch per tick for the whole
    # group instead of one executor pipeline each; ineligible shapes
    # fall back to the solo executor path (docs/performance.md)
    coschedule: bool = False
    # the heterogeneous tick compiler (stream/tick_compiler.py):
    # eligible MVs created while this is true join a compiled dispatch
    # schedule — jobs sharing an operator skeleton pad into shape-class
    # supergroups (one vmapped dispatch per class), the rest
    # concatenate into jitted mega-epochs — so N dissimilar small MVs
    # tick in a handful of dispatches instead of N. Recompiled only on
    # DDL; takes precedence over ``coschedule`` for eligible shapes.
    tick_compiler: bool = False
    # device mesh for the mesh-sharded paths (parallel/): N >= 1 builds a
    # 1-D mesh over the first N local devices (BuildConfig.mesh) so
    # grouped aggs/joins shard across chips — and, with ``coschedule``
    # on, eligible fused MVs take the mesh-sharded fused-epoch path
    # (ops/fused_sharded.py): one dispatch per epoch across ALL chips.
    # Refuses loudly (MeshUnavailableError) when the process has fewer
    # devices. 0/None = single-chip.
    mesh_shape: Optional[int] = None
    # asynchronous epoch pipeline (docs/performance.md "Pipelined
    # tick"): 1 = the classic synchronous cycle (every fused flush
    # resolves in its own tick); 2 = double-buffered epochs — each
    # epoch's packed flush fetch defers across the tick boundary (the
    # copy streams while the previous barrier's host work runs, so
    # resolving it next tick is nearly free) and epoch N+1's dispatch
    # launches before epoch N's flush chunks are decoded/materialized,
    # so that host work + the checkpoint encode overlap device
    # compute. State threads on-device, so results are
    # bit-exact; reads simply see the previous barrier's snapshot
    # between drain points (checkpoint barriers, FLUSH, DDL). Applies
    # to the fused surfaces (coschedule/shardfused) and moves the
    # durable checkpoint encode off the barrier path.
    pipeline_depth: int = 1
    # LEGACY aliases of [observability] trace_ring_capacity /
    # slow_epoch_threshold_ms (kept so existing configs keep working;
    # an explicitly-set [observability] value wins — see
    # ObservabilityConfig below)
    trace_ring_capacity: int = 4096
    slow_epoch_threshold_ms: float = 0.0


@dataclasses.dataclass
class StorageConfig:
    data_dir: Optional[str] = None          # None = RAM-only playground
    segment_target_bytes: int = 4 << 20
    # durable-tier backend: "segment" = epoch-delta log + in-process fold
    # (storage/checkpoint.py); "hummock" = L0 SSTs under a meta-managed
    # version with a compactor role (storage/hummock.py). None = AUTO:
    # recovery detects an existing dir's tier; a new dir gets "segment".
    # The default must stay None — a concrete default would be
    # indistinguishable from an explicit choice and would silently open
    # an existing hummock dir as a fresh segment store.
    state_store: Optional[str] = None
    # dedicated compactor worker processes (hummock tier only; 0 keeps
    # compaction on an in-process background thread)
    compactors: int = 0


@dataclasses.dataclass
class BatchConfig:
    """Serving-plane knobs for batch reads (frontend/serving.py;
    reference capability: the batch section + frontend query caches of
    src/common/src/config.rs — distributed query execution and the
    per-frontend plan caches)."""

    # version-pinned plan+compilation cache: entries keyed on the
    # statement's canonical form; an entry survives data-version bumps
    # (it re-executes against the new snapshot WITHOUT replanning or new
    # jit compilations) and is evicted LRU past this bound. 0 disables.
    serving_cache_size: int = 64
    # two-phase distributed aggregation: number of per-vnode-slice
    # partial tasks a local grouped agg splits into (clamped to the
    # vnode count; 0/1 keeps single-phase execution)
    serving_tasks: int = 4
    # thread pool executing local partial tasks (BatchTaskManager)
    serving_threads: int = 4
    # optimistic concurrent reads: attempts to observe a quiescent data
    # version before falling back to the API-locked path
    serving_read_retries: int = 32


@dataclasses.dataclass
class FaultConfig:
    """Fault-tolerance knobs for every external boundary (common/retry.py,
    storage/object_store.py, connector/broker.py, stream/sink.py,
    frontend/remote.py). Reference capability: object-store retry config +
    sink retry/decouple knobs (src/common/src/config.rs storage.object
    retry section; sink decouple system params)."""

    # object-store IO retry (RetryingObjectStore under hummock/segment/
    # compactor/backup)
    io_retry_attempts: int = 5
    io_retry_base_ms: float = 10.0
    io_retry_max_ms: float = 1000.0
    io_retry_deadline_ms: float = 30_000.0
    # sink delivery retry + degrade (stream/sink.py): past
    # ``sink_degrade_after`` consecutive failed epochs the sink job
    # degrades (log accumulates, barriers keep committing) instead of
    # failing the epoch; past ``sink_log_cap_rows`` logged-undelivered
    # rows it fails loudly (bounded-log backpressure)
    sink_retry_attempts: int = 3
    sink_retry_base_ms: float = 20.0
    sink_retry_deadline_ms: float = 2000.0
    sink_degrade_after: int = 3
    sink_log_cap_rows: int = 1_000_000
    # broker client reconnect-with-backoff (connector/broker.py)
    broker_reconnect_attempts: int = 6
    broker_reconnect_base_ms: float = 25.0
    broker_reconnect_max_ms: float = 1000.0
    # worker control-frame deadlines (frontend/remote.py): a wedged
    # worker trips these instead of hanging the session forever
    worker_request_timeout_s: float = 120.0
    worker_epoch_timeout_s: float = 300.0
    # idle-link keepalive on worker↔worker exchange sockets
    # (rpc/exchange.py): a half-open peer socket — peer died without a
    # FIN, or a severed link — is probed with exg_ping and declared
    # broken after ``exchange_keepalive_timeout_s`` without a pong, so
    # the pool evicts it BEFORE the next epoch's send burns a permit on
    # a doomed frame. 0 disables probing.
    exchange_keepalive_s: float = 10.0
    exchange_keepalive_timeout_s: float = 5.0
    # seeded object-store fault injection (tests / sim chaos only)
    inject_object_store_transient_rate: float = 0.0
    inject_object_store_torn_write_rate: float = 0.0
    inject_object_store_seed: int = 0

    def io_retry_policy(self):
        from .retry import RetryPolicy
        from ..storage.object_store import PermanentObjectStoreError
        return RetryPolicy(
            max_attempts=self.io_retry_attempts,
            base_delay_ms=self.io_retry_base_ms,
            max_delay_ms=self.io_retry_max_ms,
            deadline_ms=self.io_retry_deadline_ms,
            retryable=(OSError, ConnectionError, TimeoutError),
            non_retryable=(PermanentObjectStoreError,))

    def sink_retry_policy(self):
        from .retry import RetryPolicy
        return RetryPolicy(
            max_attempts=self.sink_retry_attempts,
            base_delay_ms=self.sink_retry_base_ms,
            max_delay_ms=max(self.sink_retry_base_ms * 8, 250.0),
            deadline_ms=self.sink_retry_deadline_ms,
            retryable=(Exception,))

    def broker_retry_policy(self):
        from .retry import RetryPolicy
        return RetryPolicy(
            max_attempts=self.broker_reconnect_attempts,
            base_delay_ms=self.broker_reconnect_base_ms,
            max_delay_ms=self.broker_reconnect_max_ms,
            retryable=(OSError, ConnectionError, TimeoutError))


@dataclasses.dataclass
class UdfConfig:
    """Out-of-process UDF plane knobs (udf/client.py, docs/robustness.md
    "UDF isolation plane"; reference capability: the Arrow-Flight UDF
    boundary of src/udf/src/lib.rs — user code behind a wire so it can
    never wedge an epoch). Registered UDFs evaluate in a dedicated
    server PROCESS over the rpc/wire.py frame protocol; the client side
    enforces per-call deadlines, kill + seeded respawn + bounded-retry
    batch replay, generation fencing, and bounded in-flight batches."""

    #: "process" = out-of-process evaluation (the default robustness
    #: contract); "inproc" = the documented DEGRADED mode — user code
    #: runs inside the calling process on the tick path (tests, or
    #: environments that cannot spawn subprocesses)
    mode: str = "process"
    #: attach to an already-running server ("host:port", e.g. one
    #: started with `ctl udf serve`) instead of auto-spawning; the
    #: client cannot kill an external server, so crash recovery
    #: degrades to reconnect-and-replay
    addr: Optional[str] = None
    #: per-call deadline: a batch whose reply misses it is treated as a
    #: wedged/crashed server — kill, respawn, replay (bounded below)
    call_timeout_s: float = 10.0
    #: deadline on server spawn + registration replay
    spawn_timeout_s: float = 30.0
    #: bounded-retry replay: attempts per batch beyond the first (each
    #: retry respawns the server); exhausted retries surface a typed
    #: UdfTimeoutError/UdfCallError that fails the STATEMENT, never the
    #: epoch loop
    max_retries: int = 2
    #: backpressure: batches admitted into the boundary concurrently;
    #: excess callers wait up to queue_timeout_s then fail typed
    #: (UdfOverloadedError) instead of queueing unboundedly
    max_inflight: int = 4
    queue_timeout_s: float = 30.0


@dataclasses.dataclass
class AutoscalerConfig:
    """Backlog-driven autoscaler policy (meta/autoscaler.py): watches
    the per-edge exchange counters (permits_waited, backlog —
    rpc/exchange.py EdgeStats) and the slow-epoch detector
    (common/tracing.py) and grows/shrinks a spanning job's fragment
    parallelism by issuing live rescale plans (meta/rescale.py,
    docs/scaling.md). Hysteresis + cooldown keep it from flapping under
    oscillating load; all thresholds are per observation (one barrier
    tick)."""

    enabled: bool = False
    # scale-OUT triggers: any one sustained for ``hysteresis``
    # consecutive observations fires target = parallelism * 2
    high_backlog: int = 64            # queued chunks across the job's edges
    high_permits_waited: int = 16     # new permit waits since last observe
    high_slow_epochs: int = 1         # slow-epoch detections since last
    # scale-IN: ALL load signals at/below these for ``scale_in_after``
    # consecutive observations fires target = parallelism // 2
    low_backlog: int = 0
    low_permits_waited: int = 0
    # consecutive high observations required before scaling out
    hysteresis: int = 3
    # observations after ANY decision during which no new decision may
    # fire (and streaks reset) — the anti-flap guard
    cooldown: int = 16
    # consecutive all-quiet observations required before scaling in
    # (deliberately >> hysteresis: scale-in re-migrates state, so it
    # must be much lazier than scale-out)
    scale_in_after: int = 32
    min_parallelism: int = 1
    max_parallelism: int = 8


@dataclasses.dataclass
class ObservabilityConfig:
    """Device profiling plane + tracing knobs (common/profiling.py,
    common/tracing.py, docs/observability.md). Reference capability:
    the monitor-service profiling handlers + streaming metrics config
    (src/compute/src/rpc/service/monitor_service.rs)."""

    # per-dispatch telemetry (DispatchProfiler): wall seconds, recompile
    # events, trace-ring spans for every profiled dispatch site. Pure
    # host bookkeeping — adds zero dispatches (CI-guarded); off turns
    # the wrappers into passthroughs.
    profiling: bool = True
    # dispatch spans shorter than this skip the trace ring (0 = record
    # every dispatch; the ring is bounded either way)
    dispatch_span_min_ms: float = 0.0
    # span ring + slow-epoch detector — the canonical home of the knobs
    # that used to live only on [streaming] (which still works as a
    # legacy alias). Unset (None) inherits the alias; ANY value set
    # here wins, including one equal to the alias default (effective
    # defaults: 4096 spans, 0.0 = detector off)
    trace_ring_capacity: Optional[int] = None
    slow_epoch_threshold_ms: Optional[float] = None
    # barrier observatory (common/barrier_ledger.py): how many sealed
    # per-barrier waterfall records the history ring retains
    # (rw_catalog.rw_barrier_history, ctl trace barrier)
    barrier_history_capacity: int = 256
    # slow-epoch capture ring: how many offending epochs' span-tree +
    # waterfall captures Session.slow_epochs() retains (was a hardcoded
    # 16 before the [observability] knob existed)
    slow_epoch_capture_capacity: int = 16
    # cluster-wide HBM ledger: resident state + analyzed peak temp
    # bytes are charged against this capacity (default 16 GiB ≈ one
    # v5e chip); a job reaching hbm_warn_fraction of it is flagged
    hbm_capacity_bytes: int = 16 << 30
    hbm_warn_fraction: float = 0.8
    # roofline model peaks (ctl profile roofline): chip peak FLOP/s and
    # HBM bandwidth in bytes/s (defaults ≈ TPU v4: 275 TFLOP/s bf16,
    # 1.2 TB/s)
    chip_peak_flops: float = 275e12
    chip_peak_bandwidth: float = 1.2e12


@dataclasses.dataclass
class MetaConfig:
    """The meta control plane attachment + frontend admission knobs
    (docs/control-plane.md; reference: src/meta/src/rpc/server.rs).

    ``addr`` empty means in-process meta — the playground default, with
    behavior bit-identical to before the control plane grew a process
    boundary. Set it (``host:port``) and the session attaches through a
    ``MetaClient`` instead; combined with ``Session(role="serving")``
    that is how a frontend fleet shares one writer's state."""

    #: "host:port" of a `ctl meta serve` process; "" = in-process meta
    addr: str = ""
    #: pgwire admission control: max queries executing concurrently per
    #: frontend process (the rest queue), and per-connection in-flight cap
    admission_max_inflight: int = 8
    admission_per_conn_inflight: int = 2
    #: queries allowed to WAIT beyond the in-flight cap before the
    #: frontend sheds load with a PG error (bounded queue: overload
    #: degrades with bounded p99 instead of collapsing)
    admission_queue_depth: int = 64
    #: leader lease TTL: a writer missing this many seconds of
    #: heartbeats is declared down and standbys elect (bounds failover
    #: MTTR from above; too low and a long GC pause looks like death)
    lease_ttl_s: float = 2.0
    #: writer heartbeat period; keep well under lease_ttl_s so several
    #: consecutive renewals must fail before the lease expires
    heartbeat_s: float = 0.5
    #: per-candidate jitter cap before racing lease.acquire on
    #: leader_down — spreads CAS attempts without delaying the winner
    #: by more than this
    election_backoff_ms: float = 100.0


@dataclasses.dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 4566
    telemetry_enabled: bool = False         # reference: telemetry/


@dataclasses.dataclass
class RwConfig:
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    streaming: StreamingConfig = dataclasses.field(
        default_factory=StreamingConfig)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    batch: BatchConfig = dataclasses.field(default_factory=BatchConfig)
    fault: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    autoscaler: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig)
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig)
    udf: UdfConfig = dataclasses.field(default_factory=UdfConfig)
    meta: MetaConfig = dataclasses.field(default_factory=MetaConfig)


def _parse_toml_subset(text: str) -> dict:
    """Fallback parser for the config-file TOML subset (``[section]`` +
    scalar ``key = value`` lines) on interpreters without ``tomllib``
    (< 3.11, no vendored tomli). Enough for every rw_config knob: ints,
    floats, bools, quoted strings."""
    data: dict = {}
    section: dict = data
    for raw in text.splitlines():
        # strip comments, but only a '#' OUTSIDE quotes starts one
        line = raw
        quote = None
        for i, ch in enumerate(raw):
            if quote:
                if ch == quote:
                    quote = None
            elif ch in "'\"":
                quote = ch
            elif ch == "#":
                line = raw[:i]
                break
        line = line.strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = data.setdefault(line[1:-1].strip(), {})
            continue
        key, sep, val = line.partition("=")
        if not sep:
            raise ValueError(f"unparseable config line: {raw!r}")
        key, val = key.strip(), val.strip()
        if val.startswith(("'", '"')) and val.endswith(val[0]):
            section[key] = val[1:-1]
        elif val in ("true", "false"):
            section[key] = val == "true"
        else:
            try:
                section[key] = int(val)
            except ValueError:
                section[key] = float(val)
    return data


def load_config(path: Optional[str] = None, **overrides: Any) -> RwConfig:
    """defaults ← TOML file ← dotted-key overrides
    (e.g. ``load_config("rw.toml", **{"streaming.checkpoint_frequency": 4})``)."""
    cfg = RwConfig()
    if path is not None:
        try:
            import tomllib
        except ModuleNotFoundError:
            tomllib = None
        if tomllib is not None:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        else:
            with open(path, "r", encoding="utf-8") as f:
                data = _parse_toml_subset(f.read())
        for section, values in data.items():
            _apply_section(cfg, section, values)
    for dotted, v in overrides.items():
        section, _, key = dotted.partition(".")
        if not key:
            raise ValueError(f"override key must be section.key: {dotted!r}")
        _apply_section(cfg, section, {key: v})
    return cfg


def _apply_section(cfg: RwConfig, section: str, values: dict) -> None:
    target = getattr(cfg, section, None)
    if target is None or not dataclasses.is_dataclass(target):
        raise ValueError(f"unknown config section {section!r}")
    names = {f.name for f in dataclasses.fields(target)}
    for k, v in values.items():
        if k not in names:
            raise ValueError(f"unknown config key {section}.{k}")
        setattr(target, k, v)


# -- system params (runtime-mutable; reference: system_param/mod.rs) ---------

#: params a live session accepts via SET; value = coercion fn
MUTABLE_SYSTEM_PARAMS = {
    "checkpoint_frequency": int,
    "barrier_interval_ms": int,
    "in_flight_barrier_nums": int,
    "slow_epoch_threshold_ms": float,
}
