"""Failpoints: named fault-injection sites on IO paths.

Counterpart of the reference's ``fail_point!`` sites
(reference: storage IO failpoints e.g.
src/storage/src/hummock/sstable_store.rs:285,676 and the
storage_failpoints test crate). Production cost is one dict lookup per
site; tests arm sites with an exception (raise once or always) or a
callable, to prove the durability contract holds when the disk misbehaves
mid-checkpoint.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional

_ARMED: Dict[str, Any] = {}

#: Every failpoint site in the codebase, declared up front so the
#: crash-point sweep (sim.py) can iterate ALL of them — including sites
#: a particular workload has not executed yet. This literal is the
#: source of truth the lint-time honesty rule reads STATICALLY
#: (``rwlint``'s failpoint-honesty, docs/static-analysis.md): the set
#: of ``fail_point("...")`` literals in the package must equal this
#: set, both directions, so the declared⊇executed guarantee holds
#: before any test runs. Keep it a pure literal — computed entries
#: would be invisible to the static check.
DECLARED_SITES: "frozenset[str]" = frozenset({
    # segment checkpoint log (storage/checkpoint.py) — incl. both 2PC
    # phases of the spanning-job cluster checkpoint
    "checkpoint.manifest.write",
    "checkpoint.manifest.rename",
    "checkpoint.segment.write",
    "checkpoint.segment.write.partial",
    "checkpoint.prepare",
    "checkpoint.commit",
    "checkpoint.settle",
    # hummock tier (storage/hummock.py, meta/hummock.py)
    "hummock.sst.write",
    "hummock.sst.write.partial",
    "hummock.version.publish",
    "compactor.task.start",
    "compactor.output.write",
    "compactor.merge.step",
    # sink delivery (stream/sink.py)
    "sink.deliver",
    # elastic scaling plane (frontend/session.py _rescale_spanning):
    # after the handoff export / after the placement commit — the
    # rollback/roll-forward watershed of a live vnode migration
    "rescale.migrate",
    "rescale.commit",
    # meta store durable txn append (meta/store.py)
    "meta.store.txn",
    # out-of-process UDF plane (udf/client.py, udf/server.py — ISSUE
    # 15): client-side spawn / batch send / reply decode / kill+respawn,
    # plus the SERVER-side eval site (armed via RWTPU_FAILPOINTS env in
    # the server subprocess — an "exit" action there is a deterministic
    # kill -9 of the server mid-batch)
    "udf.spawn",
    "udf.call",
    "udf.reply",
    "udf.respawn",
    "udf.server.eval",
})

#: The RUNTIME registry: seeded from the declaration, grown by
#: ``fail_point`` self-registration at first execution (a site added
#: without updating DECLARED_SITES still shows up here after it first
#: runs — and fails the lint until declared).
KNOWN_SITES: "set[str]" = set(DECLARED_SITES)


def register_site(*names: str) -> None:
    KNOWN_SITES.update(names)


def declared_sites() -> List[str]:
    """Runtime mirror of the ``DECLARED_SITES`` literal, sorted for
    stable iteration. The honesty lint reads the literal STATICALLY
    from the AST and the crash-point sweep iterates
    ``registered_sites()``; this helper is for tooling/tests that want
    the declared set at runtime (the lint-wiring smoke cross-checks the
    lint's static parse against it)."""
    return sorted(DECLARED_SITES)


def registered_sites() -> List[str]:
    return sorted(KNOWN_SITES)


def fail_point(name: str) -> None:
    """Call at an IO site; raises/executes whatever the test armed."""
    KNOWN_SITES.add(name)
    action = _ARMED.get(name)
    if action is None:
        return
    if isinstance(action, tuple) and action[0] == "once":
        _ARMED.pop(name, None)
        action = action[1]
    if isinstance(action, BaseException) or (
            isinstance(action, type) and issubclass(action, BaseException)):
        raise action if not isinstance(action, type) else action(name)
    if callable(action):
        action()


def arm(name: str, action: Any, once: bool = False) -> None:
    """Arm a site. The site must be REGISTERED (declared up front in
    ``DECLARED_SITES``, or self-registered by a prior execution): arming
    an unknown name used to succeed silently and never fire — a typo'd
    test proved nothing, and a new plane could add sites the crash-point
    sweep never swept. Registry hygiene (ISSUE 15 satellite): declare
    the site first, so the sweep and the failpoint-honesty lint see it."""
    if name not in KNOWN_SITES:
        raise ValueError(
            f"failpoint {name!r} is not a declared site — add it to "
            "common/failpoint.py DECLARED_SITES (the crash-point sweep "
            "and the failpoint-honesty lint iterate that registry)")
    _ARMED[name] = ("once", action) if once else action


def disarm(name: Optional[str] = None) -> None:
    if name is None:
        _ARMED.clear()
    else:
        _ARMED.pop(name, None)


def arm_from_env(worker_id: Optional[int] = None) -> int:
    """Subprocess bring-up (worker/compactor): arm sites from the
    ``RWTPU_FAILPOINTS`` env JSON — ``{"site": {"action": "exit",
    "once_marker": "/path", "worker": 1}}``. Action "exit" is a REAL
    process death (``os._exit``) at the site, the crash-point sweep's
    way of killing a worker at an exact instruction; the marker file
    makes it fire once across respawns (the respawned worker inherits
    the env, sees the marker, and lives), and "worker" scopes the kill
    to ONE deterministic victim (a broadcast frame like phase-2 commit
    reaches every worker — without the scope the death count races).
    Returns the number of sites armed."""
    import json
    import os
    spec = os.environ.get("RWTPU_FAILPOINTS")
    if not spec:
        return 0
    n = 0
    for site, cfg in json.loads(spec).items():
        if cfg.get("worker") is not None and worker_id is not None \
                and int(cfg["worker"]) != int(worker_id):
            continue
        action = cfg.get("action", "exit")
        if action == "exit":
            marker = cfg.get("once_marker")

            def _die(marker=marker, site=site):
                if marker:
                    if os.path.exists(marker):
                        return
                    with open(marker, "w") as f:
                        f.write(site)
                os._exit(31)

            arm(site, _die)
            n += 1
        elif action == "raise":
            arm(site, OSError(site), once=bool(cfg.get("once")))
            n += 1
    return n


@contextlib.contextmanager
def failpoints(**points: Any):
    """with failpoints(**{"checkpoint.segment.write": OSError}): ..."""
    for n, a in points.items():
        arm(n, a)
    try:
        yield
    finally:
        for n in points:
            disarm(n)
