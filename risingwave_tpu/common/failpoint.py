"""Failpoints: named fault-injection sites on IO paths.

Counterpart of the reference's ``fail_point!`` sites
(reference: storage IO failpoints e.g.
src/storage/src/hummock/sstable_store.rs:285,676 and the
storage_failpoints test crate). Production cost is one dict lookup per
site; tests arm sites with an exception (raise once or always) or a
callable, to prove the durability contract holds when the disk misbehaves
mid-checkpoint.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Optional

_ARMED: Dict[str, Any] = {}


def fail_point(name: str) -> None:
    """Call at an IO site; raises/executes whatever the test armed."""
    action = _ARMED.get(name)
    if action is None:
        return
    if isinstance(action, tuple) and action[0] == "once":
        _ARMED.pop(name, None)
        action = action[1]
    if isinstance(action, BaseException) or (
            isinstance(action, type) and issubclass(action, BaseException)):
        raise action if not isinstance(action, type) else action(name)
    if callable(action):
        action()


def arm(name: str, action: Any, once: bool = False) -> None:
    _ARMED[name] = ("once", action) if once else action


def disarm(name: Optional[str] = None) -> None:
    if name is None:
        _ARMED.clear()
    else:
        _ARMED.pop(name, None)


@contextlib.contextmanager
def failpoints(**points: Any):
    """with failpoints(**{"checkpoint.segment.write": OSError}): ..."""
    for n, a in points.items():
        arm(n, a)
    try:
        yield
    finally:
        for n in points:
            disarm(n)
