"""RetryPolicy: bounded retry with exponential backoff + full jitter.

Counterpart of the reference's retry strategies on external boundaries
(reference: src/object_store/src/object/mod.rs ObjectStoreConfig retry
knobs; src/connector/src/sink — sink retry/backoff before a sink is
declared unhealthy). Every place this build talks to something that can
fail independently — object store, broker socket, external sink, worker
control frames — routes the call through one policy object so backoff
shape, attempt caps, wall-clock deadlines, and error classification are
uniform and observable.

Observability: every ``run`` records per-site counters into a global
registry (attempts / retries / successes / give-ups / non-retryable),
federated into ``Session.metrics()["retry"]`` and the Prometheus
exposition — "is something quietly retrying?" is a dashboard read, not a
log dig.

Determinism: jitter draws from an injectable RNG and sleeps through an
injectable sleep fn, so tests pin both.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type


class RetryError(RuntimeError):
    """Retry budget exhausted; ``__cause__`` is the last real error."""


class _RetryMetrics:
    """Per-site retry counters (process-global, thread-safe)."""

    _FIELDS = ("attempts", "retries", "successes", "give_ups",
               "non_retryable")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sites: dict[str, dict] = {}

    def _site(self, site: str) -> dict:
        s = self._sites.get(site)
        if s is None:
            s = self._sites[site] = {f: 0 for f in self._FIELDS}
        return s

    def bump(self, site: str, field: str, n: int = 1) -> None:
        with self._lock:
            self._site(site)[field] += n

    def snapshot(self) -> dict:
        with self._lock:
            return {site: dict(c) for site, c in sorted(self._sites.items())}

    def reset(self) -> None:
        with self._lock:
            self._sites.clear()


#: process-global registry (the session is the scrape point)
GLOBAL_RETRY_METRICS = _RetryMetrics()


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with FULL jitter: attempt k (1-based) sleeps
    uniform(0, min(max_delay, base * 2**(k-1))) before attempt k+1.

    ``deadline_ms`` is a wall-clock budget across ALL attempts: once it
    elapses, the next failure gives up even with attempts remaining (a
    slow boundary must not absorb unbounded barrier time).
    ``retryable``/``non_retryable`` classify errors; non_retryable wins
    (programming errors and permanent backend failures surface at once).
    """

    max_attempts: int = 5
    base_delay_ms: float = 10.0
    max_delay_ms: float = 2000.0
    deadline_ms: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = (
        OSError, ConnectionError, TimeoutError)
    non_retryable: Tuple[Type[BaseException], ...] = ()

    def classify(self, exc: BaseException) -> bool:
        """True iff ``exc`` is worth another attempt."""
        if isinstance(exc, self.non_retryable):
            return False
        return isinstance(exc, self.retryable)

    def backoff_ms(self, attempt: int, rng=None) -> float:
        """Full-jitter delay after failed attempt ``attempt`` (1-based)."""
        cap = min(self.max_delay_ms,
                  self.base_delay_ms * (2 ** max(0, attempt - 1)))
        return (rng or random).uniform(0.0, cap)

    def run(self, site: str, fn: Callable, *args,
            rng=None, sleep: Callable[[float], None] = time.sleep,
            metrics: _RetryMetrics = None, **kwargs):
        """Call ``fn(*args, **kwargs)`` under this policy; ``site`` names
        the boundary for the counter registry. Raises ``RetryError`` (with
        the last error as cause) past the budget; non-retryable errors
        pass straight through."""
        m = metrics if metrics is not None else GLOBAL_RETRY_METRICS
        deadline = (None if self.deadline_ms is None
                    else time.monotonic() + self.deadline_ms / 1e3)
        attempt = 0
        while True:
            attempt += 1
            m.bump(site, "attempts")
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 - classified below
                if not self.classify(e):
                    m.bump(site, "non_retryable")
                    raise
                out_of_time = (deadline is not None
                               and time.monotonic() >= deadline)
                if attempt >= self.max_attempts or out_of_time:
                    m.bump(site, "give_ups")
                    raise RetryError(
                        f"{site}: gave up after {attempt} attempt(s)"
                        + (" (deadline exceeded)" if out_of_time else "")
                    ) from e
                m.bump(site, "retries")
                delay_s = self.backoff_ms(attempt, rng) / 1e3
                if deadline is not None:
                    delay_s = max(0.0, min(delay_s,
                                           deadline - time.monotonic()))
                if delay_s > 0:
                    sleep(delay_s)
                continue
            m.bump(site, "successes")
            return out
