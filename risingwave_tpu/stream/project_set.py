"""ProjectSet: set-returning functions in the select list.

Counterpart of the reference's ProjectSetExecutor + table functions
(reference: src/stream/src/executor/project_set.rs,
src/expr/src/table_function/ — generate_series, unnest…). Each input row
yields one output row per element of the table function's result; plain
expressions are replicated. The output stream key is the input key plus a
hidden element index (the reference's ``projected_row_id``).

Update pairs are rewritten to Delete+Insert on expansion: the old and new
rows of a pair may generate different element counts, so pairwise U-/U+
alignment cannot be preserved in general (same rule as the reference's
dispatch when keys change).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from ..common.chunk import (
    DEFAULT_CHUNK_CAPACITY, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, StreamChunk, make_chunk,
)
from ..common.types import DataType, Field, INT64, Schema
from ..expr.expr import Expr
from .executor import Executor, SingleInputExecutor

TABLE_FUNC_KINDS = {"generate_series", "regexp_split_to_table", "unnest"}


@dataclasses.dataclass(frozen=True, eq=False)
class TableFuncCall(Expr):
    """A set-returning call; only valid inside PProjectSet / FROM position
    (row-wise eval is meaningless — the planner intercepts it)."""

    name: str
    args: tuple
    type: DataType = INT64

    def eval(self, chunk):  # pragma: no cover
        raise RuntimeError("table function outside ProjectSet")


def series_values(name: str, args: Sequence) -> list:
    """Host evaluation for one row's argument values → list of elements."""
    if name == "generate_series":
        if len(args) == 2:
            lo, hi, step = args[0], args[1], 1
        else:
            lo, hi, step = args
        if lo is None or hi is None or step in (None, 0):
            return []
        return list(range(int(lo), int(hi) + (1 if step > 0 else -1),
                          int(step)))
    if name == "regexp_split_to_table":
        # args arrive as dictionary ids (ProjectSet path) or python
        # strings (constant FROM position); elements return as ids
        # (reference: src/expr/src/table_function/ set-returning regexp)
        import re
        from ..common.types import GLOBAL_STRING_DICT as D

        def as_str(v):
            return v if isinstance(v, str) else D.lookup(int(v))

        s, p = args
        if s is None or p is None:
            return []
        parts = re.split(as_str(p), as_str(s))
        return [D.intern(x) for x in parts]
    if name == "unnest":
        # one row per array element (reference:
        # src/expr/src/table_function/ unnest). The argument is a
        # list-dictionary id (ProjectSet path) or the python tuple itself
        # (constant FROM position); elements return as PHYSICAL scalars.
        from ..common.types import GLOBAL_LIST_DICT, GLOBAL_STRING_DICT
        (lst,) = args
        if lst is None:
            return []
        if not isinstance(lst, (tuple, list)):
            lst = GLOBAL_LIST_DICT.lookup(int(lst))
        return [GLOBAL_STRING_DICT.intern(e) if isinstance(e, str) else e
                for e in lst]
    raise ValueError(f"unknown table function {name}")


class ProjectSetExecutor(SingleInputExecutor):
    identity = "ProjectSet"

    def __init__(self, input: Executor, exprs: Sequence[Expr],
                 names: Sequence[str],
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY):
        super().__init__(input)
        self.exprs = list(exprs)
        self.schema = Schema(tuple(
            Field(n, e.type) for n, e in zip(names, self.exprs)))
        self.out_capacity = out_capacity

    async def map_chunk(self, chunk: StreamChunk):
        vis = np.asarray(chunk.vis)
        ops = np.asarray(chunk.ops)
        # vectorized eval of every expression / table-func argument
        plain_cols: dict[int, tuple] = {}
        tf_args: dict[int, list] = {}
        for ci, e in enumerate(self.exprs):
            if isinstance(e, TableFuncCall):
                cols = [a.eval(chunk) for a in e.args]
                tf_args[ci] = [
                    (np.asarray(c.data), np.asarray(c.mask)) for c in cols]
            else:
                c = e.eval(chunk)
                plain_cols[ci] = (np.asarray(c.data), np.asarray(c.mask))
        out_rows: list = []
        out_ops: list = []
        for i in np.nonzero(vis)[0]:
            op = int(ops[i])
            if op == OP_UPDATE_DELETE:
                op = OP_DELETE
            elif op == OP_UPDATE_INSERT:
                op = OP_INSERT
            base = {}
            for ci, (data, mask) in plain_cols.items():
                base[ci] = data[i].item() if mask[i] else None
            series: list = [()]
            for ci, e in enumerate(self.exprs):
                if isinstance(e, TableFuncCall):
                    argv = [d[i].item() if m[i] else None
                            for d, m in tf_args[ci]]
                    elems = series_values(e.name, argv)
                    series = [(ci, v, idx) for idx, v in enumerate(elems)]
            for ci, v, idx in series:
                row = [None] * len(self.exprs)
                for pc, bv in base.items():
                    row[pc] = bv
                row[ci] = v
                # hidden element index lives in the last column (the
                # planner appends the _pidx field)
                if self.schema.names[-1] == "_pidx":
                    row[-1] = idx
                out_rows.append(tuple(row))
                out_ops.append(op)
        i = 0
        while i < len(out_rows):
            take_rows = out_rows[i:i + self.out_capacity]
            take_ops = out_ops[i:i + self.out_capacity]
            i += len(take_rows)
            yield make_chunk(self.schema, take_rows, ops=take_ops,
                             capacity=max(self.out_capacity, len(take_rows)),
                             physical=True)
