"""Union / Values executors.

Counterparts of the reference's UnionExecutor and ValuesExecutor
(reference: src/stream/src/executor/union.rs, executor/values.rs). Union is
an N-way fan-in over aligned barriers (align_streams); watermarks are
re-emitted as the *minimum* across inputs per column, the reference's
BufferedWatermarks semantics (executor/union.rs + common watermark buffer):
a downstream may only see watermark W when every input has reached W.

Values emits its literal rows once, right after the first barrier — how the
reference seeds ``INSERT INTO … VALUES`` / ``CREATE TABLE AS`` plans.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common.chunk import StreamChunk, make_chunk
from ..common.types import Schema
from .barrier_align import align_streams
from .executor import Executor
from .message import Barrier, Watermark


class UnionExecutor(Executor):
    identity = "Union"

    def __init__(self, inputs: Sequence[Executor]):
        assert inputs, "union of nothing"
        self.inputs = list(inputs)
        self.schema = inputs[0].schema
        for inp in inputs[1:]:
            if [f.type.kind for f in inp.schema] != \
               [f.type.kind for f in self.schema]:
                raise ValueError("union inputs must have identical schemas")
        # per (input, col) watermark; emit min across inputs when it advances
        self._wm: dict[tuple[int, int], int] = {}
        self._emitted_wm: dict[int, int] = {}

    async def execute(self):
        named = {i: inp for i, inp in enumerate(self.inputs)}
        async for ev in align_streams(named):
            kind = ev[0]
            if kind == "chunk":
                yield ev[2]
            elif kind == "barrier":
                yield ev[1]
                if ev[1].is_stop():
                    return
            elif kind == "watermark":
                _, name, wm = ev
                self._wm[(name, wm.col_idx)] = wm.value
                per_input = [
                    self._wm.get((i, wm.col_idx)) for i in range(len(self.inputs))
                ]
                if all(v is not None for v in per_input):
                    low = min(per_input)
                    if self._emitted_wm.get(wm.col_idx) != low:
                        self._emitted_wm[wm.col_idx] = low
                        yield Watermark(wm.col_idx, low)


class ValuesExecutor(Executor):
    """Emits literal rows once after the first barrier, then only barriers."""

    identity = "Values"

    def __init__(self, schema: Schema, rows: Sequence[Sequence],
                 barrier_source: Executor, capacity: Optional[int] = None):
        self.schema = schema
        self._rows = list(rows)
        self._barriers = barrier_source
        self._capacity = capacity

    async def execute(self):
        emitted = False
        async for msg in self._barriers.execute():
            if isinstance(msg, Barrier):
                yield msg
                if not emitted:
                    emitted = True
                    cap = self._capacity or max(len(self._rows), 1)
                    for i in range(0, len(self._rows), cap):
                        yield make_chunk(self.schema, self._rows[i:i + cap],
                                         capacity=cap)
                if msg.is_stop():
                    return
