"""Source executors and test sources.

``MockSource`` mirrors the reference's test utility of the same name
(reference: src/stream/src/executor/test_utils.rs) — a scripted sequence of
messages. ``ScheduledSource`` drives a pull-based generator with periodic
barrier injection, standing in for SourceExecutor + the meta barrier tick
until the barrier manager lands (reference:
src/stream/src/executor/source/source_executor.rs:39).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Callable, Iterable, Optional, Sequence

from ..common.chunk import StreamChunk
from ..common.types import Schema
from .executor import Executor
from .message import Barrier, Message, Mutation, MutationKind, Watermark


class MockSource(Executor):
    identity = "MockSource"

    def __init__(self, schema: Schema, messages: Iterable[Message]):
        self.schema = schema
        self._messages = list(messages)

    def reset(self, messages: Iterable[Message]) -> None:
        """Replay surface: swap in a fresh message script so a built (and
        jit-warmed) pipeline can be driven again (bench / recovery tests)."""
        self._messages = list(messages)

    async def execute(self) -> AsyncIterator[Message]:
        for m in self._messages:
            yield m
            await asyncio.sleep(0)


class ScheduledSource(Executor):
    """Pulls chunks from ``generator`` (a callable returning StreamChunk or
    None when exhausted) and injects a barrier every ``chunks_per_epoch``
    chunks; every ``checkpoint_frequency``-th barrier is a checkpoint
    (reference defaults: system_param/mod.rs:39-40)."""

    identity = "ScheduledSource"

    def __init__(
        self,
        schema: Schema,
        generator: Callable[[], Optional[StreamChunk]],
        chunks_per_epoch: int = 8,
        checkpoint_frequency: int = 10,
        first_epoch: int = 1,
        stop_after_epochs: Optional[int] = None,
    ):
        self.schema = schema
        self._gen = generator
        self._chunks_per_epoch = chunks_per_epoch
        self._checkpoint_frequency = checkpoint_frequency
        self._epoch = first_epoch
        self._stop_after = stop_after_epochs

    async def execute(self) -> AsyncIterator[Message]:
        n_barriers = 0
        # initial barrier opens the first epoch (reference: recovery injects an
        # init barrier before any data, barrier/recovery.rs:154-173)
        yield Barrier.new(self._epoch, checkpoint=False)
        while True:
            for _ in range(self._chunks_per_epoch):
                chunk = self._gen()
                if chunk is None:
                    yield Barrier.new(
                        self._epoch + 1, checkpoint=True,
                        mutation=Mutation(MutationKind.STOP),
                    )
                    return
                yield chunk
                await asyncio.sleep(0)
            self._epoch += 1
            n_barriers += 1
            ckpt = n_barriers % self._checkpoint_frequency == 0
            yield Barrier.new(self._epoch, checkpoint=ckpt)
            if self._stop_after is not None and n_barriers >= self._stop_after:
                yield Barrier.new(
                    self._epoch + 1, checkpoint=True,
                    mutation=Mutation(MutationKind.STOP),
                )
                return
