"""Shared cold-tier cache helpers for device-state executors.

HashAgg and HashJoin both treat device HBM as an LRU cache over the
durable StateTable tier (reference: ManagedLruCache,
src/stream/src/cache/managed_lru.rs). The two pieces they must agree on
live here so they cannot drift:

  * ``canonical_key`` — the host-side identity of an evicted key. Float
    keys MUST NOT round-trip through int() (2.3 and 2.7 would collide;
    r4 review found exactly that bug), ints must not round-trip through
    float (precision above 2**53).
  * ``LruClock`` — the per-chunk monotonic touch stamp. Returns None when
    no budget is set so jitted steps trace a static no-stamp variant.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def canonical_key(values, types) -> tuple:
    """np key scalars → canonical python values (identity-preserving)."""
    out = []
    for v, t in zip(values, types):
        out.append(float(v) if t.is_float else int(v))
    return tuple(out)


class LruClock:
    """Monotonic int32 stamp source; disabled (always None) without a
    budget, so executors can pass the result straight into their jitted
    step as a statically-absent argument."""

    def __init__(self, enabled: bool):
        self.enabled = enabled
        self._step = 0

    def next(self) -> Optional[jnp.ndarray]:
        if not self.enabled:
            return None
        self._step += 1
        return jnp.asarray(self._step, jnp.int32)

    def advance(self, k: int) -> Optional[jnp.ndarray]:
        """k consecutive stamps at once (int32[k]) for batched scan steps;
        None when disabled."""
        if not self.enabled:
            return None
        arr = jnp.arange(self._step + 1, self._step + k + 1,
                         dtype=jnp.int32)
        self._step += k
        return arr
