"""Concurrent backfill for MV-on-MV creation (VERDICT r3 item 8).

Counterpart of the reference's BackfillExecutor
(reference: src/stream/src/executor/backfill.rs:48-69 — snapshot-read the
upstream in chunks while live deltas keep flowing, forward deltas only for
the already-backfilled pk range, switch over when the snapshot is
exhausted; progress reported to meta, src/meta/src/barrier/progress.rs).

TPU-first shape: the upstream's durable StateTable is the snapshot source
(its merged view advances with every commit, giving the per-epoch re-read
the reference gets from Hummock epochs), the backfill cursor is the
upstream's memcomparable pk key, and the delta filter is ONE vectorized
mask per chunk — a lexicographic pk-tuple compare against the cursor
values, evaluated on device, identical in order to the encoded-key cursor
(common/row.py key encoding is order-preserving; VARCHAR pk columns
compare by dictionary rank).

Per barrier at most ``batch_rows`` snapshot rows are emitted, so creating
an MV over a huge upstream never stalls the barrier loop for more than one
batch. The cursor + done flag persist in a progress state table at
checkpoints; recovery resumes mid-backfill (or passes straight through
when done).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..common.chunk import StreamChunk, physical_chunk
from ..common.types import Field, INT64, Schema, VARCHAR
from ..storage.state_table import StateTable
from .executor import Executor
from .message import Barrier, Watermark

#: progress row: (id, done, cursor-hex, cursor-pk-values-json, rows_done).
#: The pk VALUES are persisted alongside the encoded cursor so a resumed
#: backfill filters deltas at EXACTLY the snapshot boundary — re-deriving
#: them from surviving rows would drift below the cursor when the row at
#: the cursor was deleted, silently masking deltas in the gap.
PROGRESS_SCHEMA = Schema((
    Field("id", INT64), Field("done", INT64),
    Field("cursor", VARCHAR), Field("cursor_pks", VARCHAR),
    Field("rows_done", INT64),
))


class BackfillExecutor(Executor):
    """``input``: the live-delta queue subscribed to the upstream bus.
    ``upstream_table``: the upstream MV/table's durable StateTable."""

    identity = "Backfill"

    def __init__(
        self,
        input: Executor,
        upstream_table: StateTable,
        batch_rows: int = 4096,
        chunk_capacity: int = 1024,
        progress_table: Optional[StateTable] = None,
        on_progress=None,
    ):
        self.input = input
        self.schema = upstream_table.schema
        self.upstream = upstream_table
        self.pk_indices = tuple(upstream_table.pk_indices)
        self.batch_rows = batch_rows
        self.chunk_capacity = chunk_capacity
        self.progress_table = progress_table
        self.on_progress = on_progress
        self.cursor: Optional[bytes] = None
        self.cursor_row: Optional[tuple] = None   # pk values at the cursor
        self.done = False
        self.rows_done = 0
        self._pk_is_string = tuple(
            self.schema[i].type.is_string for i in self.pk_indices)
        if progress_table is not None:
            rows = list(progress_table.scan_all())
            if rows:
                import json
                _id, done, cur_hex, cur_pks, rows_done = rows[0]
                self.done = bool(done)
                self.rows_done = int(rows_done)
                cur = VARCHAR.to_python(cur_hex)
                self.cursor = bytes.fromhex(cur) if cur else None
                pks = VARCHAR.to_python(cur_pks)
                if pks:
                    # persisted as LOGICAL values (dictionary ids are not
                    # process-stable); re-encode into this process
                    pk_types = [self.schema[i].type for i in self.pk_indices]
                    self.cursor_row = tuple(
                        t.to_physical(v)
                        for t, v in zip(pk_types, json.loads(pks)))

    # -- delta filtering -------------------------------------------------------

    def _filter_delta(self, chunk: StreamChunk) -> StreamChunk:
        """Visibility-mask rows whose pk is beyond the backfill cursor —
        their current value will be read by a later snapshot batch
        (backfill.rs "mark chunk" filtering)."""
        if self.cursor_row is None:
            return chunk.with_vis(jnp.zeros_like(chunk.vis))
        le = jnp.zeros_like(chunk.vis)
        eq = jnp.ones_like(chunk.vis)
        for pos, i in enumerate(self.pk_indices):
            col = chunk.columns[i]
            d = col.data
            cur = self.cursor_row[pos]
            if self._pk_is_string[pos]:
                from ..common.types import GLOBAL_STRING_DICT
                t = GLOBAL_STRING_DICT.device_ranks()
                n = t.shape[0]
                d = t[jnp.clip(d.astype(jnp.int32), 0, n - 1)]
                cur = int(t[min(int(cur), n - 1)])
            le = le | (eq & (d < cur))
            eq = eq & (d == cur)
        mask = le | eq
        return chunk.with_vis(chunk.vis & mask)

    # -- snapshot batches ------------------------------------------------------

    def _emit_batch(self):
        rows, last = self.upstream.scan_after(self.cursor, self.batch_rows)
        if rows:
            self.cursor = last
            self.cursor_row = tuple(
                rows[-1][i] for i in self.pk_indices)
            self.rows_done += len(rows)
        if len(rows) < self.batch_rows:
            self.done = True
        cap = self.chunk_capacity
        for i in range(0, len(rows), cap):
            yield physical_chunk(self.schema, rows[i:i + cap], cap)

    def _persist(self, epoch: int) -> None:
        if self.progress_table is None:
            return
        import json
        cur_hex = self.cursor.hex() if self.cursor is not None else ""
        if self.cursor_row is not None:
            pk_types = [self.schema[i].type for i in self.pk_indices]
            pks = json.dumps([
                t.to_python(v)
                for t, v in zip(pk_types, self.cursor_row)])
        else:
            pks = ""
        self.progress_table.insert(
            (0, int(self.done), VARCHAR.to_physical(cur_hex),
             VARCHAR.to_physical(pks), self.rows_done))
        self.progress_table.commit(epoch)

    @property
    def progress(self) -> dict:
        return {"rows_done": self.rows_done, "done": self.done,
                "total_estimate": len(self.upstream)}

    # -- main loop -------------------------------------------------------------

    async def execute(self):
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                if self.done:
                    yield msg
                else:
                    filtered = self._filter_delta(msg)
                    if bool(jnp.any(filtered.vis)):
                        yield filtered
            elif isinstance(msg, Barrier):
                if not self.done:
                    for out in self._emit_batch():
                        yield out
                    if self.on_progress is not None:
                        self.on_progress(self.progress)
                if msg.checkpoint:
                    self._persist(msg.epoch.curr)
                yield msg
                if msg.is_stop():
                    return
            elif isinstance(msg, Watermark):
                yield msg
