"""Per-executor streaming metrics.

Counterpart of the reference's executor counters + barrier-latency
histograms (reference: src/stream/src/executor/monitor/streaming_stats.rs:
27-88 — actor/executor row+barrier counters scraped by Prometheus). Design
constraint the reference does not have: a host sync on a tunneled TPU costs
a full RTT (~100 ms), so counters only use host-known quantities — chunk
counts, chunk capacities, batch sizes, and wall-clock time spent in barrier
handling. Row-exact cardinalities would require device syncs and are
deliberately absent from the hot path.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterator, Optional

from ..common.tracing import CAT_BARRIER, GLOBAL_TRACE, Span


@dataclasses.dataclass
class ExecutorStats:
    chunks_in: int = 0            # single chunks received
    batches_in: int = 0           # ChunkBatch messages received
    batch_chunks_in: int = 0      # chunks carried inside batches
    capacity_rows_in: int = 0     # upper bound on rows (sum of capacities)
    chunks_out: int = 0
    barriers: int = 0
    barrier_seconds: float = 0.0  # wall time inside on_barrier handling
    watermarks: int = 0

    def snapshot(self) -> dict:
        return dataclasses.asdict(self)


class _BarrierTimer:
    __slots__ = ("stats", "identity", "epoch", "_t0", "_ts")

    def __init__(self, stats: ExecutorStats, identity: Optional[str] = None,
                 epoch: Optional[int] = None):
        self.stats = stats
        self.identity = identity
        self.epoch = epoch

    def __enter__(self):
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self.stats.barriers += 1
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        self.stats.barrier_seconds += dur
        if self.identity is not None:
            # the tracing seam: every identified barrier timing doubles as
            # a per-executor span in the epoch's trace tree
            GLOBAL_TRACE.record(Span(
                f"{self.identity}.barrier", CAT_BARRIER, self._ts, dur,
                epoch=self.epoch, tid=self.identity))
        return False


def barrier_timer(stats: ExecutorStats, identity: Optional[str] = None,
                  epoch: Optional[int] = None) -> _BarrierTimer:
    """Time one barrier's handling into ``stats``; with ``identity`` (and
    ideally ``epoch``) the timing is also recorded as a tracing span."""
    return _BarrierTimer(stats, identity, epoch)


def iter_executors(root) -> Iterator:
    """Walk an executor pipeline (input / left+right / inputs edges)."""
    seen = set()
    stack = [root]
    while stack:
        ex = stack.pop()
        if id(ex) in seen:
            continue
        seen.add(id(ex))
        yield ex
        for attr in ("input", "left", "right"):
            child = getattr(ex, attr, None)
            if child is not None and hasattr(child, "execute"):
                stack.append(child)
        for child in getattr(ex, "inputs", ()) or ():
            if hasattr(child, "execute"):
                stack.append(child)


def pipeline_metrics(root) -> dict:
    """{'<Identity>#<n>': stats_dict} for every executor with stats."""
    out: dict = {}
    counts: dict = {}
    for ex in iter_executors(root):
        stats: Optional[ExecutorStats] = getattr(ex, "stats", None)
        if stats is None:
            continue
        ident = getattr(ex, "identity", type(ex).__name__)
        n = counts.get(ident, 0)
        counts[ident] = n + 1
        out[f"{ident}#{n}" if n else ident] = stats.snapshot()
    return out


class LatencyRecorder:
    """Session-level barrier latency (inject -> collected), reference's
    barrier_latency histogram. Keeps the last ``window`` samples."""

    def __init__(self, window: int = 1024):
        self.window = window
        self.samples: list[float] = []

    def record(self, seconds: float) -> None:
        self.samples.append(seconds)
        if len(self.samples) > self.window:
            del self.samples[: len(self.samples) - self.window]

    def percentile(self, q: float) -> Optional[float]:
        if not self.samples:
            return None
        s = sorted(self.samples)
        i = min(len(s) - 1, int(q / 100.0 * len(s)))
        return s[i]

    def snapshot(self) -> dict:
        return {
            "count": len(self.samples),
            "p50_ms": None if not self.samples else round(
                1e3 * self.percentile(50), 3),
            "p99_ms": None if not self.samples else round(
                1e3 * self.percentile(99), 3),
            "max_ms": None if not self.samples else round(
                1e3 * max(self.samples), 3),
        }
