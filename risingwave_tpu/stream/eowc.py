"""Watermark machinery: WatermarkFilter, Sort (emit-on-window-close), Now.

Counterparts of the reference's watermark/EOWC pipeline
(reference: src/stream/src/executor/watermark_filter.rs, executor/sort.rs +
executor/sort_buffer.rs, executor/now.rs; Watermark message semantics
executor/mod.rs:591). Watermarks are the unbounded-stream analogue of
sequence-length handling (SURVEY.md §5 long-context note): they bound how
much state an EOWC operator must keep and let it emit+clean closed windows.

  * WatermarkFilter: tracks max(event_time) on device, emits
    ``Watermark(col, max - delay)``, and drops late rows (ts < watermark).
  * SortExecutor: buffers rows on device (ops/row_set.py) and, at each
    barrier, emits rows with ts <= watermark in (ts, pk) order, then frees
    them — the EOWC sort that makes downstream appends ordered by time.
  * NowExecutor: 1-column ``now()`` changelog + watermark per barrier.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import (
    Column, DEFAULT_CHUNK_CAPACITY, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, StreamChunk, physical_chunk,
)
from ..common.types import TIMESTAMP, Field, Schema
from ..ops.row_set import rs_apply_chunk, rs_checkpoint, rs_new
from ..ops.topn import OrderSpec, topn_order
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier, Watermark


class WatermarkFilterExecutor(SingleInputExecutor):
    """``delay``: watermark lag behind the max observed event time (the
    out-of-orderness bound). Late rows (ts < current watermark) are dropped
    — insert-only semantics, so this belongs right after sources."""

    identity = "WatermarkFilter"

    def __init__(self, input: Executor, time_col: int, delay: int,
                 state_table: Optional[StateTable] = None):
        super().__init__(input)
        self.schema = input.schema
        self.time_col = time_col
        self.delay = delay
        self.state_table = state_table
        self.current_wm = jnp.asarray(jnp.iinfo(jnp.int64).min, jnp.int64)

        @jax.jit
        def _step(wm, chunk: StreamChunk):
            col = chunk.columns[self.time_col]
            ts = col.data.astype(jnp.int64)
            valid = chunk.vis & col.mask
            # filter against the watermark already ANNOUNCED downstream (a row
            # below an emitted watermark would violate the watermark contract);
            # rows of this chunk never violate the watermark they themselves
            # advance
            keep = valid & (ts >= wm)
            chunk_max = jnp.max(jnp.where(valid, ts, jnp.iinfo(jnp.int64).min))
            new_wm = jnp.maximum(wm, chunk_max - self.delay)
            return new_wm, chunk.mask_vis(keep)

        self._step = _step
        if state_table is not None:
            rows = list(state_table.scan_all())
            if rows and rows[0][1] is not None:
                self.current_wm = jnp.asarray(rows[0][1], jnp.int64)

    async def map_chunk(self, chunk: StreamChunk):
        old = self.current_wm
        self.current_wm, out = self._step(self.current_wm, chunk)
        if bool(jnp.any(out.vis)):
            yield out
        if bool(self.current_wm > old):
            yield Watermark(self.time_col, int(self.current_wm))

    async def on_barrier(self, barrier: Barrier):
        if barrier.checkpoint and self.state_table is not None:
            wm = int(self.current_wm)
            self.state_table.insert(
                (0, None if wm == jnp.iinfo(jnp.int64).min else wm))
            self.state_table.commit(barrier.epoch.curr)
        if False:
            yield


class SortExecutor(SingleInputExecutor):
    """EOWC sort: emit buffered rows in (time, pk) order once the watermark
    passes them. Input must be append-only (EOWC contract)."""

    identity = "Sort"

    def __init__(self, input: Executor, time_col: int,
                 pk_indices: Sequence[int],
                 state_table: Optional[StateTable] = None,
                 table_capacity: int = 1 << 16,
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY):
        super().__init__(input)
        self.schema = input.schema
        self.time_col = time_col
        self.pk_indices = tuple(pk_indices)
        self.capacity = table_capacity
        self.out_capacity = out_capacity
        self.state_table = state_table
        pk_types = [input.schema[i].type for i in self.pk_indices]
        col_types = [f.type for f in input.schema]
        self.rows = rs_new(pk_types, col_types, table_capacity)
        self.order = (OrderSpec(time_col),) + tuple(
            OrderSpec(i) for i in self.pk_indices if i != time_col)
        self._pending_wm: Optional[int] = None

        self._apply = jax.jit(
            lambda st, ch: rs_apply_chunk(st, ch, self.pk_indices))

        @jax.jit
        def _close(rows, wm):
            col = rows.cols[self.time_col]
            ts = col.data.astype(jnp.int64)
            ripe = rows.live & col.mask & (ts <= wm)
            perm = topn_order(rows, jnp.zeros(self.capacity, jnp.int32),
                              self.order)
            ripe_sorted = ripe[perm]
            rank_sorted = jnp.cumsum(ripe_sorted) - ripe_sorted.astype(jnp.int64)
            # rank per slot (capacity sentinel for non-ripe)
            rank = jnp.zeros(self.capacity, jnp.int64).at[perm].set(rank_sorted)
            return ripe, rank, jnp.sum(ripe)

        @jax.jit
        def _gather(rows, ripe, rank, lo):
            C = self.out_capacity
            in_win = ripe & (rank >= lo) & (rank < lo + C)
            pos = jnp.where(in_win, rank - lo, C).astype(jnp.int32)
            ops = jnp.zeros(C, jnp.int8)
            vis = jnp.zeros(C, jnp.bool_).at[pos].set(True, mode="drop")
            cols = tuple(
                Column(
                    jnp.zeros(C, c.data.dtype).at[pos].set(c.data, mode="drop"),
                    jnp.zeros(C, jnp.bool_).at[pos].set(c.mask, mode="drop"),
                )
                for c in rows.cols
            )
            return StreamChunk(ops, vis, cols)

        @jax.jit
        def _free(rows, ripe):
            return rows.replace(live=rows.live & ~ripe,
                                ckpt_dirty=rows.ckpt_dirty | ripe)

        self._close, self._gather_ripe, self._free = _close, _gather, _free
        if state_table is not None:
            self._load_from_state_table()

    async def map_chunk(self, chunk: StreamChunk):
        self.rows, _, _ = self._apply(self.rows, chunk)
        if False:
            yield

    async def on_watermark(self, watermark: Watermark):
        if watermark.col_idx == self.time_col:
            self._pending_wm = watermark.value
        yield watermark

    async def on_barrier(self, barrier: Barrier):
        if bool(self.rows.overflow):
            raise RuntimeError(
                f"{self.identity}: sort buffer overflow (capacity "
                f"{self.capacity})")
        if self._pending_wm is not None:
            wm = jnp.asarray(self._pending_wm, jnp.int64)
            self._pending_wm = None
            ripe, rank, n_ripe = self._close(self.rows, wm)
            lo, n = 0, int(n_ripe)
            while lo < n:
                chunk = self._gather_ripe(self.rows, ripe, rank, jnp.int64(lo))
                yield chunk
                lo += self.out_capacity
            self.rows = self._free(self.rows, ripe)
        if barrier.checkpoint and self.state_table is not None:
            self.rows = rs_checkpoint(self.rows, self.state_table,
                                      barrier.epoch.curr)

    def _load_from_state_table(self) -> None:
        rows = list(self.state_table.scan_all())
        bs = 1024
        for i in range(0, len(rows), bs):
            chunk = physical_chunk(self.schema, rows[i:i + bs], bs)
            self.rows, _, _ = self._apply(self.rows, chunk)
        self.rows = self.rows.replace(
            ckpt_dirty=jnp.zeros_like(self.rows.ckpt_dirty))


class NowExecutor(Executor):
    """Emits the wall-clock of each barrier as a 1-row changelog + watermark
    (reference: executor/now.rs — the ``now()`` lower bound for temporal
    filters). ``clock``: epoch -> microseconds; default derives a synthetic
    monotone clock from the epoch number so tests are deterministic."""

    identity = "Now"

    def __init__(self, barrier_source: Executor,
                 clock: Optional[Callable[[int], int]] = None):
        self._barriers = barrier_source
        self.schema = Schema.of(("now", TIMESTAMP))
        self._clock = clock or (lambda epoch: epoch * 1_000_000)
        self._last: Optional[int] = None

    async def execute(self):
        async for msg in self._barriers.execute():
            if not isinstance(msg, Barrier):
                continue
            now = self._clock(msg.epoch.curr)
            if self._last is None:
                chunk = physical_chunk(self.schema, [(now,)], 2)
            else:
                chunk = physical_chunk(self.schema, [(self._last,), (now,)], 2)
                chunk = chunk.replace(ops=jnp.array(
                    [OP_UPDATE_DELETE, OP_UPDATE_INSERT], jnp.int8))
            self._last = now
            yield chunk
            yield Watermark(0, now)
            yield msg
            if msg.is_stop():
                return
