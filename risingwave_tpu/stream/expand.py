"""ExpandExecutor — row expansion for grouping sets / distinct aggregates.

Counterpart of the reference's ExpandExecutor
(reference: src/stream/src/executor/expand.rs; used by the distinct-agg and
grouping-sets rewrites in the optimizer). Each input row is replicated once
per column subset, with columns outside the subset nulled and a ``flag``
column identifying the subset — emitted as one statically-shaped chunk per
subset, same capacity as the input.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import INT64, Field, Schema
from .executor import Executor, SingleInputExecutor


class ExpandExecutor(SingleInputExecutor):
    identity = "Expand"

    def __init__(self, input: Executor, column_subsets: Sequence[Sequence[int]]):
        super().__init__(input)
        self.subsets = [tuple(s) for s in column_subsets]
        self.schema = Schema(tuple(input.schema) + (Field("flag", INT64),))

        @jax.jit
        def _expand(chunk: StreamChunk):
            outs = []
            for flag, subset in enumerate(self.subsets):
                cols = []
                for ci, c in enumerate(chunk.columns):
                    if ci in subset:
                        cols.append(c)
                    else:
                        cols.append(Column(c.data, jnp.zeros_like(c.mask)))
                cols.append(Column(
                    jnp.full(chunk.capacity, flag, jnp.int64),
                    jnp.ones(chunk.capacity, jnp.bool_)))
                outs.append(chunk.with_columns(cols))
            return tuple(outs)

        self._expand = _expand

    async def map_chunk(self, chunk: StreamChunk):
        for out in self._expand(chunk):
            yield out
