"""The heterogeneous tick compiler: UNEQUAL jobs → minimal dispatches.

Host-side scheduler over ops/fused_hetero.py. The co-scheduler
(stream/coschedule.py) batches jobs whose traces are IDENTICAL; every
job that misses a signature still pays its own dispatch, so a tenant
mix of 200 small dissimilar MVs ticks in ~200 dispatches. The tick
compiler takes the LIVE JOB SET and emits a minimal dispatch schedule
in two tiers:

1. **Shape-class supergroups** — ``skeletonize_exprs`` lifts numeric
   literals out of each job's projection (window widths, scale
   factors…) into parameter holes; jobs whose skeletons, agg calls and
   group keys then coincide share a ``shape_class`` (the coarsened
   ``agg_signature`` — capacities and literal VALUES excluded). Each
   member's state is padded to the class-max table capacity
   (``repad_agg_state``) and the whole bucket runs as ONE vmapped
   dispatch (``build_padded_group_epoch``) with per-job literals
   riding down the job axis as data.

2. **Mega-epochs** — jobs that share no skeleton are concatenated
   sequentially INSIDE one compiled dispatch (``build_mega_epoch``):
   one launch, one packed multi-job fetch, regardless of how unlike
   the bodies are.

The schedule is recompiled only on DDL: CREATE/DROP marks it dirty and
``ensure_compiled`` rebuilds lazily at the next tick (so creating 200
MVs triggers ONE compile, not 200 restacks). Dissolving a schedule
writes every job's state/cursor back into its job record and retires
each group's epochs-run counters (``take_retired``) so the live
``per_epoch`` dispatch-ratio invariant stays 1.0 across recompiles —
the same ledger discipline Session applies to dropped co-scheduled
groups.

Both group kinds expose the CoGroup tick API (``run_epoch`` /
``begin_flush`` / ``finish_flush`` / ``state_of`` / ``set_states``) so
frontend/session.py drives them with the same pipeline-depth deferral
and checkpoint write-back as equal groups, and each job keeps its own
HashAggExecutor-backed flush engine — checkpoint/recovery is unchanged
(``_checkpoint_to_state_table`` is capacity-agnostic, so padded states
persist through the job's own engine).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common.fetch import PendingFlush, async_fetch
from ..expr.expr import FunctionCall, InputRef, Literal
from ..ops.fused_hetero import (
    build_mega_agg_finish, build_mega_agg_probe, build_mega_epoch,
    build_padded_group_epoch, mega_agg_gathers, padded_agg_probe,
    repad_agg_state,
)
from ..ops.fused_multi import (
    gather_job_flush_chunk, index_state, multi_agg_finish, stack_states,
)
from .coschedule import FusedJobSpec, _expr_sig

#: dispatch_count / profiler identities of the two compiled surfaces
PADDED_EPOCH_FN = "build_padded_group_epoch.<locals>.padded_epoch"
MEGA_EPOCH_FN = "build_mega_epoch.<locals>.mega_epoch"


# ---------------------------------------------------------------------------
# skeletonization: literals → parameter holes
# ---------------------------------------------------------------------------


def skeletonize_exprs(exprs, n_source_cols: int):
    """Lift numeric literals out of projection exprs: ``(skel_exprs,
    hole_types, params)``. Hole ``h`` becomes ``InputRef(n_source_cols
    + h)`` — the epoch body appends one broadcast parameter column per
    hole, so evaluation is bit-identical to the inlined literal.
    ``params`` holds each hole's PHYSICAL value (``type.to_physical``),
    ready to ride as device data.

    Conservative on purpose: only plain int/float literals lift (bools,
    strings, decimals, NULLs stay inline — part of the skeleton), and
    only InputRef/Literal/FunctionCall nodes are walked; any other node
    keeps its subtree verbatim, which merely coarsens less (two jobs
    differing inside an unwalked subtree land in different classes and
    fall to the mega tier — never wrong, only less fused)."""
    hole_types: list = []
    params: list = []

    def walk(e):
        if isinstance(e, Literal):
            v = e.value
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return e
            slot = len(params)
            hole_types.append(e.type)
            params.append(e.type.to_physical(v))
            return InputRef(n_source_cols + slot, e.type)
        if isinstance(e, FunctionCall):
            return FunctionCall(e.name, tuple(walk(a) for a in e.args),
                                e.type)
        return e

    skel = tuple(walk(e) for e in exprs)
    return skel, tuple(hole_types), tuple(params)


def shape_class(core, skel_exprs, hole_types, rows_per_chunk: int,
                source_sig: tuple) -> tuple:
    """The coarsened grouping key: ``agg_signature`` minus table/output
    capacities (padded to class max) minus literal values (parameter
    data), plus the hole dtype row (two skeletons only share a class
    when their holes line up positionally and typewise)."""
    return ("hetero_agg", source_sig, int(rows_per_chunk),
            tuple(_expr_sig(e) for e in skel_exprs),
            tuple(repr(t) for t in hole_types),
            tuple(repr(t) for t in core.key_types),
            tuple(core.group_keys), repr(tuple(core.agg_calls)))


@dataclasses.dataclass
class HeteroJob:
    """One compiled-schedule member: spec + skeleton + live cursors.
    ``state`` is authoritative only while the job is UNGROUPED (fresh
    add, or between dissolve and recompile); once scheduled the group
    holds it, and dissolve writes it back here."""

    spec: FusedJobSpec
    skel_exprs: tuple
    hole_types: tuple
    params: tuple              # physical hole values (host scalars)
    shape_class: tuple
    state: object
    start: int
    batch_no: int

    @property
    def state_capacity(self) -> int:
        return self.state.dirty.shape[0]


# ---------------------------------------------------------------------------
# compiled dispatch groups
# ---------------------------------------------------------------------------


class PaddedHeteroGroup:
    """Tier 1: one shape class, one vmapped dispatch. Mirrors
    stream/coschedule.CoGroup's tick API; per-job literals ride as
    stacked parameter data and every member's state lives padded at
    the class-max capacity."""

    kind = "padded"
    epoch_qualname = PADDED_EPOCH_FN

    def __init__(self, named_jobs: list, donate: bool = True):
        self.names = [n for n, _ in named_jobs]
        jobs = [j for _, j in named_jobs]
        base = jobs[0]
        # class capacity: max over declared cores AND current states —
        # a member padded by an earlier schedule never shrinks (repad
        # grows only; per-key values are capacity-invariant)
        cap = max(max(j.spec.core.capacity, j.state_capacity)
                  for j in jobs)
        out_cap = max(j.spec.core.out_capacity for j in jobs)
        padded = []
        core = None
        for j in jobs:
            jcore = j.spec.core
            if j.state_capacity != jcore.capacity:
                # state already padded by a previous schedule: repad
                # from its CURRENT capacity, not the declared one
                jcore = type(jcore)(jcore.key_types, jcore.group_keys,
                                    jcore.agg_calls, j.state_capacity,
                                    jcore.out_capacity)
            core, st = repad_agg_state(jcore, j.state, cap,
                                       out_capacity=out_cap)
            padded.append(st)
        self.core = core
        self.rows_per_chunk = base.spec.rows_per_chunk
        self.stacked = stack_states(padded)
        self.params = tuple(
            jnp.asarray(np.array([j.params[h] for j in jobs],
                                 dtype=t.np_dtype))
            for h, t in enumerate(base.hole_types))
        self.starts = [j.start for j in jobs]
        self.batch_nos = [j.batch_no for j in jobs]
        self.seeds = [j.spec.seed for j in jobs]
        self.epochs_run = 0
        self.flush_weights = dict.fromkeys(self.names, 0)
        self.pending: Optional[PendingFlush] = None
        self._base_keys = None
        self._epoch = build_padded_group_epoch(
            base.spec.chunk_fn, base.skel_exprs, self.core,
            self.rows_per_chunk, donate)
        self._probe = padded_agg_probe(self.core)
        self._finish = multi_agg_finish(self.core)
        self._gather = gather_job_flush_chunk(self.core)

    @property
    def n_jobs(self) -> int:
        return len(self.names)

    def _keys(self):
        if self._base_keys is None:
            self._base_keys = jnp.stack(
                [jax.random.PRNGKey(s) for s in self.seeds])
        return self._base_keys

    def state_of(self, name: str):
        return index_state(self.stacked, self.names.index(name))

    def set_states(self, states: list) -> None:
        assert len(states) == self.n_jobs
        self.stacked = stack_states(states)

    def run_epoch(self, k: int):
        starts = jnp.asarray(self.starts, jnp.int64)
        nos = jnp.asarray(self.batch_nos, jnp.int64)
        self.stacked = self._epoch(self.stacked, starts, self._keys(),
                                   nos, self.params, k)
        for j in range(self.n_jobs):
            self.starts[j] += k * self.rows_per_chunk
            self.batch_nos[j] += 1
        self.epochs_run += 1

    def begin_flush(self) -> PendingFlush:
        assert self.pending is None, "flush already in flight"
        packed, ranks = self._probe(self.stacked)
        self.pending = PendingFlush(
            self.stacked, packed, ranks,
            async_fetch(packed, dispatch=self._probe.__qualname__))
        self.stacked = self._finish(self.stacked)
        return self.pending

    def finish_flush(self) -> dict:
        p = self.pending
        if p is None:
            p = self.begin_flush()
        self.pending = None
        packed_h = np.asarray(p.fetch.result())
        out: dict = {}
        for j, name in enumerate(self.names):
            n_dirty, overflow = int(packed_h[j, 0]), int(packed_h[j, 1])
            if overflow:
                raise RuntimeError(
                    f"tick-compiled job {name!r}: padded group table "
                    f"overflow (class capacity {self.core.capacity}); "
                    "increase agg_table_capacity")
            self.flush_weights[name] += n_dirty
            chunks = []
            lo = 0
            while lo < n_dirty:
                chunks.append(self._gather(p.stacked, p.ranks,
                                           jnp.int64(j), jnp.int64(lo)))
                lo += self.core.groups_per_chunk
            out[name] = chunks
        return out

    def flush(self) -> dict:
        if self.pending is None:
            self.begin_flush()
        return self.finish_flush()


class MegaGroup:
    """Tier 2: heterogeneous epoch bodies concatenated in ONE compiled
    dispatch. States stay a per-job tuple (no shape relation between
    members); the barrier is one probe dispatch / one packed [J, 3]
    fetch, with per-job gathers (per-job data, as everywhere)."""

    kind = "mega"
    epoch_qualname = MEGA_EPOCH_FN

    def __init__(self, named_jobs: list, donate: bool = True):
        self.names = [n for n, _ in named_jobs]
        jobs = [j for _, j in named_jobs]
        self.cores = [j.spec.core for j in jobs]
        self.rows_per_chunks = [j.spec.rows_per_chunk for j in jobs]
        self.states = tuple(j.state for j in jobs)
        self.starts = [j.start for j in jobs]
        self.batch_nos = [j.batch_no for j in jobs]
        self.seeds = [j.spec.seed for j in jobs]
        self.epochs_run = 0
        self.flush_weights = dict.fromkeys(self.names, 0)
        self.pending: Optional[PendingFlush] = None
        self._base_keys = None
        self._epoch = build_mega_epoch([j.spec for j in jobs], donate)
        self._probe = build_mega_agg_probe(self.cores)
        self._finish = build_mega_agg_finish(self.cores)
        self._gathers = mega_agg_gathers(self.cores)

    @property
    def n_jobs(self) -> int:
        return len(self.names)

    def _keys(self):
        if self._base_keys is None:
            self._base_keys = jnp.stack(
                [jax.random.PRNGKey(s) for s in self.seeds])
        return self._base_keys

    def state_of(self, name: str):
        return self.states[self.names.index(name)]

    def set_states(self, states: list) -> None:
        assert len(states) == self.n_jobs
        self.states = tuple(states)

    def run_epoch(self, k: int):
        starts = jnp.asarray(self.starts, jnp.int64)
        nos = jnp.asarray(self.batch_nos, jnp.int64)
        self.states = self._epoch(self.states, starts, self._keys(),
                                  nos, k)
        for j in range(self.n_jobs):
            self.starts[j] += k * self.rows_per_chunks[j]
            self.batch_nos[j] += 1
        self.epochs_run += 1

    def begin_flush(self) -> PendingFlush:
        assert self.pending is None, "flush already in flight"
        packed, ranks = self._probe(self.states)
        self.pending = PendingFlush(
            self.states, packed, ranks,
            async_fetch(packed, dispatch=self._probe.__qualname__))
        self.states = self._finish(self.states)
        return self.pending

    def finish_flush(self) -> dict:
        p = self.pending
        if p is None:
            p = self.begin_flush()
        self.pending = None
        packed_h = np.asarray(p.fetch.result())
        out: dict = {}
        for j, name in enumerate(self.names):
            n_dirty, overflow = int(packed_h[j, 0]), int(packed_h[j, 1])
            if overflow:
                raise RuntimeError(
                    f"tick-compiled job {name!r}: agg table overflow "
                    f"(capacity {self.cores[j].capacity}); increase "
                    "agg_table_capacity")
            self.flush_weights[name] += n_dirty
            chunks = []
            lo = 0
            while lo < n_dirty:
                chunks.append(self._gathers[j](p.stacked[j], p.ranks[j],
                                               jnp.int64(lo)))
                lo += self.cores[j].groups_per_chunk
            out[name] = chunks
        return out

    def flush(self) -> dict:
        if self.pending is None:
            self.begin_flush()
        return self.finish_flush()


# ---------------------------------------------------------------------------
# the compiler
# ---------------------------------------------------------------------------


class TickCompiler:
    """Live job set → minimal dispatch schedule (one per Session).

    DDL only marks the schedule dirty; ``ensure_compiled`` (called at
    the first subsequent tick) buckets jobs by ``shape_class`` —
    buckets of ≥ 2 become padded supergroups, the remainder packs into
    mega-epochs of at most ``mega_max_jobs`` in insertion order — so a
    burst of 200 CREATEs costs ONE schedule compile."""

    def __init__(self, donate: bool = True, mega_max_jobs: int = 32):
        self.jobs: dict[str, HeteroJob] = {}
        self.groups: list = []
        self.job_group: dict[str, object] = {}
        self.dirty = False
        self.donate = donate
        self.mega_max_jobs = int(mega_max_jobs)
        self.schedule_compiles = 0
        self._retired: dict[str, int] = {}

    # -- DDL ------------------------------------------------------------------

    def add(self, name: str, spec: FusedJobSpec, state,
            n_source_cols: int, start: int = 0, batch_no: int = 0
            ) -> HeteroJob:
        if name in self.jobs:
            raise ValueError(f"job {name!r} already tick-compiled")
        self._dissolve()
        skel, hole_types, params = skeletonize_exprs(
            spec.exprs, n_source_cols)
        sc = shape_class(spec.core, skel, hole_types,
                         spec.rows_per_chunk, spec.signature[1])
        job = HeteroJob(spec, skel, hole_types, params, sc, state,
                        int(start), int(batch_no))
        self.jobs[name] = job
        return job

    def remove(self, name: str):
        """Drop a job; returns its final solo-shaped state (possibly
        padded — per-key values are capacity-invariant) or None."""
        if name not in self.jobs:
            return None
        self._dissolve()
        return self.jobs.pop(name).state

    def _dissolve(self) -> None:
        """Tear the compiled schedule down to job records: write every
        group's states/cursors back and retire its epochs-run under its
        dispatch qualname — the ledger Session drains via
        ``take_retired`` to keep the per-epoch ratio exactly 1.0 across
        recompiles (ISSUE 19 satellite: DROP + re-CREATE)."""
        self.dirty = True
        if not self.groups:
            return
        for g in self.groups:
            assert g.pending is None, \
                "schedule change with a flush in flight (drain first)"
            if g.epochs_run:
                qn = g.epoch_qualname
                self._retired[qn] = self._retired.get(qn, 0) \
                    + g.epochs_run
            for j, name in enumerate(g.names):
                job = self.jobs[name]
                job.state = g.state_of(name)
                job.start = g.starts[j]
                job.batch_no = g.batch_nos[j]
        self.groups = []
        self.job_group = {}

    def take_retired(self) -> dict:
        """Drain retired epoch counts (qualname → epochs): the caller
        folds them into its ``_dispatch_epochs_retired`` ledger."""
        out, self._retired = self._retired, {}
        return out

    # -- scheduling -----------------------------------------------------------

    def ensure_compiled(self) -> None:
        if not self.dirty:
            return
        buckets: dict[tuple, list] = {}
        for name, job in self.jobs.items():
            buckets.setdefault(job.shape_class, []).append(name)
        groups: list = []
        singles: list = []
        for names in buckets.values():
            if len(names) >= 2:
                groups.append(PaddedHeteroGroup(
                    [(n, self.jobs[n]) for n in names],
                    donate=self.donate))
            else:
                singles.extend(names)
        for i in range(0, len(singles), self.mega_max_jobs):
            groups.append(MegaGroup(
                [(n, self.jobs[n]) for n in
                 singles[i:i + self.mega_max_jobs]],
                donate=self.donate))
        self.groups = groups
        self.job_group = {n: g for g in groups for n in g.names}
        self.dirty = False
        if self.jobs:
            self.schedule_compiles += 1

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "jobs": len(self.jobs),
            "dispatches_per_tick": len(self.groups),
            "schedule_compiles": self.schedule_compiles,
            "dirty": self.dirty,
            "groups": [
                {"kind": g.kind, "jobs": list(g.names),
                 "epochs_run": g.epochs_run,
                 "capacity": (g.core.capacity if g.kind == "padded"
                              else [c.capacity for c in g.cores])}
                for g in self.groups
            ],
        }

    def attribution(self) -> dict:
        """Per-job cost weights inside fused dispatches: cumulative
        flushed-group counts (packed slot 0) per job, grouped by
        dispatch qualname. common/profiling.per_job_attribution splits
        a qualname's measured seconds over these weights."""
        out: dict = {}
        for g in self.groups:
            out.setdefault(g.epoch_qualname, {}).update(g.flush_weights)
        return out
