"""DynamicFilterExecutor — filter LHS rows by a moving 1-row RHS bound.

Counterpart of the reference's DynamicFilterExecutor
(reference: src/stream/src/executor/dynamic_filter.rs:46-64, apply_batch :94,
loop :256): the pattern behind ``WHERE v > (SELECT max(...) ...)``. The LHS
row set lives on device (ops/row_set.py); the RHS is a single scalar fed by
a 1-row aggregate stream. When the bound moves, the rows whose predicate
outcome flips are emitted retroactively as Inserts/Deletes — here that is
one vectorized membership diff at each barrier instead of the reference's
range scan between the old and new bound (a sort-free full-compare is the
natural vector-machine form; the row set is already resident in HBM).

Barrier alignment across the two inputs follows the same combinator as the
join. Within an epoch the RHS update is applied *at the barrier*, so chunk
emission is consistent with the epoch's closing bound on both sides — this
matches the reference, which buffers the RHS update and applies it on
barrier (dynamic_filter.rs loop :256).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..common.chunk import (
    DEFAULT_CHUNK_CAPACITY, OP_INSERT, OP_UPDATE_INSERT, StreamChunk,
    chunk_to_rows, physical_chunk,
)
from ..ops.row_set import (
    rs_apply_chunk, rs_changed, rs_checkpoint, rs_finish_flush,
    rs_gather_delta, rs_new,
)

from ..storage.state_table import StateTable
from .barrier_align import barrier_align
from .executor import Executor
from .message import Barrier

_CMP_FNS = {
    "greater_than": lambda v, b: v > b,
    "greater_than_or_equal": lambda v, b: v >= b,
    "less_than": lambda v, b: v < b,
    "less_than_or_equal": lambda v, b: v <= b,
}


class DynamicFilterExecutor(Executor):
    """``key_col``: LHS column compared against the RHS scalar (column 0 of
    the RHS input). ``cmp``: one of greater_than / greater_than_or_equal /
    less_than / less_than_or_equal. ``pk_indices``: LHS stream pk."""

    identity = "DynamicFilter"

    def __init__(
        self,
        left: Executor,
        right: Executor,
        key_col: int,
        cmp: str,
        pk_indices,
        state_table: Optional[StateTable] = None,
        bound_table: Optional[StateTable] = None,
        table_capacity: int = 1 << 16,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
    ):
        if cmp not in _CMP_FNS:
            raise ValueError(f"unsupported comparator {cmp}")
        if state_table is not None and bound_table is None:
            raise ValueError(
                "state_table requires bound_table: recovery must restore the "
                "committed bound to rebuild the emitted snapshot")
        self.left, self.right = left, right
        self.schema = left.schema
        self.key_col = key_col
        self.cmp = cmp
        self.pk_indices = tuple(pk_indices)
        self.capacity = table_capacity
        self.out_capacity = out_capacity
        self.state_table = state_table
        self.bound_table = bound_table

        pk_types = [left.schema[i].type for i in self.pk_indices]
        col_types = [f.type for f in left.schema]
        self.rows = rs_new(pk_types, col_types, table_capacity)
        # committed bound: (value, valid). Invalid (no RHS row yet / NULL)
        # means nothing passes — comparison with NULL is unknown.
        key_dtype = left.schema[key_col].type.dtype
        self.bound = jnp.zeros((), key_dtype)
        self.bound_valid = jnp.zeros((), jnp.bool_)
        self._staged_bound: tuple = ()  # () = no update; (v,) = set to v (may be None)

        # VARCHAR bounds compare by dictionary rank, never raw id
        self._is_string_key = left.schema[key_col].type.is_string
        self._apply = jax.jit(
            lambda st, ch: rs_apply_chunk(st, ch, self.pk_indices))
        self._compute_flush = jax.jit(self._compute_flush_impl)
        self._gather = jax.jit(rs_gather_delta, static_argnames=("out_capacity",))
        self._finish = jax.jit(rs_finish_flush)
        if state_table is not None:
            self._load_from_state_table()

    def _compute_flush_impl(self, rows, bound, bound_valid, str_ranks=None):
        col = rows.cols[self.key_col]
        data, b = col.data, bound
        if self._is_string_key:
            n = str_ranks.shape[0]
            data = str_ranks[jnp.clip(data.astype(jnp.int32), 0, n - 1)]
            b = str_ranks[jnp.clip(bound.astype(jnp.int32), 0, n - 1)]
        passes = _CMP_FNS[self.cmp](data, b)
        in_set = rows.live & col.mask & passes & bound_valid
        changed = rs_changed(rows, in_set)
        return in_set, changed, jnp.sum(changed)

    async def execute(self):
        async for ev in barrier_align(self.left, self.right):
            kind = ev[0]
            if kind == "chunk":
                _, side, chunk = ev
                if side == "left":
                    self.rows, _, _ = self._apply(self.rows, chunk)
                else:
                    # RHS is a 1-row changelog; the last visible insert wins.
                    # Tiny by construction (a global agg output) — host read.
                    for op, row in chunk_to_rows(
                            chunk, self.right.schema, with_ops=True,
                            physical=True):
                        if op in (OP_INSERT, OP_UPDATE_INSERT):
                            self._staged_bound = (row[0],)  # None = NULL bound
                        else:
                            # bound row deleted with no replacement (yet):
                            # bound becomes invalid — nothing passes until a
                            # new RHS row arrives (a following U+ in the same
                            # chunk overwrites this)
                            self._staged_bound = (None,)
            elif kind == "barrier":
                barrier = ev[1]
                for out in self._flush(barrier):
                    yield out
                yield barrier
                if barrier.is_stop():
                    return
            elif kind == "watermark":
                _, side, wm = ev
                if side == "left":
                    yield wm

    def _flush(self, barrier: Barrier):
        if bool(self.rows.overflow):
            raise RuntimeError(
                f"{self.identity}: row table overflow (capacity "
                f"{self.capacity}); increase table_capacity")
        if self._staged_bound:
            (v,) = self._staged_bound
            if v is None:
                self.bound_valid = jnp.zeros((), jnp.bool_)
            else:
                self.bound = jnp.asarray(v, self.bound.dtype)
                self.bound_valid = jnp.ones((), jnp.bool_)
            self._staged_bound = ()
        in_set, changed, n_changed = self._compute_flush(
            self.rows, self.bound, self.bound_valid, self._cur_ranks())
        lo, n = 0, int(n_changed)
        while lo < n:
            chunk = self._gather(self.rows, in_set, changed, jnp.int64(lo),
                                 out_capacity=self.out_capacity)
            if bool(jnp.any(chunk.vis)):
                yield chunk
            lo += self.out_capacity // 2
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint(barrier.epoch.curr)
        self.rows = self._finish(self.rows, in_set)

    # -- persistence ----------------------------------------------------------
    # Durable state = the LHS rows plus the committed bound in a 1-row side
    # table (schema: id, value; the reference keeps the RHS in its own state
    # table the same way, dynamic_filter.rs right_table).

    def _checkpoint(self, epoch: int) -> None:
        self.rows = rs_checkpoint(self.rows, self.state_table, epoch)
        if self.bound_table is not None:
            v = self.bound.item() if bool(self.bound_valid) else None
            self.bound_table.insert((0, v))
            self.bound_table.commit(epoch)

    def _load_from_state_table(self) -> None:
        rows = list(self.state_table.scan_all())
        bs = 1024
        for i in range(0, len(rows), bs):
            chunk = physical_chunk(self.schema, rows[i:i + bs], bs)
            self.rows, _, _ = self._apply(self.rows, chunk)
        if self.bound_table is not None:
            brows = list(self.bound_table.scan_all())
            if brows and brows[0][1] is not None:
                self.bound = jnp.asarray(brows[0][1], self.bound.dtype)
                self.bound_valid = jnp.ones((), jnp.bool_)
        # rebuild the emitted snapshot at the recovered bound so the first
        # post-recovery flush emits only genuine deltas (downstream restored
        # from the same checkpoint and already holds the old passing set)
        in_set, _, _ = self._compute_flush(self.rows, self.bound,
                                           self.bound_valid,
                                           self._cur_ranks())
        self.rows = self._finish(self.rows, in_set).replace(
            ckpt_dirty=jnp.zeros_like(self.rows.ckpt_dirty))

    def _cur_ranks(self):
        if not self._is_string_key:
            return None
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks()
