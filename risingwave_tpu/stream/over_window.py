"""OverWindow executors: window functions over partitions.

Counterparts of the reference's OverWindowExecutor (general, retractable)
and EowcOverWindowExecutor (append-only, emit-on-window-close)
(reference: src/stream/src/executor/over_window/general.rs,
over_window/eowc.rs, delta_btree_map.rs). Supported functions:
row_number / rank / dense_rank, lag(k) / lead(k) (general only), and the
running aggregates sum/count/min/max/avg with the PG default frame
(RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW — peers included; the
whole partition when there is no ORDER BY).

Design notes (TPU-first framing): window maintenance is control-flow-heavy
and output-sparse — the wrong shape for the MXU — so like the reference it
runs at the host tier over the partition cache, recomputing only *dirty*
partitions per barrier and emitting changed rows as retraction pairs. The
device path stays upstream (joins/aggs); chunks leave this operator as
ordinary device chunks.

* ``OverWindowExecutor`` — keeps the input rows per partition, recomputes
  dirty partitions at each barrier, and diffs against the previously
  emitted output (delete / insert / update pairs).
* ``EowcOverWindowExecutor`` — expects watermark-sorted append-only input
  (SortExecutor upstream, the reference's SortBuffer): rows flow through
  per-partition *running accumulators* and are emitted exactly once, when
  their peer group closes; O(1) state per partition + the open peer group.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

from ..common.chunk import (
    DEFAULT_CHUNK_CAPACITY, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, StreamChunk, chunk_to_rows, make_chunk,
)
from ..common.types import DataType, Field, INT64, Schema, TypeKind
from ..ops.topn import OrderSpec
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier, Watermark

AGG_WINDOW_KINDS = {"sum", "count", "min", "max", "avg"}
RANK_KINDS = {"row_number", "rank", "dense_rank"}


@dataclasses.dataclass(frozen=True)
class WindowCall:
    """One window function over the shared (partition_by, order_by) frame.
    ``arg`` indexes the operator's input schema (-1 = none)."""

    kind: str
    output_type: DataType
    arg: int = -1
    offset: int = 1                    # lag/lead distance
    partition_by: tuple = ()           # input col indices
    order_by: tuple = ()               # OrderSpec over input cols


class _RevStr(str):
    """A str comparing in REVERSE lexicographic order — lets DESC string
    order keys live inside ordinary ascending-sorted tuples."""

    __slots__ = ()

    def __lt__(self, o):
        return str.__gt__(self, o)

    def __le__(self, o):
        return str.__ge__(self, o)

    def __gt__(self, o):
        return str.__lt__(self, o)

    def __ge__(self, o):
        return str.__le__(self, o)


def _order_key(row, order_by: Sequence[OrderSpec]):
    """Sortable key implementing desc + nulls placement per spec. VARCHAR
    order columns compare by STRING CONTENT, never raw id — and never by
    dictionary *rank* either: ranks renumber as new strings intern, so a
    rank baked into a stored sort key goes stale (the incremental
    executor keeps keys across barriers)."""
    key = []
    for spec in order_by:
        v = row[spec.col] if spec.col < len(row) else None
        null_rank = 1 if spec.nulls_last else -1
        if v is None:
            key.append((null_rank, 0))
        elif spec.is_string:
            from ..common.types import GLOBAL_STRING_DICT
            s = GLOBAL_STRING_DICT.lookup(int(v))
            key.append((0, _RevStr(s) if spec.desc else s))
        else:
            key.append((0, -v if spec.desc else v))
    return tuple(key)


def _sort_partition(rows: list, order_by, pk_indices) -> list:
    return sorted(rows, key=lambda r: (
        _order_key(r, order_by), tuple(r[i] for i in pk_indices)))


def _agg_step(kind: str, acc, v):
    if v is None:
        return acc
    cnt, s, mn, mx = acc
    return (cnt + 1, (s or 0) + v,
            v if mn is None else min(mn, v),
            v if mx is None else max(mx, v))


def _agg_value(kind: str, acc, out_type: DataType):
    cnt, s, mn, mx = acc
    if kind == "count":
        return cnt
    if kind == "sum":
        return None if cnt == 0 else s
    if kind == "min":
        return mn
    if kind == "max":
        return mx
    if kind == "avg":
        return None if cnt == 0 else (
            s / cnt if out_type.is_float else s // cnt)
    raise AssertionError(kind)


def compute_window_values(rows: list, calls: Sequence[WindowCall],
                          pk_indices) -> dict:
    """Full recompute for one partition: {pk: (win values…)} — the host
    model the executors and tests share. ``rows`` are physical tuples."""
    if not rows:
        return {}
    order_by = calls[0].order_by
    srows = _sort_partition(rows, order_by, pk_indices)
    n = len(srows)
    keys = [_order_key(r, order_by) for r in srows]
    # peer groups: [start, end) spans of equal order key
    group_of = [0] * n
    g = 0
    for i in range(1, n):
        if keys[i] != keys[i - 1]:
            g += 1
        group_of[i] = g
    group_start = {}
    group_end = {}
    for i in range(n):
        group_start.setdefault(group_of[i], i)
        group_end[group_of[i]] = i + 1

    out_cols = []
    for c in calls:
        vals: list = [None] * n
        if c.kind == "row_number":
            vals = [i + 1 for i in range(n)]
        elif c.kind == "rank":
            vals = [group_start[group_of[i]] + 1 for i in range(n)]
        elif c.kind == "dense_rank":
            vals = [group_of[i] + 1 for i in range(n)]
        elif c.kind == "lag":
            vals = [srows[i - c.offset][c.arg] if i - c.offset >= 0 else None
                    for i in range(n)]
        elif c.kind == "lead":
            vals = [srows[i + c.offset][c.arg] if i + c.offset < n else None
                    for i in range(n)]
        elif c.kind in AGG_WINDOW_KINDS:
            acc = (0, None, None, None)

            def arg_of(r, _c=c):
                # count(*) (arg=-1) counts every row; others skip NULL args
                return 1 if _c.arg < 0 else r[_c.arg]

            if not order_by:
                for r in srows:
                    acc = _agg_step(c.kind, acc, arg_of(r))
                v = _agg_value(c.kind, acc, c.output_type)
                vals = [v] * n
            else:
                # RANGE ... CURRENT ROW: value at end of own peer group
                per_group_val = {}
                for gi in sorted(group_end):
                    for i in range(group_start[gi], group_end[gi]):
                        acc = _agg_step(c.kind, acc, arg_of(srows[i]))
                    per_group_val[gi] = _agg_value(c.kind, acc, c.output_type)
                vals = [per_group_val[group_of[i]] for i in range(n)]
        else:
            raise ValueError(f"unsupported window function {c.kind}")
        out_cols.append(vals)
    return {
        tuple(srows[i][j] for j in pk_indices):
            tuple(col[i] for col in out_cols)
        for i in range(n)
    }


def _emit_chunks(schema: Schema, pairs: list, out_capacity: int):
    """pairs: list of (op, physical_row); U-/U+ pairs kept adjacent and
    never split across chunk boundaries."""
    i = 0
    while i < len(pairs):
        take = pairs[i:i + out_capacity]
        if (take and take[-1][0] == OP_UPDATE_DELETE
                and i + len(take) < len(pairs)):
            take = take[:-1]
        i += len(take)
        yield make_chunk(schema, [r for _, r in take],
                         ops=[op for op, _ in take],
                         capacity=max(out_capacity, len(take)),
                         physical=True)


class _Partition:
    """Sorted partition state for incremental maintenance: entries kept in
    (order-key, pk) order with per-position value/accumulator snapshots so
    a barrier recomputes only the suffix from the first changed position
    (the reference's delta-neighborhood idea, delta_btree_map.rs)."""

    __slots__ = ("entries", "vals", "accs", "dense")

    def __init__(self):
        self.entries: list = []     # (sortkey, row); sortkey=(okey, pk)
        self.vals: list = []        # aligned output tuples
        self.accs: list = []        # aligned tuple-of-acc per agg call
        self.dense: list = []       # aligned 0-based dense-group ordinal


class OverWindowExecutor(SingleInputExecutor):
    """General (retractable) over-window with **incremental** maintenance:
    per-barrier work is O(delta · log n + affected-suffix), not
    O(partition) (VERDICT r4 weak #6; reference:
    over_window/delta_btree_map.rs). Rows before the first changed
    order-key position keep their values — window functions with the PG
    default frame only ever read the prefix — so for in-order (event-time
    ascending) streams the suffix IS the delta. Output schema = input ⧺
    window columns."""

    identity = "OverWindow"

    def __init__(self, input: Executor, calls: Sequence[WindowCall],
                 pk_indices: Sequence[int],
                 state_table: Optional[StateTable] = None,
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY):
        super().__init__(input)
        self.calls = tuple(calls)
        self.pk_indices = tuple(pk_indices)
        self.schema = Schema(tuple(input.schema) + tuple(
            Field(f"_win{i}", c.output_type)
            for i, c in enumerate(self.calls)))
        self.in_schema = input.schema
        self.state_table = state_table
        self.out_capacity = out_capacity
        self._part_cols = self.calls[0].partition_by
        self._order_by = self.calls[0].order_by
        self._max_lead = max(
            (c.offset for c in self.calls if c.kind == "lead"), default=0)
        self._agg_idx = [i for i, c in enumerate(self.calls)
                        if c.kind in AGG_WINDOW_KINDS]
        self._parts: dict[tuple, _Partition] = {}
        self._pk_loc: dict[tuple, tuple] = {}   # pk -> (part, sortkey)
        self._out: dict[tuple, dict] = {}   # part -> {pk: (row, vals)}
        #: per-barrier change tracking
        self._min_key: dict[tuple, tuple] = {}   # part -> min touched key
        self._removed: dict[tuple, set] = {}     # part -> pks deleted
        #: count of positions recomputed since construction (microbench /
        #: introspection hook proving O(delta) behavior)
        self.positions_recomputed = 0
        if state_table is not None:
            for row in state_table.scan_all():
                self._apply_row(OP_INSERT, tuple(row))
            for part in list(self._min_key):
                self._recompute_and_diff(part)   # discard initial diff
            self._min_key.clear()
            self._removed.clear()

    def _part_of(self, row) -> tuple:
        return tuple(row[i] for i in self._part_cols)

    def _sortkey(self, row: tuple) -> tuple:
        return (_order_key(row, self._order_by),
                tuple(row[i] for i in self.pk_indices))

    def _note(self, part: tuple, key: tuple) -> None:
        cur = self._min_key.get(part)
        if cur is None or key < cur:
            self._min_key[part] = key

    def _apply_row(self, op: int, row: tuple) -> None:
        import bisect
        pk = tuple(row[i] for i in self.pk_indices)
        part = self._part_of(row)
        key = self._sortkey(row)
        if op in (OP_INSERT, OP_UPDATE_INSERT):
            loc = self._pk_loc.get(pk)
            if loc is not None:
                # upsert: a bare INSERT for a live pk replaces its row —
                # possibly in a DIFFERENT partition (the pre-incremental
                # executor's contract)
                self._drop_entry(pk, *loc)
                self._removed.setdefault(loc[0], set()).add(pk)
            p = self._parts.get(part)
            if p is None:
                p = self._parts[part] = _Partition()
            pos = bisect.bisect_left(p.entries, key, key=lambda e: e[0])
            p.entries.insert(pos, (key, row))
            p.vals.insert(pos, None)
            p.accs.insert(pos, None)
            p.dense.insert(pos, -1)
            self._pk_loc[pk] = (part, key)
            self._removed.get(part, set()).discard(pk)
            self._note(part, key)
        else:
            loc = self._pk_loc.pop(pk, None)
            if loc is None:
                return                     # delete of unknown row
            self._drop_entry(pk, *loc)
            self._removed.setdefault(loc[0], set()).add(pk)

    def _drop_entry(self, pk: tuple, part: tuple, key: tuple) -> None:
        import bisect
        p = self._parts.get(part)
        if p is None:
            return
        pos = bisect.bisect_left(p.entries, key, key=lambda e: e[0])
        if pos < len(p.entries) and p.entries[pos][0] == key:
            del p.entries[pos], p.vals[pos], p.accs[pos], p.dense[pos]
        self._note(part, key)

    async def map_chunk(self, chunk: StreamChunk):
        for op, row in chunk_to_rows(chunk, self.in_schema, with_ops=True,
                                     physical=True):
            self._apply_row(op, tuple(row))
            if self.state_table is not None:
                if op in (OP_INSERT, OP_UPDATE_INSERT):
                    self.state_table.insert(row)
                else:
                    self.state_table.delete(row)
        if False:
            yield

    # -- incremental recompute ------------------------------------------------

    def _start_pos(self, p: _Partition, min_key: tuple) -> int:
        import bisect
        n = len(p.entries)
        start = bisect.bisect_left(p.entries, min_key, key=lambda e: e[0])
        start = max(0, start - self._max_lead)
        start = min(start, n)
        # back up to the start of the peer group (rank/agg values are
        # shared across peers; the group containing the first change
        # recomputes wholesale)
        while 0 < start < n and p.entries[start - 1][0][0] == \
                p.entries[start][0][0]:
            start -= 1
        if start == n and n > 0:
            # change strictly beyond the end (deletion of the old tail):
            # the surviving tail's lead()s looked past it — recompute the
            # last peer group + lead reach
            start = max(0, n - 1 - self._max_lead)
            while 0 < start and p.entries[start - 1][0][0] == \
                    p.entries[start][0][0]:
                start -= 1
        return start

    def _recompute_suffix(self, p: _Partition, start: int) -> None:
        """Recompute vals/accs/dense for positions [start, n)."""
        n = len(p.entries)
        self.positions_recomputed += n - start
        if start > 0:
            prev_accs = p.accs[start - 1]
            prev_dense = p.dense[start - 1]
        else:
            prev_accs = tuple((0, None, None, None) for _ in self._agg_idx)
            prev_dense = -1
        rows = p.entries
        calls = self.calls
        # group-close assignment: collect the open peer group's positions,
        # assign agg values when the key changes
        group_positions: list = []
        group_start = start

        def close_group(end_accs):
            for gi in group_positions:
                vals = list(p.vals[gi])
                for aj, ci in enumerate(self._agg_idx):
                    vals[ci] = _agg_value(calls[ci].kind, end_accs[aj],
                                          calls[ci].output_type)
                p.vals[gi] = tuple(vals)

        accs = prev_accs
        dense = prev_dense
        for i in range(start, n):
            okey = rows[i][0][0]
            new_group = (i == start) or okey != rows[i - 1][0][0]
            if new_group:
                if group_positions:
                    close_group(accs)
                group_positions = []
                group_start = i
                dense += 1
            row = rows[i][1]
            new_accs = []
            for aj, ci in enumerate(self._agg_idx):
                c = calls[ci]
                v = 1 if c.arg < 0 else row[c.arg]
                new_accs.append(_agg_step(c.kind, accs[aj], v))
            accs = tuple(new_accs)
            p.accs[i] = accs
            p.dense[i] = dense
            vals = []
            for ci, c in enumerate(calls):
                if c.kind == "row_number":
                    vals.append(i + 1)
                elif c.kind == "rank":
                    vals.append(group_start + 1)
                elif c.kind == "dense_rank":
                    vals.append(dense + 1)
                elif c.kind == "lag":
                    j = i - c.offset
                    vals.append(rows[j][1][c.arg] if j >= 0 else None)
                elif c.kind == "lead":
                    j = i + c.offset
                    vals.append(rows[j][1][c.arg] if j < n else None)
                else:
                    vals.append(None)       # agg: assigned at group close
            p.vals[i] = tuple(vals)
            group_positions.append(i)
        if group_positions:
            close_group(accs)
        # (lead() needs no extra pass: _start_pos already backed up by
        # _max_lead, so every position whose lead target changed is INSIDE
        # the recomputed suffix)

    def _recompute_and_diff(self, part: tuple) -> tuple:
        """Returns (deletes, others) op/out_row pair lists for one dirty
        partition and updates the emitted-output cache. Deletes are
        separated so the barrier can emit ALL deletes first — a pk moving
        between partitions must retract from its old partition before the
        new partition's insert reaches the downstream pk-keyed state."""
        p = self._parts.get(part)
        out = self._out.setdefault(part, {})
        deletes: list = []
        others: list = []
        min_key = self._min_key[part]
        removed = self._removed.pop(part, set())
        if p is None or not p.entries:
            self._parts.pop(part, None)
            for pk, (row, vals) in out.items():
                deletes.append((OP_DELETE, row + vals))
            self._out.pop(part, None)
            return deletes, others
        start = self._start_pos(p, min_key)
        self._recompute_suffix(p, start)
        live_suffix_pks = set()
        for i in range(start, len(p.entries)):
            key, row = p.entries[i]
            pk = key[1]
            live_suffix_pks.add(pk)
            vals = p.vals[i]
            old = out.get(pk)
            if old is None:
                others.append((OP_INSERT, row + vals))
            elif old != (row, vals):
                others.append((OP_UPDATE_DELETE, old[0] + old[1]))
                others.append((OP_UPDATE_INSERT, row + vals))
            out[pk] = (row, vals)
        for pk in removed:
            if pk not in live_suffix_pks and pk in out:
                row, vals = out.pop(pk)
                deletes.append((OP_DELETE, row + vals))
        return deletes, others

    async def on_barrier(self, barrier: Barrier):
        deletes: list = []
        others: list = []
        for part in sorted(self._min_key, key=repr):
            d, o = self._recompute_and_diff(part)
            deletes.extend(d)
            others.extend(o)
        self._min_key.clear()
        self._removed.clear()
        for chunk in _emit_chunks(self.schema, deletes + others,
                                  self.out_capacity):
            yield chunk
        if self.state_table is not None:
            self.state_table.commit(barrier.epoch.curr)


def eowc_acc_schema(in_schema: Schema, calls: Sequence[WindowCall]) -> Schema:
    """Accumulator-table schema for the EOWC executor: partition key cols
    ⧺ (n, last_order, rank_last, dense_last) ⧺ per-call (cnt, sum, min, max)."""
    part = calls[0].partition_by
    fields = [Field(f"p{i}", in_schema[c].type) for i, c in enumerate(part)]
    fields += [Field("_n", INT64), Field("_last_ord", INT64),
               Field("_rank_last", INT64), Field("_dense_last", INT64)]
    for i, c in enumerate(calls):
        arg_t = in_schema[c.arg].type if c.arg >= 0 else INT64
        sum_t = c.output_type if c.kind in ("sum", "avg") else arg_t
        fields += [Field(f"c{i}_cnt", INT64), Field(f"c{i}_sum", sum_t),
                   Field(f"c{i}_min", arg_t), Field(f"c{i}_max", arg_t)]
    return Schema(tuple(fields))


class EowcOverWindowExecutor(SingleInputExecutor):
    """Append-only over-window with emit-on-window-close semantics
    (reference: over_window/eowc.rs). Input must arrive sorted by the
    order column (SortExecutor upstream) and append-only; each row is
    emitted exactly once, when its peer group closes (a later order value
    arrives, or the watermark passes it at a barrier)."""

    identity = "EowcOverWindow"

    def __init__(self, input: Executor, calls: Sequence[WindowCall],
                 pk_indices: Sequence[int],
                 acc_table: Optional[StateTable] = None,
                 buffer_table: Optional[StateTable] = None,
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY):
        super().__init__(input)
        self.calls = tuple(calls)
        for c in self.calls:
            if c.kind not in RANK_KINDS | AGG_WINDOW_KINDS:
                raise ValueError(
                    f"{c.kind} is not emit-on-window-close capable")
        if not self.calls[0].order_by:
            raise ValueError("EOWC over-window requires ORDER BY")
        self.order_col = self.calls[0].order_by[0].col
        self.pk_indices = tuple(pk_indices)
        self.schema = Schema(tuple(input.schema) + tuple(
            Field(f"_win{i}", c.output_type)
            for i, c in enumerate(self.calls)))
        self.in_schema = input.schema
        self.out_capacity = out_capacity
        self.acc_table = acc_table
        self.buffer_table = buffer_table
        self._part_cols = self.calls[0].partition_by
        # part -> {"n", "last_ord", "rank_last", "dense_last", "accs": [...]}
        self._accs: dict[tuple, dict] = {}
        self._pending: dict[tuple, list] = {}     # open peer group rows
        self._wm: Optional[int] = None
        self._emit_buf: list = []
        if acc_table is not None:
            npart = len(self._part_cols)
            for row in acc_table.scan_all():
                part = tuple(row[:npart])
                st = {"n": row[npart], "last_ord": row[npart + 1],
                      "rank_last": row[npart + 2],
                      "dense_last": row[npart + 3], "accs": []}
                base = npart + 4
                for i in range(len(self.calls)):
                    st["accs"].append(tuple(row[base + 4 * i:base + 4 * i + 4]))
                self._accs[part] = st
        if buffer_table is not None:
            for row in buffer_table.scan_all():
                part = tuple(row[i] for i in self._part_cols)
                self._pending.setdefault(part, []).append(tuple(row))

    def _flush_group(self, part: tuple) -> None:
        """Close the open peer group: run it through the accumulators and
        emit its rows."""
        rows = self._pending.pop(part, None)
        if not rows:
            return
        rows = _sort_partition(rows, self.calls[0].order_by, self.pk_indices)
        st = self._accs.setdefault(part, {
            "n": 0, "last_ord": None, "rank_last": 0, "dense_last": 0,
            "accs": [(0, None, None, None)] * len(self.calls)})
        n0 = st["n"]
        rank = n0 + 1
        dense = st["dense_last"] + 1
        # aggregates: whole peer group folds in before any row's value
        # (RANGE frame includes peers)
        for i, c in enumerate(self.calls):
            if c.kind in AGG_WINDOW_KINDS:
                acc = st["accs"][i]
                for r in rows:
                    acc = _agg_step(c.kind, acc,
                                    1 if c.arg < 0 else r[c.arg])
                st["accs"][i] = acc
        for j, r in enumerate(rows):
            vals = []
            for i, c in enumerate(self.calls):
                if c.kind == "row_number":
                    vals.append(n0 + j + 1)
                elif c.kind == "rank":
                    vals.append(rank)
                elif c.kind == "dense_rank":
                    vals.append(dense)
                else:
                    vals.append(_agg_value(c.kind, st["accs"][i],
                                           c.output_type))
            self._emit_buf.append((OP_INSERT, r + tuple(vals)))
            if self.buffer_table is not None:
                self.buffer_table.delete(r)
        st["n"] = n0 + len(rows)
        st["last_ord"] = rows[-1][self.order_col]
        st["rank_last"] = rank
        st["dense_last"] = dense

    async def map_chunk(self, chunk: StreamChunk):
        for op, row in chunk_to_rows(chunk, self.in_schema, with_ops=True,
                                     physical=True):
            if op != OP_INSERT:
                raise AssertionError(
                    "EOWC over-window requires append-only input")
            row = tuple(row)
            part = self._part_of(row)
            pend = self._pending.get(part)
            if pend and row[self.order_col] != pend[0][self.order_col]:
                if row[self.order_col] < pend[0][self.order_col]:
                    raise AssertionError(
                        "EOWC over-window input not sorted by order column")
                self._flush_group(part)
            self._pending.setdefault(part, []).append(row)
            if self.buffer_table is not None:
                self.buffer_table.insert(row)
        for chunk_out in self._drain_emit():
            yield chunk_out

    def _part_of(self, row) -> tuple:
        return tuple(row[i] for i in self._part_cols)

    def _drain_emit(self):
        buf, self._emit_buf = self._emit_buf, []
        yield from _emit_chunks(self.schema, buf, self.out_capacity)

    async def on_watermark(self, watermark: Watermark):
        if watermark.col_idx == self.order_col:
            self._wm = watermark.value
        yield watermark

    async def on_barrier(self, barrier: Barrier):
        # peer groups strictly below the watermark can never grow again
        # (rows with ts >= wm may still arrive; ts < wm were dropped
        # upstream by the WatermarkFilter): close them now
        if self._wm is not None:
            for part in list(self._pending):
                rows = self._pending[part]
                if rows and rows[0][self.order_col] < self._wm:
                    self._flush_group(part)
        for chunk in self._drain_emit():
            yield chunk
        epoch = barrier.epoch.curr
        if self.acc_table is not None:
            for part, st in self._accs.items():
                row = list(part) + [st["n"], st["last_ord"],
                                    st["rank_last"], st["dense_last"]]
                for acc in st["accs"]:
                    row.extend(acc)
                self.acc_table.insert(tuple(row))
            self.acc_table.commit(epoch)
        if self.buffer_table is not None:
            self.buffer_table.commit(epoch)
