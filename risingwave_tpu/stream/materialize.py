"""MaterializeExecutor — terminal sink writing MV rows to a StateTable.

Counterpart of the reference's MaterializeExecutor
(reference: src/stream/src/executor/mview/materialize.rs:52). The egress
boundary is where device chunks become host rows (one device_get per chunk);
everything upstream stayed on device. Conflict handling is overwrite-on-pk,
matching the reference's default HandleConflictBehavior for MVs.
"""

from __future__ import annotations

from typing import AsyncIterator

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, StreamChunk,
    chunk_to_rows,
)
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


class MaterializeExecutor(SingleInputExecutor):
    identity = "Materialize"

    def __init__(self, input: Executor, state_table: StateTable):
        super().__init__(input)
        self.schema = input.schema
        self.table = state_table

    async def map_chunk(self, chunk: StreamChunk):
        for op, phys in chunk_to_rows(chunk, self.schema, with_ops=True,
                                      physical=True):
            if op in (OP_INSERT, OP_UPDATE_INSERT):
                self.table.insert(phys)
            else:
                self.table.delete(phys)
        yield chunk

    async def on_barrier(self, barrier: Barrier):
        # table-level seal only: the STORE-level epoch commit belongs to the
        # barrier conductor (Session.tick) after ALL jobs collected the
        # barrier — an executor-side commit raced concurrent jobs' ingests
        # and could strand them pending forever (reference: HummockManager.
        # commit_epoch is driven by meta after barrier collection, not by
        # materialize).
        from ..common.tracing import CAT_STORAGE, trace_span
        with trace_span(f"{self.identity}.seal", CAT_STORAGE,
                        epoch=barrier.epoch.curr, tid=self.identity):
            self.table.commit(barrier.epoch.curr)
        if False:
            yield

    # -- query surface (batch scan over the MV) ------------------------------

    def rows(self) -> list[tuple]:
        out = []
        for phys in self.table.scan_all():
            out.append(tuple(
                None if v is None else self.schema[i].type.to_python(v)
                for i, v in enumerate(phys)
            ))
        return out
