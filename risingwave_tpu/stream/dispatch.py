"""Dispatchers + permit-based exchange channels + merge fan-in.

Counterparts of the reference's actor delivery fabric:
  * dispatchers (reference: src/stream/src/executor/dispatch.rs — Hash
    :532, Broadcast :715, Simple :798, RoundRobin :455), including the
    update-pair rule at dispatch.rs:635-650: an UpdateDelete/UpdateInsert
    pair whose key moves across outputs is degraded to Delete+Insert;
  * permit-based backpressure channels (reference:
    exchange/permit.rs:35-107 — bounded budget for data, barriers always
    admitted so the control stream can never deadlock behind data);
  * merge fan-in with barrier alignment (reference: executor/merge.rs:114
    SelectReceivers — forward data freely, hold each upstream's barrier
    until ALL upstreams produced the epoch's barrier).

TPU angle: the hash split is computed on device for the whole chunk (one
vnode hash + per-output visibility masks — no row loop); only the
channel plumbing is host asyncio.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, StreamChunk,
)
from ..common.hashing import vnode_of, vnode_to_shard
from ..common.types import Schema
from .executor import Executor
from .message import Barrier, Message, Watermark


class MsgQueue:
    """Single-consumer unbounded message queue.

    Functionally asyncio.Queue(put_nowait/get), minus one asyncio.Queue
    wart this runtime keeps hitting: Queue.get's cleanup path calls
    ``getter.cancel()`` → ``loop.call_soon`` even when finalized by GC
    AFTER the owning loop closed, spraying "Event loop is closed"
    unraisable warnings whenever an abandoned executor generator (job
    stop/reschedule leaves them suspended in get()) is collected late.
    This get() awaits a bare future and only clears it in ``finally`` —
    no loop interaction on finalization, so late GC is silent."""

    def __init__(self) -> None:
        import collections
        self._items: collections.deque = collections.deque()
        self._waiter: Optional[asyncio.Future] = None

    def put_nowait(self, item) -> None:
        self._items.append(item)
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)

    async def put(self, item) -> None:
        # unbounded: never blocks (PermitChannel does its own flow
        # control with a semaphore before calling this)
        self.put_nowait(item)

    async def get(self):
        while not self._items:
            self._waiter = asyncio.get_running_loop().create_future()
            try:
                await self._waiter
            finally:
                self._waiter = None
        return self._items.popleft()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items


class PermitChannel:
    """Bounded exchange edge. Data messages consume permits (one per chunk
    of capacity rows — the reference counts cardinality; capacity is the
    host-known stand-in) and block the SENDER when the budget is
    exhausted; barriers and watermarks always pass (control never queues
    behind data)."""

    def __init__(self, permits: int = 32):
        self._sem = asyncio.Semaphore(permits)
        self._q = MsgQueue()
        self.permits = permits

    async def send(self, msg: Message) -> None:
        if isinstance(msg, StreamChunk):
            await self._sem.acquire()
            await self._q.put(("data", msg))
        else:
            await self._q.put(("ctl", msg))

    async def recv(self) -> Message:
        kind, msg = await self._q.get()
        if kind == "data":
            self._sem.release()
        return msg

    def close(self) -> None:
        self._q.put_nowait(("ctl", None))


def open_channel(permits: int = 32) -> PermitChannel:
    """THE way to obtain an exchange channel outside this module. Every
    exchange edge — in-process fragment fabric, worker-local span edges —
    goes through here so flow-control policy stays in one place
    (scripts/check.sh lints direct ``PermitChannel(...)`` construction
    outside the fabric the same way raw object-store opens are linted)."""
    return PermitChannel(permits)


class ChannelSource(Executor):
    """Executor view of a PermitChannel's receiving end."""

    identity = "ChannelSource"

    def __init__(self, channel: PermitChannel, schema: Schema):
        self.channel = channel
        self.schema = schema

    async def execute(self) -> AsyncIterator[Message]:
        while True:
            msg = await self.channel.recv()
            if msg is None:
                return
            yield msg
            if isinstance(msg, Barrier) and msg.is_stop():
                return


class HashDispatcher:
    """Route each row to ``vnode → shard`` output; barriers/watermarks
    broadcast. The whole split is one jitted device step producing one
    visibility mask per output."""

    def __init__(self, outputs: Sequence[PermitChannel],
                 key_cols: Sequence[int], schema: Schema):
        self.outputs = list(outputs)
        self.key_cols = tuple(key_cols)
        n_out = len(self.outputs)

        @jax.jit
        def _split(chunk: StreamChunk):
            cols = [chunk.columns[i] for i in self.key_cols]
            shard = vnode_to_shard(vnode_of(cols), n_out)
            ops = chunk.ops
            # update-pair splitting (dispatch.rs:635-650): if U- and its
            # U+ land on different shards, both degrade to plain ops
            is_ud = ops == OP_UPDATE_DELETE
            is_ui = ops == OP_UPDATE_INSERT
            partner_shard = jnp.roll(shard, -1)       # U- partner follows
            partner_shard_prev = jnp.roll(shard, 1)   # U+ partner precedes
            split_pair = (is_ud & (partner_shard != shard)) | (
                is_ui & (partner_shard_prev != shard))
            new_ops = jnp.where(
                split_pair & is_ud, OP_DELETE,
                jnp.where(split_pair & is_ui, OP_INSERT, ops),
            ).astype(ops.dtype)
            masks = tuple(
                chunk.vis & (shard == o) for o in range(n_out))
            return new_ops, masks

        self._split = _split

    async def dispatch(self, msg: Message) -> None:
        if isinstance(msg, StreamChunk):
            new_ops, masks = self._split(msg)
            rebased = msg.replace(ops=new_ops)
            for out, mask in zip(self.outputs, masks):
                await out.send(rebased.with_vis(mask))
            return
        from ..common.chunk import ChunkBatch
        if isinstance(msg, ChunkBatch):
            # data must be split, never broadcast: unpack the batch
            for i in range(msg.num_chunks):
                await self.dispatch(msg.at(i))
            return
        for out in self.outputs:
            await out.send(msg)


class BroadcastDispatcher:
    def __init__(self, outputs: Sequence[PermitChannel]):
        self.outputs = list(outputs)

    async def dispatch(self, msg: Message) -> None:
        for out in self.outputs:
            await out.send(msg)


class RoundRobinDispatcher:
    """Chunk-granular round robin (reference :455 — used for stateless
    fragments where row placement is free)."""

    def __init__(self, outputs: Sequence[PermitChannel]):
        self.outputs = list(outputs)
        self._i = 0

    async def dispatch(self, msg: Message) -> None:
        if isinstance(msg, StreamChunk):
            out = self.outputs[self._i % len(self.outputs)]
            self._i += 1
            await out.send(msg)
        else:
            for out in self.outputs:
                await out.send(msg)


class SimpleDispatcher(BroadcastDispatcher):
    """1:1 pipe (reference :798 / NoShuffle)."""

    def __init__(self, output: PermitChannel):
        super().__init__([output])


class MergeExecutor(Executor):
    """N-ary fan-in with barrier alignment: chunks forward as they arrive;
    an upstream that produced the epoch's barrier is parked until every
    upstream has. Watermarks are ALIGNED per column: the merge forwards
    the minimum over all upstreams, and only once every live upstream has
    reported one for that column (reference: BufferedWatermarks in
    executor/merge.rs — a fan-in must not let one shard's watermark
    overtake another shard's still-buffered rows below it)."""

    identity = "Merge"

    def __init__(self, channels: Sequence[PermitChannel], schema: Schema):
        self.channels = list(channels)
        self.schema = schema
        # col_idx -> {channel_idx: latest value}; col_idx -> last forwarded
        self._wm: dict[int, dict[int, object]] = {}
        self._wm_sent: dict[int, object] = {}

    def _on_watermark(self, i: int, wm: Watermark,
                      finished: set) -> Optional[Watermark]:
        per_chan = self._wm.setdefault(wm.col_idx, {})
        per_chan[i] = wm.value
        live = [j for j in range(len(self.channels)) if j not in finished]
        if not all(j in per_chan for j in live):
            return None
        lo = min(per_chan[j] for j in live)
        if wm.col_idx in self._wm_sent and lo <= self._wm_sent[wm.col_idx]:
            return None
        self._wm_sent[wm.col_idx] = lo
        return Watermark(wm.col_idx, lo)

    async def execute(self) -> AsyncIterator[Message]:
        n = len(self.channels)
        held: dict[int, Barrier] = {}
        finished: set[int] = set()
        pending: dict[int, asyncio.Task] = {}
        try:
            while True:
                for i, ch in enumerate(self.channels):
                    if i not in pending and i not in finished and i not in held:
                        pending[i] = asyncio.ensure_future(ch.recv())
                if not pending and not held:
                    return
                if pending:
                    done, _ = await asyncio.wait(
                        pending.values(),
                        return_when=asyncio.FIRST_COMPLETED)
                    for i in list(pending):
                        task = pending[i]
                        if task not in done:
                            continue
                        del pending[i]
                        msg = task.result()
                        if msg is None:
                            finished.add(i)
                        elif isinstance(msg, Barrier):
                            held[i] = msg
                        elif isinstance(msg, Watermark):
                            out = self._on_watermark(i, msg, finished)
                            if out is not None:
                                yield out
                        else:
                            yield msg
                live = [i for i in range(n) if i not in finished]
                if live and all(i in held for i in live):
                    epochs = {held[i].epoch.curr for i in live}
                    if len(epochs) != 1:
                        raise AssertionError(
                            f"barrier misalignment at merge: {sorted(epochs)}")
                    barrier = held[next(iter(live))]
                    held.clear()
                    yield barrier
                    if barrier.is_stop():
                        return
                if not live:
                    return
        finally:
            for task in pending.values():
                task.cancel()
