"""HashJoinExecutor — streaming equi-join on device-resident state.

Host control loop over the pure device join step (ops/join_state.py).
Counterpart of the reference's HashJoinExecutor
(reference: src/stream/src/executor/hash_join.rs:227-270; barrier-aligned
two-input loop :693; flush :837). All join types of the reference's
const-generic ``JoinTypePrimitive`` are supported, plus non-equi conditions.

Durability: each side has an optional StateTable holding its live rows
(pk = the stream pk). On checkpoint barriers the lanes dirtied since the
last checkpoint are flushed (upserts for live rows, deletes for tombstoned
ones) — degrees are NOT persisted; recovery replays both sides' rows
through the normal insert path with emission suppressed, which rebuilds
degrees exactly (cheaper and simpler than the reference's degree table,
managed_state/join/mod.rs:228-258).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    physical_chunk,
    DEFAULT_CHUNK_CAPACITY, StreamChunk, count_units, flatten_shards,
    gather_units_window, make_chunk,
)
from ..common.fetch import fetch
from ..ops.join_state import (
    JoinCore, JoinSideState, JoinState, JoinType, apply_evict_side,
    clean_side_below, compact_side, import_state, join_evict_plan,
)
from ..storage.state_table import StateTable
from .barrier_align import barrier_align
from .executor import Executor
from .message import Barrier


class HashJoinExecutor(Executor):
    identity = "HashJoin"

    def __init__(
        self,
        left: Executor,
        right: Executor,
        left_keys: Sequence[int],
        right_keys: Sequence[int],
        join_type: JoinType = JoinType.INNER,
        condition=None,
        left_state_table: Optional[StateTable] = None,
        right_state_table: Optional[StateTable] = None,
        key_capacity: int = 1 << 13,
        bucket_width: int = 16,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
        strict: bool = True,
        interval_clean: Sequence[tuple] = (),
        load_shard: Optional[tuple] = None,
        hbm_key_budget: Optional[int] = None,
        null_aware_anti: bool = False,
    ):
        """``interval_clean``: state-cleaning rules for interval/windowed
        joins — tuples ``(clean_side, clean_col, watch_side, watch_col,
        lag)``: when a watermark arrives on ``watch_side``'s column
        ``watch_col``, rows on ``clean_side`` whose ``clean_col`` value is
        below ``watermark - lag`` are freed at the next checkpoint
        (reference: interval-join state cleaning, hash_join.rs).

        ``load_shard``: (shard_idx, n_shards) for fragmented builds — the N
        join actors of one fragment share BOTH logical state tables; on
        recovery each actor keeps only the rows whose JOIN KEY hashes to
        its shard (the same device vnode hash the HashDispatcher routes
        live rows with), so recovery works across any parallelism change
        (reference: vnode-bitmap reassignment, stream/scale.rs:657).

        ``hbm_key_budget``: cap on LIVE join keys held per device arena.
        When a checkpoint finds more, the coldest keys (LRU by touch step,
        synced across the two sides) are evicted from BOTH arenas to the
        state tables and faulted back when a chunk mentions them — device
        state becomes a cache over the durable tier instead of
        grow-or-raise (reference: JoinHashMap's ManagedLruCache,
        src/stream/src/executor/managed_state/join/mod.rs:228-258).
        Requires both state tables with JOIN-KEY-PREFIXED pks (the
        builder lays pks out as join_keys ++ stream_pk so fault-in is a
        pk prefix scan)."""
        self.left, self.right = left, right
        self.load_shard = load_shard
        # PG NOT IN semantics (planner.py _plan_in_subquery): a NULL
        # arriving on the build side would have to retract EVERY emitted
        # probe row — incremental null-aware anti join is a global flip
        # this executor does not implement, so it rejects loudly instead
        # of silently diverging from PG (NULL probe keys are already
        # filtered below the join at plan time).
        self.null_aware_anti = bool(null_aware_anti) and \
            join_type == JoinType.LEFT_ANTI
        from .metrics import ExecutorStats
        self.stats = ExecutorStats()
        self._join_args = dict(join_type=join_type, condition=condition)
        self._key_args = (left_keys, right_keys)
        self.interval_clean = tuple(interval_clean)
        self._pending_clean: dict[tuple[str, int], int] = {}
        # max threshold ever applied per (side, col) — the fault-in filter
        self._applied_clean: dict[tuple[str, int], int] = {}
        self.core = JoinCore(
            left.schema, right.schema, left_keys, right_keys, join_type,
            condition=condition, key_capacity=key_capacity,
            bucket_width=bucket_width,
        )
        self.schema = self.core.out_schema
        self.out_capacity = out_capacity
        # chunks applied per host sync (optimistic batched emission)
        self.emit_batch = 16
        # chunks scanned per dispatch when a whole ChunkBatch arrives
        # (memory-bounds the stacked emission grids of the scan)
        self.batch_chunks = 8
        self.strict = strict
        self.max_state_cells = 1 << 26    # growth ceiling (cap * W)
        self.state_tables = {"left": left_state_table,
                             "right": right_state_table}
        if hbm_key_budget is not None:
            if left_state_table is None or right_state_table is None:
                hbm_key_budget = None      # no cold tier to evict to
            elif hbm_key_budget >= key_capacity:
                raise ValueError("hbm_key_budget must be < key_capacity")
            else:
                # the growth ceiling exists to stop unbounded arenas; with
                # a cold tier the arena is bounded by eviction instead
                self.max_state_cells = 1 << 30
        self.hbm_key_budget = hbm_key_budget
        self._evicted: set = set()
        from .cache import LruClock
        self._lru_clock = LruClock(hbm_key_budget is not None)
        self.state = self.core.init_state()
        self._make_jits()
        if any(self.state_tables.values()):
            self._load_from_state_tables()

    def _make_jits(self) -> None:
        core = self.core
        self._apply = {
            "left": jax.jit(lambda st, ch, step=None:
                            core.apply_chunk(st, ch, side="left", step=step)),
            "right": jax.jit(lambda st, ch, step=None:
                             core.apply_chunk(st, ch, side="right", step=step)),
        }

        # batched single-dispatch ingest: ONE lax.scan applies a whole
        # sub-batch of chunks to one side and stacks each chunk's packed
        # stats + emission grid — K chunks cost one dispatch and one stats
        # transfer instead of K of each (the ChunkBatch amortization the
        # agg path has had since round 3; docs/performance.md)
        def _apply_batch(state: JoinState, batched_chunk, steps, side: str):
            # steps=None (no LRU budget) traces the stamp-free variant —
            # the per-chunk path's static elision of the three lru
            # scatter-maxes, preserved under the scan
            def body(st, x):
                ch, step = x if steps is not None else (x, None)
                st, big = core.apply_chunk(st, ch, side=side, step=step)
                return st, (_pack_stats_of(st, big), big)

            xs = (batched_chunk, steps) if steps is not None \
                else batched_chunk
            state, (stats, bigs) = jax.lax.scan(body, state, xs)
            return state, stats, bigs

        self._apply_batch = {
            "left": jax.jit(functools.partial(_apply_batch, side="left")),
            "right": jax.jit(functools.partial(_apply_batch, side="right")),
        }

        def _gather_at(bigs, k, lo):
            big = jax.tree_util.tree_map(lambda x: x[k], bigs)
            return gather_units_window(big, lo, self.out_capacity)

        self._gather_at = jax.jit(_gather_at)
        self._evict_plan = jax.jit(join_evict_plan, static_argnums=(1,))

        def _apply_evict(state: JoinState, mask_l, mask_r) -> JoinState:
            return JoinState(left=apply_evict_side(state.left, mask_l),
                             right=apply_evict_side(state.right, mask_r))

        self._apply_evict = jax.jit(_apply_evict)
        self._gather = jax.jit(
            lambda ch, lo: gather_units_window(ch, lo, self.out_capacity))
        self._count_units = jax.jit(count_units)

        self._pack_stats = jax.jit(_pack_stats_of)
        self._clear_ckpt = jax.jit(_clear_ckpt_marks)
        self._clean_side = jax.jit(clean_side_below, static_argnums=(1,))

        def _compact(state: JoinState) -> JoinState:
            return JoinState(
                left=compact_side(self.core, state.left,
                                  self.core.left_schema, self.core.left_keys),
                right=compact_side(self.core, state.right,
                                   self.core.right_schema,
                                   self.core.right_keys),
            )

        self._compact = jax.jit(_compact)

    # -- LRU stamping ----------------------------------------------------------

    def _lru(self):
        return self._lru_clock.next()

    def _pykey(self, values) -> tuple:
        from .cache import canonical_key
        return canonical_key(values, self.core.key_types)

    # -- adaptive growth -------------------------------------------------------

    def _apply_growing(self, side: str, chunk: StreamChunk):
        """Apply a chunk; on overflow discard the result, grow the state
        geometry (bucket width for hot-key skew, key capacity for table
        fill), and retry on the untouched previous state. Functional state
        makes the retry exact — no partial effects to undo."""
        step = self._lru()
        while True:
            new_state, big = self._apply[side](self.state, chunk, step)
            sides = {"left": new_state.left, "right": new_state.right}
            lane_ovf = any(bool(st.lane_overflow) for st in sides.values())
            ht_ovf = any(bool(st.ht_overflow) for st in sides.values())
            if not lane_ovf and not ht_ovf:
                self.state = new_state
                return big
            new_W = self.core.W * 2 if lane_ovf else self.core.W
            new_cap = self.core.capacity * 2 if ht_ovf else self.core.capacity
            if new_W * new_cap > self.max_state_cells:
                raise RuntimeError(
                    f"{self.identity}: join state would exceed "
                    f"{self.max_state_cells} cells (cap={new_cap}, W={new_W})")
            self._grow(new_cap, new_W)

    def _grow(self, new_cap: int, new_W: int) -> None:
        left_keys, right_keys = self._key_args
        self.core = JoinCore(
            self.left.schema, self.right.schema, left_keys, right_keys,
            key_capacity=new_cap, bucket_width=new_W, **self._join_args)
        self.state = import_state(self.core, self.state)
        self._make_jits()

    # -- host loop -------------------------------------------------------------

    # -- optimistic batched emission ------------------------------------------
    # Applying a chunk is ONE async device dispatch, but reading its output
    # row count (and the overflow flags) is a host sync — on a tunneled
    # chip that sync dominated throughput (~1 RTT per chunk). The hot path
    # is therefore optimistic: apply up to ``emit_batch`` chunks without
    # syncing, then fetch ALL their packed stats in one transfer and emit.
    # If any chunk overflowed, rewind to the pre-batch state snapshot and
    # replay chunk-by-chunk through the growing path (rare; functional
    # state makes the rewind exact).

    def _flush_pending(self):
        if not self._pending:
            return
        import numpy as np
        stats = self.stats
        packed = np.asarray(jnp.stack([p[2] for p in self._pending]))
        if not packed[:, :4].any():
            for (side, chunk, _, big), row in zip(self._pending, packed):
                n_units = int(row[4])
                for lo in range(0, n_units, self.out_capacity // 2):
                    stats.chunks_out += 1
                    yield self._gather(big, jnp.int64(lo))
        else:
            # overflow inside the batch: rewind and replay with growth
            self.state = self._rewind_state
            for side, chunk, _, _ in self._pending:
                big = self._apply_growing(side, chunk)
                n_units = int(self._count_units(big))
                for lo in range(0, n_units, self.out_capacity // 2):
                    stats.chunks_out += 1
                    yield self._gather(big, jnp.int64(lo))
        self._pending.clear()
        self._rewind_state = None

    # -- batched single-dispatch ingest ---------------------------------------
    # A ChunkBatch arriving on either side is scanned on device in
    # sub-batches of ``batch_chunks``: one dispatch applies the chunks in
    # order, one transfer fetches all their packed stats — the unstack-
    # and-loop default paid K dispatches + K syncs per batch.

    def _consume_batch(self, side: str, batch):
        if self.null_aware_anti and side == "right":
            self._reject_null_build_keys(flatten_shards(batch.chunk))
        if self._evicted:
            hits = self._evicted_hits(side, flatten_shards(batch.chunk))
            if hits:
                self._fault_in(hits)
        for lo in range(0, batch.num_chunks, self.batch_chunks):
            sub = jax.tree_util.tree_map(
                lambda x: x[lo:lo + self.batch_chunks], batch.chunk)
            yield from self._apply_subbatch(side, sub)

    def _apply_subbatch(self, side: str, sub_chunk):
        stats = self.stats
        k = sub_chunk.ops.shape[0]
        steps = self._lru_clock.advance(k)    # None without an LRU budget
        rewind = self.state
        new_state, packed, bigs = self._apply_batch[side](
            self.state, sub_chunk, steps)
        self.state = new_state
        rows = np.asarray(packed)             # ONE transfer for k chunks
        if not rows[:, :4].any():
            for kk in range(k):
                n_units = int(rows[kk, 4])
                for lo in range(0, n_units, self.out_capacity // 2):
                    stats.chunks_out += 1
                    yield self._gather_at(bigs, jnp.int32(kk),
                                          jnp.int64(lo))
        else:
            # overflow inside the scanned sub-batch: rewind and replay
            # chunk-by-chunk through the growing path (functional state
            # makes the rewind exact, as in the optimistic path above)
            self.state = rewind
            for kk in range(k):
                ch = jax.tree_util.tree_map(lambda x: x[kk], sub_chunk)
                big = self._apply_growing(side, ch)
                n_units = int(self._count_units(big))
                for lo in range(0, n_units, self.out_capacity // 2):
                    stats.chunks_out += 1
                    yield self._gather(big, jnp.int64(lo))

    async def execute(self):
        from .metrics import barrier_timer
        stats = self.stats
        self._pending: list = []
        self._rewind_state = None
        async for ev in barrier_align(self.left, self.right, batched=True):
            kind = ev[0]
            if kind == "batch":
                _, side, batch = ev
                stats.batches_in += 1
                stats.batch_chunks_in += batch.num_chunks
                stats.capacity_rows_in += (batch.num_chunks
                                           * batch.chunk_capacity)
                # scanned batches and the optimistic per-chunk window must
                # not interleave rewinds — flush pending output first
                for out in self._flush_pending():
                    yield out
                for out in self._consume_batch(side, batch):
                    yield out
            elif kind == "chunk":
                _, side, chunk = ev
                stats.chunks_in += 1
                stats.capacity_rows_in += chunk.capacity
                if self.null_aware_anti and side == "right":
                    self._reject_null_build_keys(chunk)
                if self._evicted:
                    hits = self._evicted_hits(side, chunk)
                    if hits:
                        # flush the optimistic batch FIRST: fault-in
                        # replays mutate state, and a later rewind of the
                        # batch must not lose them
                        for out in self._flush_pending():
                            yield out
                        self._fault_in(hits)
                if self._rewind_state is None:
                    self._rewind_state = self.state
                new_state, big = self._apply[side](self.state, chunk,
                                                   self._lru())
                self.state = new_state
                self._pending.append(
                    (side, chunk, self._pack_stats(new_state, big), big))
                if len(self._pending) >= self.emit_batch:
                    for out in self._flush_pending():
                        yield out
            elif kind == "barrier":
                barrier = ev[1]
                for out in self._flush_pending():
                    yield out
                with barrier_timer(stats, self.identity, barrier.epoch.curr):
                    self._check_flags()
                    if barrier.checkpoint:
                        cleaned = self._apply_pending_clean()
                        self._checkpoint(barrier.epoch.curr)
                        if self.hbm_key_budget is not None:
                            cleaned |= self._evict_cold()
                        if cleaned:
                            self.state = self._compact(self.state)
                yield barrier
                if barrier.is_stop():
                    return
            elif kind == "watermark":
                _, side, wm = ev
                stats.watermarks += 1
                for cs, cc, ws, wc, lag in self.interval_clean:
                    if ws == side and wc == wm.col_idx:
                        key = (cs, cc)
                        thr = wm.value - lag
                        if (key not in self._pending_clean
                                or thr > self._pending_clean[key]):
                            self._pending_clean[key] = thr
                # forward with the column index remapped into the output schema
                out_idx = self._map_watermark_col(side, wm.col_idx)
                if out_idx is not None:
                    # pending join output must not be overtaken by the
                    # watermark — downstream EOWC operators would finalize
                    # windows those buffered rows still belong to
                    for out in self._flush_pending():
                        yield out
                    yield wm.__class__(out_idx, wm.value)

    def _reject_null_build_keys(self, chunk: StreamChunk) -> None:
        """NULL-aware anti join (NOT IN): a NULL subquery value makes PG
        return zero rows for the WHOLE view, which incrementally means
        retracting everything already emitted — unsupported; fail with an
        actionable message instead of diverging. One host sync per
        build-side chunk, only on NOT IN plans."""
        keyed = chunk.vis
        for i in self.core.right_keys:
            keyed = keyed & chunk.columns[i].mask
        if bool(jnp.any(chunk.vis & ~keyed)):
            raise RuntimeError(
                "NULL value in NOT IN (SELECT ...) subquery: PostgreSQL "
                "semantics would drop every row of the view, which a "
                "streaming anti join cannot express incrementally — "
                "filter NULLs in the subquery (WHERE col IS NOT NULL) "
                "or use NOT EXISTS")

    # -- eviction / fault-in ---------------------------------------------------

    def _evict_cold(self) -> bool:
        """Evict the coldest live keys' buckets from BOTH arenas down to
        3/4 of the budget (their durable rows were just written by this
        barrier's checkpoint). Returns True if anything was evicted (the
        caller compacts to reclaim the key slots)."""
        # ONE packed fetch covers the budget gate AND the plan: the evict
        # plan's packed already carries [n_evict_l, n_evict_r, n_live_l,
        # n_live_r] (ops/join_state.join_evict_plan), so the old
        # two-round-trip cadence — a live-count gate fetch, then the plan
        # fetch — coalesces into a single device→host transfer per
        # checkpoint. Under budget the plan's sort is wasted DEVICE work
        # (async-dispatched, off the critical path); the host sync it
        # replaces was on it.
        keep = max(self.hbm_key_budget * 3 // 4, 1)
        mask_l, mask_r, packed = self._evict_plan(self.state, keep)
        nel, ner, nl, nr = (int(x) for x in fetch(packed[:4]))
        if max(nl, nr) <= self.hbm_key_budget:
            return False
        if nel == 0 and ner == 0:
            return False
        for side, mask in (("left", mask_l), ("right", mask_r)):
            st = getattr(self.state, side)
            nm = np.asarray(mask)
            idx = np.nonzero(nm)[0]
            if not len(idx):
                continue
            key_np = [np.asarray(kd)[idx] for kd in st.ht.key_data]
            for row in zip(*key_np):
                self._evicted.add(self._pykey(row))
        self.state = self._apply_evict(self.state, mask_l, mask_r)
        return True

    def _evicted_hits(self, side: str, chunk: StreamChunk) -> list:
        """Evicted join keys mentioned by this chunk (host sync; paid only
        while evicted keys exist)."""
        key_idx = (self.core.left_keys if side == "left"
                   else self.core.right_keys)
        vis = np.asarray(chunk.vis)
        datas = [np.asarray(chunk.columns[i].data) for i in key_idx]
        ok = vis.copy()
        for i in key_idx:
            ok &= np.asarray(chunk.columns[i].mask)
        present = set(zip(*(d[ok] for d in datas))) if datas else set()
        return [k for k in (self._pykey(p) for p in present)
                if k in self._evicted]

    def _fault_in(self, keys: list) -> None:
        """Restore the given keys' rows on BOTH sides from the cold tier:
        prefix-scan each state table by join key (pks are join-key-
        prefixed) and replay through the insert path with emission
        discarded — degrees rebuild exactly, the same way recovery does."""
        nk = len(self.core.left_keys)
        for k in keys:
            self._evicted.discard(k)
        for side in ("left", "right"):
            table = self.state_tables[side]
            schema = (self.core.left_schema if side == "left"
                      else self.core.right_schema)
            rows = []
            for k in keys:
                rows.extend(table.scan_prefix(list(k), nk))
            # watermark state cleaning already retired rows below the
            # applied thresholds on DEVICE; an evicted key's durable rows
            # missed that — drop them here (and delete them durably)
            # instead of resurrecting expired state
            for (cs, cc), thr in self._applied_clean.items():
                if cs != side or not rows:
                    continue
                expired = [r for r in rows
                           if r[cc] is not None and r[cc] < thr]
                if expired:
                    for r in expired:
                        table.delete(r)
                    rows = [r for r in rows
                            if r[cc] is None or r[cc] >= thr]
            bs = 1024
            for i in range(0, len(rows), bs):
                ch = physical_chunk(schema, rows[i: i + bs], bs)
                big = self._apply_growing(side, ch)
                del big                      # outputs were emitted long ago

    def _apply_pending_clean(self) -> bool:
        """Free rows below the pending watermark thresholds (mark dead +
        tombstone; deletes persist via the checkpoint that follows)."""
        if not self._pending_clean:
            return False
        for (side, col), threshold in self._pending_clean.items():
            st = getattr(self.state, side)
            st = self._clean_side(st, col, jnp.asarray(threshold))
            self.state = self.state.replace(**{side: st})
            # evicted keys' durable rows are NOT on device: remember the
            # high-water threshold so fault-in drops (and durably deletes)
            # expired rows instead of resurrecting them
            prev = self._applied_clean.get((side, col))
            if prev is None or threshold > prev:
                self._applied_clean[(side, col)] = threshold
            # _applied_clean is process-local: durably retire the evicted
            # keys' expired rows NOW (staged; commits with the next
            # checkpoint, same atomicity as the device cleaning) so a
            # restart cannot resurrect them
            if self._evicted and self.state_tables.get(side) is not None:
                table = self.state_tables[side]
                nk = len(self.core.left_keys)
                for k in list(self._evicted):
                    for r in table.scan_prefix(list(k), nk):
                        if r[col] is not None and r[col] < threshold:
                            table.delete(r)
        self._pending_clean.clear()
        return True

    def _map_watermark_col(self, side: str, col_idx: int) -> Optional[int]:
        sa = self.core.join_type.semi_anti_side
        if sa is not None:
            return col_idx if sa == side else None
        return col_idx if side == "left" else col_idx + len(self.core.left_schema)

    def _check_flags(self) -> None:
        for side in ("left", "right"):
            st: JoinSideState = getattr(self.state, side)
            if bool(st.ht_overflow) or bool(st.lane_overflow):
                raise RuntimeError(
                    f"{self.identity}: {side} join state overflow escaped "
                    f"growth (key_capacity={self.core.capacity}, "
                    f"bucket_width={self.core.W})")
            if self.strict and bool(st.inconsistent):
                raise RuntimeError(
                    f"{self.identity}: {side} saw delete of an absent row")

    # -- persistence -----------------------------------------------------------

    def _checkpoint(self, epoch: int) -> None:
        for side in ("left", "right"):
            table = self.state_tables[side]
            if table is None:
                continue
            st: JoinSideState = getattr(self.state, side)
            dirty = np.asarray(st.ckpt_dirty)
            slots, lanes = np.nonzero(dirty)
            if len(slots):
                occ = np.asarray(st.occupied)
                tomb = np.asarray(st.tomb)
                datas = [np.asarray(d) for d in st.row_data]
                masks = [np.asarray(m) for m in st.row_mask]
                from ..native import codec as _native_codec
                codec = _native_codec()
                if codec is not None:
                    # batch path: flatten (slot, lane) → row index and
                    # encode the whole dirty delta in one native call;
                    # stage_encoded applies deletes before inserts, the
                    # same-pk update ordering rule below
                    width = occ.shape[1]
                    flat = slots * width + lanes
                    fdatas = [d.reshape(-1) for d in datas]
                    fmasks = [m.reshape(-1) for m in masks]
                    occ_f = occ.reshape(-1)
                    tomb_f = tomb.reshape(-1)
                    del_idx = flat[tomb_f[flat] & ~occ_f[flat]]
                    ins_idx = flat[occ_f[flat]]
                    types = table.schema.types
                    pk = table.pk_indices
                    pk_d = [fdatas[i] for i in pk]
                    pk_m = [fmasks[i] for i in pk]
                    pk_t = [types[i] for i in pk]
                    table.stage_encoded(
                        dict(zip(codec.encode_keys(pk_d, pk_m, pk_t,
                                                   ins_idx),
                                 codec.encode_value_rows(
                                     fdatas, fmasks, types, ins_idx))),
                        codec.encode_keys(pk_d, pk_m, pk_t, del_idx))
                    table.commit(epoch)
                    continue

                def row_at(s, l):
                    return tuple(
                        datas[c][s, l].item() if masks[c][s, l] else None
                        for c in range(len(datas))
                    )

                # deletes strictly before inserts: a same-pk update lands in
                # two different lanes and scan order must not let the delete
                # clobber the freshly upserted row
                for s, l in zip(slots, lanes):
                    if tomb[s, l] and not occ[s, l]:
                        table.delete(row_at(s, l))
                for s, l in zip(slots, lanes):
                    if occ[s, l]:
                        table.insert(row_at(s, l))
                table.commit(epoch)
        self.state = self._clear_ckpt(self.state)

    def _load_from_state_tables(self) -> None:
        """Recovery: replay both sides' committed rows through the insert
        path (left first, then right) — degrees rebuild exactly; outputs are
        discarded. Under an ``hbm_key_budget`` only the first ``budget``
        keys load hot; the rest stay in the cold tier and fault in on
        mention (keys are chosen jointly across the two sides — a key is
        hot or cold on BOTH, the degree-coherence invariant)."""
        cold_keys: Optional[set] = None
        if self.hbm_key_budget is not None:
            side_rows = {}
            seen: list = []
            seen_set: set = set()
            for side in ("left", "right"):
                table = self.state_tables[side]
                rows = list(table.scan_all()) if table is not None else []
                if rows and self.load_shard is not None:
                    key_idx = (self.core.left_keys if side == "left"
                               else self.core.right_keys)
                    schema = (self.core.left_schema if side == "left"
                              else self.core.right_schema)
                    rows = self._filter_shard(rows, key_idx, schema)
                side_rows[side] = rows
                key_idx = (self.core.left_keys if side == "left"
                           else self.core.right_keys)
                for r in rows:
                    kv = tuple(r[i] for i in key_idx)
                    if any(v is None for v in kv):
                        continue                   # null keys always hot
                    k = self._pykey(kv)
                    if k not in seen_set:
                        seen_set.add(k)
                        seen.append(k)
            if len(seen) > self.hbm_key_budget:
                cold_keys = set(seen[self.hbm_key_budget:])
                self._evicted |= cold_keys
        for side in ("left", "right"):
            table = self.state_tables[side]
            if table is None:
                continue
            schema = (self.core.left_schema if side == "left"
                      else self.core.right_schema)
            key_idx = (self.core.left_keys if side == "left"
                       else self.core.right_keys)
            if cold_keys is not None:
                rows = [
                    r for r in side_rows[side]
                    if any(r[i] is None for i in key_idx)
                    or self._pykey(tuple(r[i] for i in key_idx))
                    not in cold_keys]
            elif self.hbm_key_budget is not None:
                rows = side_rows[side]      # already scanned + shard-filtered
            else:
                rows = list(table.scan_all())
                if rows and self.load_shard is not None:
                    rows = self._filter_shard(rows, key_idx, schema)
            bs = 1024
            for i in range(0, len(rows), bs):
                chunk = physical_chunk(schema, rows[i: i + bs], bs)
                self._apply_growing(side, chunk)
        self.state = self._clear_ckpt(self.state)

    def _filter_shard(self, rows: list, key_idx, schema) -> list:
        """Keep rows whose join key hashes to this actor's shard — the same
        device hash the dispatcher routes live rows with, so reload
        placement always matches routing, for ANY shard count."""
        import jax.numpy as jnp
        from ..common.chunk import Column
        from ..common.hashing import vnode_of, vnode_to_shard
        idx, n_shards = self.load_shard
        out = []
        bs = 1024
        for i in range(0, len(rows), bs):
            batch = rows[i:i + bs]
            cols = []
            for c in key_idx:
                vals = [r[c] for r in batch]
                data = np.array([v if v is not None else 0 for v in vals],
                                dtype=schema[c].type.np_dtype)
                mask = np.array([v is not None for v in vals])
                cols.append(Column(jnp.asarray(data), jnp.asarray(mask)))
            shard = np.asarray(vnode_to_shard(vnode_of(cols), n_shards))
            out.extend(r for r, s in zip(batch, shard) if int(s) == idx)
        return out


def _pack_stats_of(state: JoinState, big) -> jax.Array:
    """Every host-read scalar of one applied chunk in ONE vector:
    [l.lane_ovf, l.ht_ovf, r.lane_ovf, r.ht_ovf, n_units]."""
    return jnp.stack([
        state.left.lane_overflow.astype(jnp.int64),
        state.left.ht_overflow.astype(jnp.int64),
        state.right.lane_overflow.astype(jnp.int64),
        state.right.ht_overflow.astype(jnp.int64),
        count_units(big),
    ])


def _clear_ckpt_marks(state: JoinState) -> JoinState:
    def clear(st: JoinSideState) -> JoinSideState:
        return st.replace(
            ckpt_dirty=jnp.zeros_like(st.ckpt_dirty),
            tomb=jnp.zeros_like(st.tomb),
        )
    return state.replace(left=clear(state.left), right=clear(state.right))


