"""N-input barrier alignment.

Counterpart of the reference's ``barrier_align`` stream combinator and the
MergeExecutor's SelectReceivers fan-in
(reference: src/stream/src/executor/barrier_align.rs:43,
src/stream/src/executor/merge.rs:36,114-172): read all inputs concurrently;
once a barrier arrives on one input, stop polling that input until every
other input's barrier for the same epoch arrives, then emit one aligned
barrier. This is what makes a barrier a consistent cut across a multi-input
operator.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Hashable, Mapping

from ..common.chunk import ChunkBatch, StreamChunk
from .executor import Executor
from .message import Barrier, Watermark


async def align_streams(inputs: Mapping[Hashable, Executor],
                        batched: bool = False) -> AsyncIterator[tuple]:
    """Align barriers across named inputs.

    Yields ("chunk", name, chunk) / ("watermark", name, wm) /
    ("barrier", barrier) events; terminates after a stop barrier or when all
    inputs are exhausted. An input holding a barrier is not polled again
    until the barrier is resolved (the alignment backpressure).

    ``batched=True``: a ChunkBatch arriving on any input is forwarded
    whole as a ("batch", name, batch) event for consumers with a
    single-dispatch batched step (stream/hash_join.py); the default
    unstacks so batches are never silently dropped."""
    names = list(inputs)
    its = {s: inputs[s].execute().__aiter__() for s in names}
    pending: dict = {}
    held_barrier: dict = {}
    finished: set = set()

    try:
        while len(finished) < len(names):
            for s in names:
                if s not in pending and s not in held_barrier and s not in finished:
                    pending[s] = asyncio.ensure_future(its[s].__anext__())
            if not pending:
                break
            done, _ = await asyncio.wait(
                pending.values(), return_when=asyncio.FIRST_COMPLETED)
            for s in list(pending):
                task = pending[s]
                if task not in done:
                    continue
                del pending[s]
                try:
                    msg = task.result()
                except StopAsyncIteration:
                    finished.add(s)
                    continue
                if isinstance(msg, Barrier):
                    held_barrier[s] = msg
                elif isinstance(msg, StreamChunk):
                    yield ("chunk", s, msg)
                elif isinstance(msg, ChunkBatch):
                    if batched:
                        yield ("batch", s, msg)
                    else:
                        # consumer has no batched step; unstack so batches
                        # from upstream are never silently dropped
                        for i in range(msg.num_chunks):
                            yield ("chunk", s, msg.at(i))
                elif isinstance(msg, Watermark):
                    yield ("watermark", s, msg)
            live = [s for s in names if s not in finished]
            if live and all(s in held_barrier for s in live):
                barriers = [held_barrier[s] for s in live]
                epochs = {b.epoch.curr for b in barriers}
                if len(epochs) != 1:
                    raise AssertionError(
                        f"barrier misalignment: epochs {sorted(epochs)}")
                held_barrier.clear()
                yield ("barrier", barriers[0])
                if barriers[0].is_stop():
                    return
    finally:
        for task in pending.values():
            task.cancel()


async def barrier_align(left: Executor, right: Executor,
                        batched: bool = False) -> AsyncIterator[tuple]:
    """Two-input alignment with "left"/"right" naming (join-style callers)."""
    async for ev in align_streams({"left": left, "right": right},
                                  batched=batched):
        yield ev
