"""Two-input barrier alignment.

Counterpart of the reference's ``barrier_align`` stream combinator
(reference: src/stream/src/executor/barrier_align.rs:43): read both inputs
concurrently; once a barrier arrives on one side, stop polling that side
until the other side's barrier for the same epoch arrives, then emit one
aligned barrier. This is what makes a barrier a consistent cut across a
binary operator.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from ..common.chunk import StreamChunk
from .executor import Executor
from .message import Barrier, Watermark


async def barrier_align(left: Executor, right: Executor) -> AsyncIterator[tuple]:
    """Yields ("chunk", side, chunk) / ("watermark", side, wm) /
    ("barrier", barrier) events; terminates after a stop barrier or when both
    inputs are exhausted."""
    its = {"left": left.execute().__aiter__(),
           "right": right.execute().__aiter__()}
    pending: dict[str, asyncio.Task] = {}
    held_barrier: dict[str, Barrier] = {}
    finished: set[str] = set()

    try:
        while len(finished) < 2:
            for s in ("left", "right"):
                if s not in pending and s not in held_barrier and s not in finished:
                    pending[s] = asyncio.ensure_future(its[s].__anext__())
            if not pending:
                break
            done, _ = await asyncio.wait(
                pending.values(), return_when=asyncio.FIRST_COMPLETED)
            for s in list(pending):
                task = pending[s]
                if task not in done:
                    continue
                del pending[s]
                try:
                    msg = task.result()
                except StopAsyncIteration:
                    finished.add(s)
                    continue
                if isinstance(msg, Barrier):
                    held_barrier[s] = msg
                elif isinstance(msg, StreamChunk):
                    yield ("chunk", s, msg)
                elif isinstance(msg, Watermark):
                    yield ("watermark", s, msg)
            if len(held_barrier) == 2:
                bl, br = held_barrier["left"], held_barrier["right"]
                if bl.epoch.curr != br.epoch.curr:
                    raise AssertionError(
                        f"barrier misalignment: left epoch {bl.epoch.curr} "
                        f"!= right epoch {br.epoch.curr}")
                held_barrier.clear()
                yield ("barrier", bl)
                if bl.is_stop():
                    return
            elif held_barrier and finished - held_barrier.keys():
                # one side ended without a stop barrier; flush the other's
                # barrier so the operator can still make progress
                (s, b), = held_barrier.items()
                held_barrier.clear()
                yield ("barrier", b)
                if b.is_stop():
                    return
    finally:
        for task in pending.values():
            task.cancel()
