"""Cross-worker fragment execution: exchange endpoints + fragment jobs.

This is the executor-level half of the remote exchange subsystem (the
socket half lives in rpc/exchange.py). One streaming job's fragment graph
spans worker PROCESSES: each worker hosts some of the job's fragments as
a ``FragmentJob``, whose actors drain their fragment subtree and dispatch
into exchange edges — worker-local edges ride ``PermitChannel``s from the
in-process fabric, cross-worker edges ride ``ExchangeOutput``/
``ExchangeInput`` pairs over the multiplexed peer sockets with the SAME
credit semantics (data consumes permits released on consumption, barriers
and watermarks always pass). The consuming side of every edge is a
``MergeExecutor`` fan-in with barrier alignment, so two-phase checkpoints
hold end-to-end across processes: a worker acks a barrier only after
every local actor of the job has seen it flow through, and the session
commits only after every participating worker acked (reference:
dispatch.rs + merge.rs + exchange/permit.rs + stream_service.rs, now
composed ACROSS compute nodes instead of inside one).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ..common.chunk import StreamChunk
from ..common.types import Schema
from ..rpc.exchange import EdgeStats, ExchangePeerClient, PeerLost
from ..rpc.wire import message_from_wire, message_to_wire, write_frame
from .dispatch import (
    BroadcastDispatcher, HashDispatcher, MergeExecutor, MsgQueue,
    SimpleDispatcher, open_channel,
)
from .message import Barrier, Message


class ExchangeInput:
    """Consuming end of a cross-worker edge: channel-shaped (``recv``)
    so ``MergeExecutor`` treats it exactly like a local ``PermitChannel``
    end. Frames decode lazily and the permit ack goes back over the peer
    socket only when the consumer TAKES a chunk — end-to-end
    consumption-based credit (reference: permit.rs).

    Faulty-delivery hardening (ISSUE 9): frames carry per-channel
    sequence numbers. A duplicate (seq already delivered) is dropped
    WITHOUT an ack — the producer consumed one permit for it, so acking
    twice would inflate the edge's credit. An out-of-order frame (a
    chaos-delayed sibling overtook it) is held and released in seq
    order, so barrier position in the stream — the exactly-once cut —
    survives reordering networks."""

    def __init__(self, chan: int, schema: Schema, capacity: int,
                 stats: EdgeStats, job: str, link: str = ""):
        from ..rpc.exchange import SeqReorderBuffer
        self.chan = chan
        self.schema = schema
        self.capacity = capacity
        self.stats = stats
        self.job = job
        self.link = link              # fault-plane name of the ACK edge
        self._q = MsgQueue()
        self._seqbuf = SeqReorderBuffer()
        self._ack_seq = 0             # per-chan ack sequence (producer
        #                               dedups duplicated acks by it)

    def feed_wire(self, wire_msg: dict, writer, wlock,
                  seq: Optional[int] = None) -> None:
        """Called by the peer-connection read loop for every exg_data
        frame on this channel (the writer is the SAME connection, used to
        send consumption acks back). Dedup + re-order by ``seq`` HERE,
        before the frame enters the delivery queue, so ``recv`` only ever
        sees each frame once, in send order (a dropped duplicate is NOT
        acked — the producer consumed one permit for it)."""
        delivered = self._seqbuf.feed(seq, ("wire", wire_msg, writer,
                                            wlock))
        self.stats.dup_frames = self._seqbuf.dup_frames
        self.stats.reordered = self._seqbuf.reordered
        for item in delivered:
            self._q.put_nowait(item)

    def put_local(self, msg: Optional[Message]) -> None:
        """Locally injected message (stop barriers at drop; None closes)."""
        self._q.put_nowait(("local", msg, None, None))

    def peer_lost(self) -> None:
        """The producing worker's connection dropped: fail the consumer
        instead of starving it (the merge would otherwise wait forever
        for a barrier that can never arrive)."""
        self._q.put_nowait(("peer_lost", None, None, None))

    def qsize(self) -> int:
        return self._q.qsize()

    async def recv(self) -> Optional[Message]:
        kind, payload, writer, wlock = await self._q.get()
        if kind == "peer_lost":
            raise PeerLost(
                f"exchange edge {self.stats.edge} lost its producer")
        if kind == "local":
            return payload
        msg = message_from_wire(payload, self.schema, self.capacity)
        if isinstance(msg, StreamChunk):
            self.stats.chunks += 1
            ack = {"type": "exg_ack", "chan": self.chan,
                   "seq": self._ack_seq}
            self._ack_seq += 1
            try:
                await write_frame(writer, ack, wlock,
                                  link=self.link or None)
            except (ConnectionError, OSError):
                pass      # producer gone; its permits die with it
        elif isinstance(msg, Barrier):
            # per-edge barrier-epoch monotonicity: the auditor asserts
            # regressions == 0 after every chaos run
            self.stats.saw_barrier(msg.epoch.curr)
        return msg


class ExchangeOutput:
    """Producing end of a cross-worker edge: channel-shaped (``send``) so
    every dispatcher writes to it exactly like a local channel. Data
    consumes a peer-client permit before the frame is written (blocking
    this actor when the consumer is behind); control always passes."""

    def __init__(self, client: ExchangePeerClient, chan: int,
                 schema: Schema, stats: EdgeStats):
        self.client = client
        self.chan = chan
        self.schema = schema
        self.stats = stats

    async def send(self, msg: Message) -> None:
        is_data = isinstance(msg, StreamChunk)
        n = await self.client.send(self.chan, message_to_wire(msg, self.schema),
                                   is_data, self.stats)
        self.stats.bytes += n
        if is_data:
            self.stats.chunks += 1
        elif isinstance(msg, Barrier):
            self.stats.saw_barrier(msg.epoch.curr)


class FragmentJob:
    """The fragments of ONE spanning job hosted by THIS worker process.
    Job-shaped for the WorkerHost (wait_barrier / stop / sources /
    pipeline / table), so barrier conduction, drop, scan, and stats treat
    it like a whole worker-hosted job; completion of an epoch means EVERY
    local fragment actor forwarded that epoch's barrier (state staged),
    which is what the worker's ``barrier_complete`` ack asserts."""

    spanning = True

    def __init__(self, name: str):
        self.name = name
        self.sources: list = []               # local source-feed queues
        self.pipeline = None                  # root MaterializeExecutor
        self.table = None
        self.exchange_inputs: List[ExchangeInput] = []
        self.exchange_outputs: List[ExchangeOutput] = []
        self.local_chan_ids: List[int] = []
        # per-fragment executor roots + the root actor's owned vnode
        # range: the live-migration export walks fragment_execs for
        # state tables, and scans of a vnode-distributed root MV filter
        # to root_vnodes (meta/rescale.py, worker/host.py)
        self.fragment_execs: Dict[int, object] = {}
        self.root_vnodes: Optional[tuple] = None
        self._actors: list = []               # (fragment) coroutine factories
        self._tasks: List[asyncio.Task] = []
        self._events: Dict[int, asyncio.Event] = {}
        self._counts: Dict[int, int] = {}
        self._failure: Optional[BaseException] = None

    def add_actor(self, run) -> None:
        self._actors.append(run)

    def start(self) -> None:
        for run in self._actors:
            self._tasks.append(asyncio.ensure_future(self._guard(run)))

    async def _guard(self, run) -> None:
        try:
            await run()
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 - surfaced on next await
            self._failure = self._failure or e
            for ev in self._events.values():
                ev.set()
            raise

    def _mark(self, epoch: int) -> None:
        n = self._counts.get(epoch, 0) + 1
        self._counts[epoch] = n
        if n >= len(self._actors):
            self._events.setdefault(epoch, asyncio.Event()).set()
            self._counts.pop(epoch, None)

    async def wait_barrier(self, epoch: int) -> None:
        if self._failure is not None:
            raise self._failure
        ev = self._events.setdefault(epoch, asyncio.Event())
        await ev.wait()
        self._events.pop(epoch, None)
        if self._failure is not None:
            raise self._failure

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks.clear()


def _fragment_actor(job: FragmentJob, ex, dispatcher):
    """One fragment actor: drain the fragment subtree, forward every
    message into the output edge(s), and mark barrier passage — AFTER the
    dispatch, so the barrier is on the wire (socket order: all of the
    epoch's data precedes it) before this worker can ack the epoch."""

    async def run() -> None:
        async for msg in ex.execute():
            if dispatcher is not None:
                await dispatcher.dispatch(msg)
            if isinstance(msg, Barrier):
                job._mark(msg.epoch.curr)
                if msg.is_stop():
                    return
    return run


def build_fragments(host, req: dict, store) -> FragmentJob:
    """Build this worker's share of a spanning job from a
    ``create_fragments`` request (the worker half of the meta scheduler's
    deployment; reference: stream_service.rs build_actors). Each
    fragment spec carries its serialized subtree (PExchange cut leaves),
    its input edges (channel per upstream actor), and its output edge
    (dispatch kind + per-target channels naming remote peers)."""
    from ..frontend.build import BuildConfig

    name = req["name"]
    permits = int(req.get("permits", 32))
    cfg = BuildConfig(**req.get("config", {}))
    job = FragmentJob(name)
    state_table_ids: list[int] = []

    try:
        _build_fragments_into(host, req, store, job, state_table_ids,
                              permits, cfg)
    except BaseException:
        # a half-built deployment must leave NO endpoint registrations
        # behind: retried deployments allocate fresh channel ids, so a
        # leaked registration would never be reclaimed
        for inp in job.exchange_inputs:
            if host.exchange_inputs.get(inp.chan) is inp:
                host.exchange_inputs.pop(inp.chan, None)
        for out in job.exchange_outputs:
            out.client.unregister(out.chan)
        for chan in job.local_chan_ids:
            host.span_chans.pop(chan, None)
        raise
    job.state_table_ids = state_table_ids  # type: ignore[attr-defined]
    return job


def _build_fragments_into(host, req: dict, store, job: FragmentJob,
                          state_table_ids: list, permits: int, cfg) -> None:
    from ..frontend.build import BuildContext, build_plan
    from ..frontend.plan_json import plan_from_json
    from ..frontend.planner import PExchange, PSource
    from ..storage.state_table import StateTable
    from ..stream.materialize import MaterializeExecutor

    name = req["name"]
    for spec in req["fragments"]:
        plan = plan_from_json(spec["plan"], host.catalog)
        ids = iter(range(spec["id_start"],
                         spec["id_start"] + req["id_stride"]))

        def next_table_id(_ids=ids) -> int:
            return next(_ids)

        exchange_i = [0]
        shard_i = [0]
        inputs = spec["inputs"]

        def factory(leaf, _spec=spec, _inputs=inputs, _exi=exchange_i,
                    _shi=shard_i, _ids=next_table_id):
            if isinstance(leaf, PSource):
                shard = _spec["shard_base"] + _shi[0]
                _shi[0] += 1
                ex = host._source_leaf(leaf, name, store, _ids,
                                       shard_id=shard)
                inner = ex
                from ..frontend.runtime import QueueSource
                while not isinstance(inner, QueueSource):
                    inner = getattr(inner, "inner", None) or inner.input
                job.sources.append(inner)
                return ex
            if isinstance(leaf, PExchange):
                edge_in = _inputs[_exi[0]]
                _exi[0] += 1
                chans = []
                for c in edge_in["chans"]:
                    if c["from_worker"] == host.worker_id:
                        ch = host.span_chan(c["chan"], permits)
                        job.local_chan_ids.append(c["chan"])
                        chans.append(ch)
                    else:
                        stats = EdgeStats(c["edge"], "in", c["from_worker"])
                        inp = ExchangeInput(
                            c["chan"], leaf.schema, host.chunk_capacity,
                            stats, name,
                            link=(f"w{host.worker_id}"
                                  f"->w{c['from_worker']}"))
                        host.exchange_inputs[c["chan"]] = inp
                        job.exchange_inputs.append(inp)
                        chans.append(inp)
                return MergeExecutor(chans, leaf.schema)
            raise ValueError(
                f"cannot build span leaf {type(leaf).__name__}")

        vnodes = spec.get("vnodes")
        ctx = BuildContext(store, next_table_id, factory, cfg, durable=True,
                           vnode_range=(tuple(vnodes) if vnodes else None))
        pipeline = build_plan(plan, ctx)
        state_table_ids.extend(ctx.state_table_ids)
        if ctx.actors:
            raise ValueError(
                "span fragments must build single-actor subtrees "
                "(fragment_parallelism belongs to the scheduler here)")

        out = spec.get("output")
        if spec["is_root"]:
            mat = MaterializeExecutor(
                pipeline, StateTable(store, req["mv_table_id"],
                                     plan.schema, list(plan.pk)))
            job.pipeline = mat
            job.table = mat.table
            if vnodes:
                job.root_vnodes = tuple(vnodes)
            job.fragment_execs[spec["fid"]] = mat
            job.add_actor(_fragment_actor(job, mat, None))
        else:
            outs = []
            for t in out["targets"]:
                if t["worker"] == host.worker_id:
                    ch = host.span_chan(t["chan"], permits)
                    job.local_chan_ids.append(t["chan"])
                    outs.append(ch)
                else:
                    client = host.peer_pool.get(t["host"], t["port"],
                                                peer_worker=t["worker"])
                    client.register(t["chan"], permits)
                    stats = EdgeStats(t["edge"], "out", t["worker"])
                    o = ExchangeOutput(client, t["chan"], plan.schema, stats)
                    job.exchange_outputs.append(o)
                    outs.append(o)
            if out["kind"] == "hash":
                disp = HashDispatcher(outs, list(out["keys"]), plan.schema)
            elif len(outs) == 1:
                disp = SimpleDispatcher(outs[0])
            else:
                disp = BroadcastDispatcher(outs)
            job.fragment_execs[spec["fid"]] = pipeline
            job.add_actor(_fragment_actor(job, pipeline, disp))


def exchange_stats(host) -> list:
    """Per-edge counter snapshot for this worker's stats frame: every
    cross-worker edge endpoint it hosts, in both directions."""
    out = []
    for chan, inp in sorted(host.exchange_inputs.items()):
        out.append(inp.stats.snapshot(backlog=inp.qsize()))
    for job in host.jobs.values():
        for o in getattr(job, "exchange_outputs", ()):
            out.append(o.stats.snapshot())
    return out
