"""SinkExecutor + log store: exactly-once changelog delivery.

Counterpart of the reference's SinkExecutor with its LogStore decoupling
(reference: src/stream/src/executor/sink.rs:38;
src/stream/src/common/log_store/mod.rs:57-168 — LogWriter buffers the
epoch's chunks, LogReader delivers them to the external system and
*truncates* up to the delivered offset). Here both halves run in one host
loop per barrier; the log lives in a StateTable keyed (epoch, seq) so it
shares the state store's atomic epoch commit:

  on chunk      — buffer rows (host decode; sinks are host IO anyway)
  on barrier e  — append buffered rows to the log table,
                  deliver log rows up to e to the sink,
                  record (delivered_epoch, sink position) in the progress
                  table, truncate delivered log rows; all three writes
                  commit atomically with epoch e.

Exactly-once across crashes: the sink's byte/row position is persisted in
the SAME epoch commit as the log truncation. After a crash the executor
rolls the sink back to the last committed position (FileSink.truncate_to),
and undelivered log rows (still present — their truncation never
committed) are re-delivered. Delivered-but-uncommitted bytes are exactly
the truncated tail.
"""

from __future__ import annotations

from typing import Optional

from ..common.chunk import StreamChunk, chunk_to_rows
from ..common.types import INT64, Field, Schema
from ..connector.sinks import Sink
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


def log_table_schema(value_schema: Schema) -> Schema:
    """(epoch, seq, op) ⧺ row values; pk = (epoch, seq) so iteration order
    is delivery order (reference: KvLogStore key layout)."""
    head = (Field("_epoch", INT64), Field("_seq", INT64), Field("_op", INT64))
    return Schema(head + tuple(value_schema))


PROGRESS_SCHEMA = Schema((Field("_id", INT64), Field("_delivered_epoch", INT64),
                          Field("_position", INT64)))


class SinkExecutor(SingleInputExecutor):
    identity = "Sink"

    def __init__(self, input: Executor, sink: Sink,
                 log_table: StateTable, progress_table: StateTable,
                 n_visible: Optional[int] = None, recovering: bool = False):
        super().__init__(input)
        self.schema = input.schema
        self.n_visible = len(self.schema) if n_visible is None else n_visible
        self._recovering = recovering
        self.sink = sink
        self.log = log_table
        self.progress = progress_table
        # sink jobs are StreamJobs; .table is the job's "output" table —
        # for a sink that is its progress table (scanned by nothing, but
        # keeps the job protocol uniform)
        self.table = progress_table
        self._pending: list[tuple[int, tuple]] = []
        self._seq = 0
        self.delivered_epoch = 0
        self._recover()

    def _recover(self) -> None:
        row = self.progress.get_row((0,))
        if row is not None:
            self.delivered_epoch = int(row[1])
            self.sink.truncate_to(int(row[2]))
        elif self._recovering:
            # crashed before the first progress row durably committed:
            # anything already delivered is phantom output — roll the sink
            # back to empty (the committed position is 0)
            self.sink.truncate_to(0)
        # seq continues above any undelivered log rows
        seqs = [int(r[1]) for r in self.log.scan_all()]
        self._seq = max(seqs) + 1 if seqs else 0

    async def map_chunk(self, chunk: StreamChunk):
        self._pending.extend(
            chunk_to_rows(chunk, self.schema, with_ops=True, physical=True))
        yield chunk

    async def on_barrier(self, barrier: Barrier):
        epoch = barrier.epoch.curr
        for op, values in self._pending:
            self.log.insert((epoch, self._seq, int(op)) + tuple(values))
            self._seq += 1
        self._pending.clear()
        # deliver everything logged through this epoch, oldest first
        to_deliver = []
        for row in self.log.scan_all():
            if int(row[0]) <= epoch:
                to_deliver.append(row)
        if to_deliver or self.delivered_epoch < epoch:
            typed = [(int(r[2]), tuple(
                None if v is None else self.schema[i].type.to_python(v)
                for i, v in enumerate(r[3:3 + self.n_visible])))
                for r in to_deliver]
            self.sink.write_rows(typed)
            self.sink.flush()
            for r in to_deliver:
                self.log.delete(r)
            self.delivered_epoch = epoch
            old = self.progress.get_row((0,))
            new = (0, epoch, int(self.sink.position()))
            if old is not None:
                self.progress.update(old, new)
            else:
                self.progress.insert(new)
        self.log.commit(epoch)
        self.progress.commit(epoch)
        if False:  # pragma: no cover - async generator shape
            yield
