"""SinkExecutor + log store: exactly-once changelog delivery, decoupled
from the barrier path.

Counterpart of the reference's SinkExecutor with its LogStore decoupling
(reference: src/stream/src/executor/sink.rs:38;
src/stream/src/common/log_store/mod.rs:57-168 — LogWriter buffers the
epoch's chunks, LogReader delivers them to the external system and
*truncates* up to the delivered offset; sink-decouple: a dead sink
backend degrades one job instead of stalling cluster checkpointing). The
log lives in a StateTable keyed (epoch, seq) so it shares the state
store's atomic epoch commit:

  on chunk      — buffer rows (host decode; sinks are host IO anyway)
  on barrier e  — append buffered rows to the log table (ALWAYS commits
                  with epoch e — this is the barrier-path contract),
                  then ATTEMPT delivery of log rows up to e with bounded
                  retry/backoff; on success, record (delivered_epoch,
                  sink position) in the progress table and truncate the
                  delivered rows — those writes ride the SAME epoch
                  commit.

Failure containment: a delivery failure never fails the epoch. The log
keeps the undelivered rows; after ``degrade_after`` consecutive failed
epochs the job goes DEGRADED (delivery attempts pause, the log keeps
absorbing changes, health is surfaced in Session.metrics()["sinks"]).
``resume()`` (Session.resume_sink — the ALTER SINK ... RESUME shape) or
crash recovery re-arms delivery; every logged row is then delivered
exactly once. The only hard failure is the log cap
(``log_cap_rows``): unbounded log growth is refused loudly.

Exactly-once across crashes AND in-process retries: the sink's byte/row
position is persisted in the SAME epoch commit as the log truncation,
and every delivery attempt first rolls the sink back to the last
successful position (FileSink.truncate_to), so a half-delivered failed
attempt is overwritten by the retry, and after a crash undelivered log
rows (whose truncation never committed) are re-delivered on top of the
committed position.
"""

from __future__ import annotations

from typing import Optional

from ..common.chunk import StreamChunk, chunk_to_rows
from ..common.failpoint import fail_point
from ..common.types import INT64, Field, Schema
from ..connector.sinks import Sink
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


def log_table_schema(value_schema: Schema) -> Schema:
    """(epoch, seq, op) ⧺ row values; pk = (epoch, seq) so iteration order
    is delivery order (reference: KvLogStore key layout)."""
    head = (Field("_epoch", INT64), Field("_seq", INT64), Field("_op", INT64))
    return Schema(head + tuple(value_schema))


PROGRESS_SCHEMA = Schema((Field("_id", INT64), Field("_delivered_epoch", INT64),
                          Field("_position", INT64)))


class SinkExecutor(SingleInputExecutor):
    identity = "Sink"

    def __init__(self, input: Executor, sink: Sink,
                 log_table: StateTable, progress_table: StateTable,
                 n_visible: Optional[int] = None, recovering: bool = False,
                 retry_policy=None, degrade_after: int = 3,
                 log_cap_rows: int = 1_000_000):
        super().__init__(input)
        self.schema = input.schema
        self.n_visible = len(self.schema) if n_visible is None else n_visible
        self._recovering = recovering
        self.sink = sink
        self.log = log_table
        self.progress = progress_table
        if retry_policy is None:
            # single source of default numbers: the FaultConfig dataclass
            from ..common.config import FaultConfig
            retry_policy = FaultConfig().sink_retry_policy()
        self._policy = retry_policy
        self.degrade_after = max(1, int(degrade_after))
        self.log_cap_rows = int(log_cap_rows)
        # sink jobs are StreamJobs; .table is the job's "output" table —
        # for a sink that is its progress table (scanned by nothing, but
        # keeps the job protocol uniform)
        self.table = progress_table
        self._pending: list[tuple[int, tuple]] = []
        self._seq = 0
        self.delivered_epoch = 0
        #: last successful sink position (the rollback point every
        #: delivery attempt starts from)
        self._position = 0
        # health (surfaced via sink_health() → Session.metrics()["sinks"])
        self.degraded = False
        self.delivery_failures = 0
        self.consecutive_failures = 0
        self.rows_delivered = 0
        self.last_error: Optional[str] = None
        self._recover()

    def _recover(self) -> None:
        row = self.progress.get_row((0,))
        if row is not None:
            self.delivered_epoch = int(row[1])
            self._position = int(row[2])
            self.sink.truncate_to(self._position)
        elif self._recovering:
            # crashed before the first progress row durably committed:
            # anything already delivered is phantom output — roll the sink
            # back to empty (the committed position is 0)
            self.sink.truncate_to(0)
        # seq continues above any undelivered log rows
        seqs = [int(r[1]) for r in self.log.scan_all()]
        self._seq = max(seqs) + 1 if seqs else 0

    # -- delivery (off the epoch-failure path) --------------------------------

    def resume(self) -> None:
        """Re-arm delivery on a degraded sink (the ALTER SINK resume
        shape; also what a fresh executor after recovery starts as). The
        backlog drains at the next barrier."""
        self.degraded = False
        self.consecutive_failures = 0
        self.last_error = None

    def sink_health(self) -> dict:
        return {
            "degraded": self.degraded,
            "delivered_epoch": self.delivered_epoch,
            "pending_rows": len(self.log),   # O(keys), no row decode
            "delivery_failures": self.delivery_failures,
            "consecutive_failures": self.consecutive_failures,
            "rows_delivered": self.rows_delivered,
            "last_error": self.last_error,
        }

    def _deliver_once(self, typed: list) -> None:
        """One delivery attempt, idempotent under retry: roll the sink
        back to the last committed position first so a previous partial
        attempt's bytes are discarded, then write + flush."""
        fail_point("sink.deliver")
        self.sink.truncate_to(self._position)
        self.sink.write_rows(typed)
        self.sink.flush()

    def _try_deliver(self, epoch: int) -> None:
        to_deliver = [row for row in self.log.scan_all()
                      if int(row[0]) <= epoch]
        if not to_deliver and self.delivered_epoch >= epoch:
            return
        typed = [(int(r[2]), tuple(
            None if v is None else self.schema[i].type.to_python(v)
            for i, v in enumerate(r[3:3 + self.n_visible])))
            for r in to_deliver]
        try:
            self._policy.run("sink.deliver", self._deliver_once, typed)
        except Exception as e:  # noqa: BLE001 - degrade, don't fail the epoch
            self.delivery_failures += 1
            self.consecutive_failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            if self.consecutive_failures >= self.degrade_after:
                self.degraded = True
            return
        # success: truncate delivered rows + persist (epoch, position) —
        # all staged into the SAME epoch commit below
        for r in to_deliver:
            self.log.delete(r)
        self.delivered_epoch = epoch
        self._position = int(self.sink.position())
        self.rows_delivered += len(typed)
        self.consecutive_failures = 0
        self.last_error = None
        old = self.progress.get_row((0,))
        new = (0, epoch, self._position)
        if old is not None:
            self.progress.update(old, new)
        else:
            self.progress.insert(new)

    async def on_barrier(self, barrier: Barrier):
        epoch = barrier.epoch.curr
        for op, values in self._pending:
            self.log.insert((epoch, self._seq, int(op)) + tuple(values))
            self._seq += 1
        self._pending.clear()
        if not self.degraded:
            from ..common.barrier_ledger import timed_stage
            with timed_stage(epoch, "sink_deliver"):
                self._try_deliver(epoch)
        else:
            # degraded: the log absorbs changes up to the cap; bounded-log
            # backpressure is a LOUD failure, not silent truncation
            # (len() counts keys without decoding the backlog)
            n_logged = len(self.log)
            if n_logged > self.log_cap_rows:
                raise RuntimeError(
                    f"sink log exceeded log_cap_rows={self.log_cap_rows} "
                    f"({n_logged} undelivered rows) while degraded; "
                    "resume the sink or raise the cap")
        self.log.commit(epoch)
        self.progress.commit(epoch)
        if False:  # pragma: no cover - async generator shape
            yield

    async def map_chunk(self, chunk: StreamChunk):
        self._pending.extend(
            chunk_to_rows(chunk, self.schema, with_ops=True, physical=True))
        yield chunk
