"""DmlManager: the frontend↔stream rendezvous for table writes.

Counterpart of the reference's DML plumbing
(reference: src/source/src/dml_manager.rs:44 + src/source/src/table.rs:33
TableDmlHandle — the DML batch executor hands INSERT chunks to the
registered table's stream job through a channel; executor/dml.rs is the
stream-side receiver). Here the registry maps table id → writer handles;
a write fans out to every handle (a table rebuilt by reschedule registers
a fresh handle under the same id). The Session's epoch loop drains staged
chunks into the handles at tick time so DML lands inside exactly one
epoch (atomic with that epoch's barrier).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..common.chunk import StreamChunk


class TableDmlHandle:
    """One registered writer endpoint of a table's stream job."""

    def __init__(self, push: Callable[[StreamChunk], None]):
        self._push = push

    def write_chunk(self, chunk: StreamChunk) -> None:
        self._push(chunk)


class DmlManager:
    def __init__(self) -> None:
        self._handles: Dict[int, List[TableDmlHandle]] = {}
        self._staged: Dict[int, List[StreamChunk]] = {}

    def register(self, table_id: int, handle: TableDmlHandle) -> None:
        self._handles.setdefault(table_id, []).append(handle)

    def unregister_table(self, table_id: int) -> None:
        self._handles.pop(table_id, None)
        self._staged.pop(table_id, None)

    def has_staged(self) -> bool:
        return bool(self._staged)

    def stage(self, table_id: int, chunk: StreamChunk) -> None:
        """Buffer a DML chunk; it reaches the table inside the next epoch
        (reference: DML batches rendezvous with the stream at the next
        barrier boundary)."""
        if table_id not in self._handles:
            raise KeyError(f"no stream job registered for table {table_id}")
        self._staged.setdefault(table_id, []).append(chunk)

    def drain_into_epoch(self) -> int:
        """Deliver all staged chunks to their handles; returns chunks
        delivered. Called by the barrier conductor at tick time."""
        n = 0
        for table_id, chunks in self._staged.items():
            for h in self._handles.get(table_id, []):
                for c in chunks:
                    h.write_chunk(c)
                    n += 1
        self._staged.clear()
        return n
