"""Streaming message protocol: Chunk / Barrier / Watermark.

Counterpart of the reference's ``Message`` enum and ``Barrier``/``Mutation``
(reference: src/stream/src/executor/mod.rs:170-206,220-251,591,677-681). In
this design messages are host-level control objects flowing between async
executor generators; the chunks they carry are device-resident pytrees. A
barrier is purely host-side — device work is fenced by the host awaiting the
step results for the epoch before forwarding the barrier (SURVEY.md §7
"Exactly-once barrier semantics").
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Union

from ..common.chunk import StreamChunk


class MutationKind(enum.Enum):
    """Config-change commands carried by barriers (reference: Mutation enum,
    src/stream/src/executor/mod.rs:220-238)."""

    STOP = "stop"
    ADD = "add"
    UPDATE = "update"
    PAUSE = "pause"
    RESUME = "resume"
    SOURCE_CHANGE_SPLIT = "source_change_split"


@dataclasses.dataclass(frozen=True)
class Mutation:
    kind: MutationKind
    payload: Any = None


@dataclasses.dataclass(frozen=True)
class EpochPair:
    """curr = epoch the barrier opens; prev = epoch it closes
    (reference: src/common/src/util/epoch.rs)."""

    curr: int
    prev: int


@dataclasses.dataclass(frozen=True)
class Barrier:
    epoch: EpochPair
    checkpoint: bool = False
    mutation: Optional[Mutation] = None

    @staticmethod
    def new(curr: int, checkpoint: bool = False, mutation: Optional[Mutation] = None) -> "Barrier":
        return Barrier(EpochPair(curr, curr - 1), checkpoint, mutation)

    def is_stop(self) -> bool:
        return self.mutation is not None and self.mutation.kind == MutationKind.STOP


@dataclasses.dataclass(frozen=True)
class Watermark:
    """Event-time progress on one column (reference: executor/mod.rs:591);
    downstream state with keys below the watermark can be cleaned/emitted."""

    col_idx: int
    value: Any


Message = Union[StreamChunk, Barrier, Watermark]


def is_chunk(m: Message) -> bool:
    return isinstance(m, StreamChunk)
