"""Pipeline introspection: the "what is my stream stuck on" tool.

Counterpart of the reference's await-tree dumps
(reference: src/stream/src/executor/wrapper/trace.rs + the await-tree
registry served by MonitorService.stack_trace,
src/compute/src/rpc/service/monitor_service.rs:46 — live async stack
trees per actor shown in the dashboard / risectl trace). The analogue
here walks each job's executor tree and reports, per executor: identity,
message counters, barrier time, and source-queue depths — enough to see
where an epoch is stuck without attaching a debugger.
"""

from __future__ import annotations

from typing import List


def executor_tree(root, indent: int = 0) -> List[str]:
    """Indented one-line-per-executor rendering of a pipeline."""
    lines = []
    ident = getattr(root, "identity", type(root).__name__)
    stats = getattr(root, "stats", None)
    extra = ""
    if stats is not None:
        extra = (f"  in={stats.chunks_in + stats.batch_chunks_in}"
                 f" out={stats.chunks_out} barriers={stats.barriers}"
                 f" barrier_s={stats.barrier_seconds:.3f}")
    q = getattr(root, "queue", None)
    if q is not None:
        extra += f"  queued={q.qsize()}"
    lines.append("  " * indent + ident + extra)
    for attr in ("input", "left", "right"):
        child = getattr(root, attr, None)
        if child is not None:
            lines.extend(executor_tree(child, indent + 1))
    for child in getattr(root, "inputs", ()) or ():
        lines.extend(executor_tree(child, indent + 1))
    return lines


def dump_session(session) -> str:
    """Full session dump: per-job executor trees + barrier progress.

    Worker-hosted jobs (pipeline lives in another process) render from
    the session's federation cache — their trees arrive over the ``stats``
    control frame (``Session._federate_worker_stats``), so a remote job is
    as inspectable as a local one (reference: MonitorService.stack_trace
    aggregating per-compute-node await-trees)."""
    lines = [
        f"epoch: completed={session.epoch} injected={session._injected} "
        f"in_flight={[e for e, _ in session._inflight]}",
    ]
    remote_trees: dict = {}
    for wid, st in sorted(getattr(session, "_worker_stats", {}).items()):
        for name, tree in (st.get("trees") or {}).items():
            remote_trees[name] = (wid, tree)
    for name, job in session.jobs.items():
        if job.pipeline is not None:
            # a live local pipeline always wins over a cached worker
            # snapshot of the same name (e.g. an MV recreated in-process
            # after its worker died)
            remote_trees.pop(name, None)
            lines.append(f"job {name!r}:")
            lines.extend(executor_tree(job.pipeline, indent=1))
            continue
        if name in remote_trees:
            wid, tree = remote_trees.pop(name)
            lines.append(f"job {name!r} (worker {wid}):")
            lines.extend("  " + ln for ln in tree)
            continue
        lines.append(f"job {name!r}: <remote; no stats snapshot yet>")
    # trees cached for jobs no longer in session.jobs (post-mortem)
    for name, (wid, tree) in remote_trees.items():
        lines.append(f"job {name!r} (worker {wid}, cached):")
        lines.extend("  " + ln for ln in tree)
    return "\n".join(lines)
