"""Epoch co-scheduler: many MVs' epochs batched into ONE dispatch per tick.

The host-side grouping layer over ops/fused_multi.py. A *group* holds
jobs whose fused-epoch trace is identical (same core shape — source+agg
or source+join — same static config, same projection, same source
family); their states live STACKED under a leading job axis and every
tick runs one jitted, vmapped epoch for the whole group. Per-job
identity rides as data: a start-event cursor and a PRNG base key per
job (keys are folded with the per-job batch counter INSIDE the jit, so
adding the fold costs zero extra dispatches and stays bit-identical to
the solo path's host-side ``jax.random.fold_in``).

Grouping rules (docs/performance.md "Epoch co-scheduling"):

* eligibility is decided by a static **signature** — (shape kind,
  source signature, rows/chunk, projection exprs, core config). Equal
  signature ⇒ identical trace ⇒ stackable. Different window literals,
  agg calls, capacities… ⇒ different signature ⇒ different group.
* a job that matches no group's signature simply starts its own group
  (a group of one is still one dispatch — the solo fused epoch with a
  [1] job axis, bit-exact vs the un-stacked builder).
* membership changes (CREATE/DROP) restack the job axis and recompile
  at the new [J] shape; jit caches per shape, so toggling between two
  sizes does not re-trace.

Barrier work is also batched: one vmapped probe returns the WHOLE
group's packed stats in a single [J, 3] fetch; only per-job output
gathers remain per job (they are per-job data), served by one compiled
gather with a traced job index.

``match_coschedulable`` is the Session's CREATE MATERIALIZED VIEW hook:
it recognizes the fusable source+agg plan shape (NEXmark bid source →
projection → grouped agg) and returns a build recipe, or None — the
documented solo-executor fallback for every other shape (joins under
the planner, retraction-bearing inputs, materialized-input aggs,
watermarked sources, fragmented/sharded/worker-placed builds).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common.fetch import PendingFlush, async_fetch
from ..ops.fused_multi import (
    append_state, build_group_epoch, gather_job_flush_chunk, index_state,
    multi_agg_finish, multi_agg_probe, remove_state, stack_states,
)


@dataclasses.dataclass
class FusedJobSpec:
    """One co-schedulable job: everything needed to trace its epoch."""

    kind: str                  # "agg" | "join"
    signature: tuple           # static trace signature (grouping key)
    chunk_fn: Callable         # traceable (start, key) -> StreamChunk
    exprs: tuple               # projection Exprs ((), for no projection)
    core: object               # AggCore / IntervalJoinCore
    rows_per_chunk: int
    seed: int                  # per-job PRNG base seed


def _expr_sig(e) -> str:
    # runtime Exprs are frozen dataclasses: repr() recurses into fields,
    # so it is a complete structural signature
    return repr(e)


def agg_signature(core, exprs, rows_per_chunk: int,
                  source_sig: tuple) -> tuple:
    """Static signature of a source+agg fused epoch: equal signatures ⇒
    identical traced computation ⇒ stackable."""
    return ("agg", source_sig, int(rows_per_chunk),
            tuple(_expr_sig(e) for e in exprs),
            tuple(repr(t) for t in core.key_types),
            tuple(core.group_keys), repr(tuple(core.agg_calls)),
            core.capacity, core.out_capacity)


def join_signature(core, exprs, rows_per_chunk: int,
                   source_sig: tuple) -> tuple:
    return ("join", source_sig, int(rows_per_chunk),
            tuple(_expr_sig(e) for e in exprs),
            repr(core.probe_schema), core.ts_col, core.val_col,
            core.window_us, core.n_buckets, core.W, core.band_col,
            core.band_us)


class CoGroup:
    """One signature's job set: stacked state + compiled group steps.

    The authoritative per-job state lives in ``self.stacked``;
    ``state_of``/``set_state`` give solo-shaped views for checkpointing
    and bit-exactness tests."""

    def __init__(self, spec: FusedJobSpec, donate: bool = True):
        self.kind = spec.kind
        self.signature = spec.signature
        self.core = spec.core
        self.rows_per_chunk = spec.rows_per_chunk
        self.names: list[str] = []
        self.starts: list[int] = []      # per-job event cursor
        self.batch_nos: list[int] = []   # per-job epoch counter (PRNG fold)
        self.seeds: list[int] = []
        self.stacked = None
        self.epochs_run = 0
        self._epoch = build_group_epoch(
            spec.kind, spec.chunk_fn, spec.exprs, spec.core,
            spec.rows_per_chunk, donate)
        if spec.kind == "agg":
            self._probe = multi_agg_probe(spec.core)
            self._finish = multi_agg_finish(spec.core)
            self._gather = gather_job_flush_chunk(spec.core)
        self._join_out = None            # last join epoch's outputs
        self.pending: Optional[PendingFlush] = None

    # -- membership -----------------------------------------------------------

    @property
    def n_jobs(self) -> int:
        return len(self.names)

    def add(self, name: str, state, start: int = 0, seed: int = 0,
            batch_no: int = 0) -> None:
        if name in self.names:
            raise ValueError(f"job {name!r} already co-scheduled")
        assert self.pending is None, \
            "membership change with a flush in flight (drain first)"
        if self.stacked is None:
            self.stacked = stack_states([state])
        else:
            self.stacked = append_state(self.stacked, state)
        self.names.append(name)
        self.starts.append(int(start))
        self.batch_nos.append(int(batch_no))
        self.seeds.append(int(seed))
        self._base_keys = None

    def remove(self, name: str):
        """Drop a job; returns its final solo-shaped state."""
        assert self.pending is None, \
            "membership change with a flush in flight (drain first)"
        j = self.names.index(name)
        st = index_state(self.stacked, j)
        self.stacked = (remove_state(self.stacked, j)
                        if self.n_jobs > 1 else None)
        for lst in (self.names, self.starts, self.batch_nos, self.seeds):
            lst.pop(j)
        self._base_keys = None
        return st

    def state_of(self, name: str):
        return index_state(self.stacked, self.names.index(name))

    def set_states(self, states: list) -> None:
        """Replace every job's state (post-checkpoint write-back):
        ONE restack instead of J in-place scatters."""
        assert len(states) == self.n_jobs
        self.stacked = stack_states(states)

    # -- ticking --------------------------------------------------------------

    def _keys(self):
        # stacked per-job base keys, rebuilt only on membership change;
        # the per-epoch fold happens INSIDE the group dispatch
        if self._base_keys is None:
            self._base_keys = jnp.stack(
                [jax.random.PRNGKey(s) for s in self.seeds])
        return self._base_keys

    def run_epoch(self, k: int):
        """ONE dispatch: every member job advances k chunks. For join
        groups the epoch's flush outputs are held for ``flush()``."""
        starts = jnp.asarray(self.starts, jnp.int64)
        nos = jnp.asarray(self.batch_nos, jnp.int64)
        res = self._epoch(self.stacked, starts, self._keys(), nos, k)
        if self.kind == "agg":
            self.stacked = res
        else:
            self.stacked = res[0]
            self._join_out = res[1:]
        for j in range(self.n_jobs):
            self.starts[j] += k * self.rows_per_chunk
            self.batch_nos[j] += 1
        self.epochs_run += 1
        return res if self.kind == "join" else None

    def begin_flush(self) -> "PendingFlush":
        """Start the barrier flush WITHOUT resolving it: one vmapped
        probe is enqueued and its packed [J, 3] stats start streaming to
        the host (common/fetch.py), then the vmapped finish is enqueued
        eagerly — finish depends only on device state, so the NEXT
        epoch's dispatch can launch on finished state before this
        flush's fetch resolves (pipeline_depth = 2). The pre-finish
        state rides in the pending handle for the gathers."""
        if self.kind != "agg":
            raise NotImplementedError(
                "join-group flush is driven by the caller from the "
                "epoch outputs (bench.py measure pattern)")
        assert self.pending is None, "flush already in flight"
        packed, ranks = self._probe(self.stacked)
        self.pending = PendingFlush(
            self.stacked, packed, ranks,
            async_fetch(packed, dispatch=self._probe.__qualname__))
        self.stacked = self._finish(self.stacked)
        return self.pending

    def finish_flush(self) -> dict:
        """Resolve the in-flight flush: one packed fetch (already
        streaming — usually landed) for all J jobs, then per-job gather
        windows against the pending pre-finish state. Returns
        {job: [StreamChunk, ...]}."""
        p = self.pending
        if p is None:
            p = self.begin_flush()
        self.pending = None
        packed_h = np.asarray(p.fetch.result())
        out: dict = {}
        for j, name in enumerate(self.names):
            n_dirty, overflow = int(packed_h[j, 0]), int(packed_h[j, 1])
            if overflow:
                raise RuntimeError(
                    f"co-scheduled job {name!r}: group table overflow "
                    f"(capacity {self.core.capacity}); increase "
                    "agg_table_capacity")
            chunks = []
            lo = 0
            while lo < n_dirty:
                chunks.append(self._gather(p.stacked, p.ranks,
                                           jnp.int64(j), jnp.int64(lo)))
                lo += self.core.groups_per_chunk
            out[name] = chunks
        return out

    def flush(self) -> dict:
        """Synchronous barrier flush (begin + finish in one call): one
        vmapped probe, ONE packed fetch, per-job gathers, one vmapped
        finish — the pre-pipeline cadence, still the default."""
        if self.pending is None:
            self.begin_flush()
        return self.finish_flush()


class CoScheduler:
    """Signature-keyed group registry (one per Session)."""

    def __init__(self, donate: bool = True):
        self.groups: dict[tuple, CoGroup] = {}
        self.jobs: dict[str, CoGroup] = {}
        self.donate = donate

    def add(self, name: str, spec: FusedJobSpec, state,
            start: int = 0, batch_no: int = 0) -> CoGroup:
        group = self.groups.get(spec.signature)
        if group is None:
            group = CoGroup(spec, donate=self.donate)
            self.groups[spec.signature] = group
        group.add(name, state, start=start, seed=spec.seed,
                  batch_no=batch_no)
        self.jobs[name] = group
        return group

    def remove(self, name: str):
        group = self.jobs.pop(name, None)
        if group is None:
            return None
        st = group.remove(name)
        if group.n_jobs == 0:
            self.groups.pop(group.signature, None)
        return st

    def stats(self) -> dict:
        return {
            "jobs": len(self.jobs),
            "groups": [
                {"kind": g.kind, "jobs": list(g.names),
                 "epochs_run": g.epochs_run}
                for g in self.groups.values()
            ],
        }


# ---------------------------------------------------------------------------
# Session-side plan matching (CREATE MATERIALIZED VIEW hook)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CoschedMatch:
    """Recipe for building a plan as a co-scheduled fused job."""

    exprs: tuple               # projection onto the agg input
    proj_names: tuple
    group_keys: tuple
    agg_calls: tuple
    source: object             # SourceDef (nexmark bid)
    col_map: tuple             # declared column -> device BID_SCHEMA column


def _nexmark_bid_colmap(schema) -> Optional[tuple]:
    """Declared source columns → device BID_SCHEMA positions (the host
    reader adapts chunks to the declared schema by name; the fused path
    does the same with a column gather around chunk_fn). None when a
    declared column does not exist in the bid stream."""
    from ..connector import BID_SCHEMA
    by_name = {f.name: i for i, f in enumerate(BID_SCHEMA)}
    cmap = []
    for f in schema:
        i = by_name.get(f.name)
        if i is None or BID_SCHEMA[i].type.kind != f.type.kind:
            return None
        cmap.append(i)
    return tuple(cmap)


def declared_chunk_fn(full_fn: Callable, col_map: tuple) -> Callable:
    """Wrap a full-schema device chunk_fn to emit the declared column
    subset (a tuple re-index — free under fusion)."""
    def fn(start, key):
        ch = full_fn(start, key)
        return ch.with_columns(tuple(ch.columns[i] for i in col_map))
    return fn


def _expr_refs(e):
    # the optimizer's field-walking helper covers every Expr subtype
    from ..frontend.optimizer import expr_refs
    return expr_refs(e)


def match_coschedulable(plan) -> Optional[CoschedMatch]:
    """Recognize the fusable source+agg shape: PAgg over PProject over
    PSource(nexmark, table=bid). Returns a build recipe or None (solo
    fallback). Conservative on purpose — anything the device NEXmark
    generator + AggCore pair cannot reproduce bit-exactly stays on the
    executor path."""
    from ..expr.expr import InputRef
    from ..frontend import planner as P
    if isinstance(plan, P.PProject):
        # the planner wraps the agg in an output-naming projection;
        # accept the identity one (SELECT keys, aggs in plan order) —
        # reordering/computed outputs fall back to the executor path
        if not (len(plan.exprs) == len(plan.input.schema)
                and all(isinstance(e, InputRef) and e.index == i
                        for i, e in enumerate(plan.exprs))):
            return None
        plan = plan.input
    if not isinstance(plan, P.PAgg) or not plan.group_keys or plan.eowc:
        return None
    for c in plan.agg_calls:
        if c.lanes_unsupported or c.is_string_minmax:
            return None            # materialized-input / rank-table aggs
    inp = plan.input
    if not isinstance(inp, P.PProject):
        return None
    src = inp.input
    if not isinstance(src, P.PSource):
        return None
    sd = src.source
    if sd.connector != "nexmark":
        return None
    if (sd.options or {}).get("nexmark_table", "bid").lower() != "bid":
        return None                # device generator covers bids only
    if sd.watermark is not None:
        return None                # watermark filter not in the fused body
    # projection must not touch the hidden row-id column (the device
    # chunk has only the declared bid columns)
    n_data_cols = len(sd.schema)
    for e in inp.exprs:
        if any(r >= n_data_cols for r in _expr_refs(e)):
            return None
    col_map = _nexmark_bid_colmap(sd.schema)
    if col_map is None:
        return None                # declared column unknown to the stream
    return CoschedMatch(
        exprs=tuple(inp.exprs), proj_names=tuple(inp.schema.names),
        group_keys=tuple(plan.group_keys),
        agg_calls=tuple(plan.agg_calls), source=sd, col_map=col_map)


class DeviceSourceCursor:
    """Split-state shim for a device-generated source: the feed
    machinery persists ``offsets`` per checkpoint epoch and seeks on
    recovery, exactly like a connector SplitReader (frontend/session.py
    ``_SourceFeed``)."""

    SPLIT = "device"

    def __init__(self, events: int = 0, epochs: int = 0):
        self.events = int(events)
        self.epochs = int(epochs)     # PRNG batch counter rides along

    @property
    def offsets(self) -> dict:
        # pack (events, epochs) into the split map — both cursors must
        # recover together or replayed generation would re-key
        return {self.SPLIT: self.events, "epochs": self.epochs}

    def seek(self, offsets: dict) -> None:
        self.events = int(offsets.get(self.SPLIT, 0))
        self.epochs = int(offsets.get("epochs", 0))

    def rows_emitted(self) -> int:
        return self.events
