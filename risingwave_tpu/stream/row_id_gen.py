"""RowIdGenExecutor — assign serial row ids to source rows.

Counterpart of the reference's RowIdGenExecutor
(reference: src/stream/src/executor/row_id_gen.rs; RowId layout
src/common/src/util/row_id.rs — vnode-prefixed monotone ids so ids generated
by parallel source actors never collide). Here: id = shard_id << 48 | seq,
seq a device counter bumped per visible row — one fused step, no host sync.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from .executor import Executor, SingleInputExecutor


class RowIdGenExecutor(SingleInputExecutor):
    identity = "RowIdGen"

    def __init__(self, input: Executor, row_id_index: int, shard_id: int = 0,
                 start_seq: int = 0):
        super().__init__(input)
        self.schema = input.schema
        self.row_id_index = row_id_index
        self.seq = jnp.asarray(start_seq, jnp.int64)
        base = jnp.int64(shard_id) << 48

        @jax.jit
        def _step(seq, chunk: StreamChunk):
            vis = chunk.vis
            offset = jnp.cumsum(vis) - vis.astype(jnp.int64)
            ids = base | (seq + offset)
            cols = list(chunk.columns)
            cols[row_id_index] = Column(ids, jnp.ones_like(vis))
            return seq + jnp.sum(vis), chunk.with_columns(cols)

        self._step = _step

    async def map_chunk(self, chunk: StreamChunk):
        self.seq, out = self._step(self.seq, chunk)
        yield out
