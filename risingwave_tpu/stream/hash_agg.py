"""HashAggExecutor — incremental group-by aggregation on device-resident state.

TPU-native counterpart of the reference's HashAggExecutor
(reference: src/stream/src/executor/hash_agg.rs:66-123, apply_chunk :319,
flush_data :404; per-group AggGroup, executor/aggregation/agg_group.rs:159).
Design differences, deliberately (SURVEY.md §7):

  * Group state is NOT an LRU cache over a row store — it lives wholly in
    device HBM as an open-addressing table (ops/hash_table.py) plus per-group
    aggregate "lanes" arrays. A whole chunk updates all its groups in one
    jitted step via scatter-reduce: no per-key host loop anywhere.
  * The dirty-group set is a device bitmask; on every barrier the changed
    groups are gathered into output chunks (Insert / U-,U+ / Delete exactly
    like the reference's flush), and ``prev`` lanes advance.
  * A second bitmask accumulates dirtiness between *checkpoint* barriers;
    on checkpoint the delta groups are flushed to the host StateTable (the
    durable tier) and recovery reloads them (hash_agg.rs state tables +
    recovery §3.4).

Row-count lane 0 is implicit (the reference's AggGroup ``row_count``) and
drives Insert-vs-Update-vs-Delete emission and group liveness.

The pure device logic lives in ops/grouped_agg.py (shared with the sharded
multi-chip path, parallel/sharded_agg.py); this class is the host control
loop + persistence.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import DEFAULT_CHUNK_CAPACITY, Column, StreamChunk
from ..common.fetch import fetch
from ..common.types import INT64, Field, Schema
from ..expr.agg import AggCall
from ..ops.grouped_agg import AggCore, AggState, load_rows_into_state
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


class HashAggExecutor(SingleInputExecutor):
    """``group_keys``: input column indices; ``agg_calls``: AggCall specs.

    Output schema: group key columns then one column per agg call."""

    identity = "HashAgg"

    def __init__(
        self,
        input: Executor,
        group_keys: Sequence[int],
        agg_calls: Sequence[AggCall],
        state_table: Optional[StateTable] = None,
        table_capacity: int = 1 << 16,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
        load_shard: Optional[tuple] = None,
        load_vnodes: Optional[tuple] = None,
        hbm_group_budget: Optional[int] = None,
    ):
        """``load_shard``: (shard_idx, n_shards) for fragmented builds —
        this actor shares its state table with its sibling shards and on
        recovery keeps only the rows whose group key hashes to its shard
        (vnode reassignment across a parallelism change, reference:
        stream/scale.rs:657 vnode-bitmap updates).

        ``load_vnodes``: (vnode_start, vnode_end) for SPANNING fragment
        actors (meta-placed vnode ranges): recovery keeps only rows in
        the owned range. After a live vnode migration the actor's local
        store may hold rows for ranges that moved away (and an imported
        handoff may sit beside foreign leftovers) — this filter is what
        makes reload placement equal live routing regardless of
        migration history (meta/rescale.py, docs/scaling.md).

        ``hbm_group_budget``: cap on LIVE groups held in device memory.
        When a checkpoint finds more, the coldest (LRU by touch step) are
        evicted to the state table and faulted back in on access
        (reference: ManagedLruCache over StateTables,
        src/stream/src/cache/managed_lru.rs) — device state becomes a
        cache over the durable tier instead of grow-or-raise. Requires a
        state_table; must be < table_capacity (headroom for growth
        between checkpoints)."""
        super().__init__(input)
        for c in agg_calls:
            if c.lanes_unsupported:
                # silent wrongness guard: fixed device lanes cannot dedup
                # or materialize input; the planner must route these to
                # MaterializedAggExecutor
                raise ValueError(
                    f"{c.kind}{'(distinct)' if c.distinct else ''} needs "
                    "materialized-input state (stream/materialized_agg.py)")
        self.load_shard = load_shard
        self.load_vnodes = load_vnodes
        if hbm_group_budget is not None:
            if state_table is None:
                hbm_group_budget = None       # no cold tier to evict to
            elif hbm_group_budget >= table_capacity:
                raise ValueError(
                    "hbm_group_budget must be < table_capacity")
        self.hbm_group_budget = hbm_group_budget
        self._evicted: set = set()
        from .cache import LruClock
        self._lru_clock = LruClock(hbm_group_budget is not None)
        in_schema = input.schema
        key_types = tuple(in_schema[i].type for i in group_keys)
        self.core = AggCore(key_types, group_keys, agg_calls, table_capacity,
                            out_capacity)
        self.schema = Schema(
            tuple(in_schema[i] for i in group_keys)
            + tuple(Field(f"agg{i}", c.output_type) for i, c in enumerate(agg_calls))
        )
        self.state_table = state_table
        self.state = self.core.init_state()
        # Donating the state pytree lets XLA update the group table in place
        # (no copy of the [capacity]-sized lanes per chunk). CPU sometimes
        # cannot honor donation and warns; keep it for the TPU hot path only.
        donate = (0,) if jax.default_backend() == "tpu" else ()
        self._apply = jax.jit(self.core.apply_chunk, donate_argnums=donate)
        # string MIN/MAX compares dictionary ranks, fetched fresh per apply
        self._needs_ranks = any(c.is_string_minmax for c in self.core.agg_calls)

        def _apply_batch(state, batched_chunk, str_ranks=None, step=None):
            def body(st, ch):
                return self.core.apply_chunk(st, ch, str_ranks, step), None
            state, _ = jax.lax.scan(body, state, batched_chunk)
            return state

        # One dispatch applies a whole ChunkBatch: the epoch loop stays on
        # device (lax.scan), amortizing host->device dispatch latency.
        self._apply_batch = jax.jit(_apply_batch, donate_argnums=donate)
        self._gather = jax.jit(self.core.gather_flush_chunk)
        self._finish = jax.jit(self.core.finish_flush)

        # barrier probe: ONE packed scalar fetch per barrier (every host sync
        # over a tunneled chip costs a full RTT — ~100ms on axon; the old
        # separate overflow + n_dirty + per-chunk cardinality syncs made the
        # barrier path ~5 RTTs). The dirty-rank prefix sums stay on device and
        # are shared by all flush windows of the barrier.
        def _probe(st):
            rank = self.core.flush_rank(st)
            if self.hbm_group_budget is not None:
                # live-group census gates cold eviction; only budgeted
                # executors pay for it (an O(capacity) int64 compare —
                # kept OFF the bench-critical unbudgeted probe, which is
                # the exact graph proven on-chip in round 3)
                n_live = jnp.sum(st.table.occupied & (st.lanes[0] > 0))
                n_live = n_live.astype(jnp.int32)
            else:
                n_live = jnp.zeros((), jnp.int32)
            packed = jnp.stack([rank[-1], st.overflow.astype(jnp.int32),
                                n_live])
            return packed, rank

        self._probe = jax.jit(_probe)
        self._clean = jax.jit(self.core.clean_below, static_argnums=(1,))
        self._compact = jax.jit(self.core.compact)
        self._evict_plan = jax.jit(self.core.evict_plan,
                                   static_argnums=(1,))
        self._apply_evict = jax.jit(self.core.apply_evict)
        self._absorb = jax.jit(self.core.absorb)
        # group-key watermark state cleaning (reference: hash_agg group-key
        # watermarks + state_table.rs:885 update_watermark)
        self._pending_clean: dict[int, Any] = {}
        if self.state_table is not None:
            self._load_from_state_table()

    # convenience accessors used by tests/tools
    @property
    def group_keys(self):
        return self.core.group_keys

    @property
    def agg_calls(self):
        return self.core.agg_calls

    # -- host control ---------------------------------------------------------

    def _str_ranks(self):
        if not self._needs_ranks:
            return None
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks()

    def _pykey(self, values) -> tuple:
        from .cache import canonical_key
        return canonical_key(values, self.core.key_types)

    def _lru(self):
        return self._lru_clock.next()

    async def map_chunk(self, chunk: StreamChunk):
        self.state = self._apply(self.state, chunk, self._str_ranks(),
                                 self._lru())
        if self._evicted:
            self._fault_in(chunk.columns, chunk.vis)
        if False:
            yield

    async def map_chunk_batch(self, batch):
        self.state = self._apply_batch(self.state, batch.chunk,
                                       self._str_ranks(), self._lru())
        if self._evicted:
            self._fault_in(batch.chunk.columns, batch.chunk.vis)
        if False:
            yield

    # -- eviction / fault-in ---------------------------------------------------

    def _fault_in(self, columns, vis) -> None:
        """Reload any evicted group keys present in this chunk/batch from
        the cold tier and merge their stored lanes into device state
        (one host sync per chunk, paid only while evicted keys exist)."""
        nk = len(self.core.group_keys)
        key_np = [np.asarray(columns[i].data).ravel()
                  for i in self.core.group_keys]
        vis_np = np.asarray(vis).ravel()
        present = set(zip(*(k[vis_np] for k in key_np))) if nk else set()
        hits = [k for k in present if self._pykey(k) in self._evicted]
        if not hits:
            return
        rows = []
        keys = []
        for k in hits:
            pk = self._pykey(k)
            row = self.state_table.get_row(pk)
            if row is not None:
                rows.append(row)
                keys.append(k)
            self._evicted.discard(pk)
        if not rows:
            return
        n = len(rows)
        cap = 1
        while cap < n:
            cap *= 2
        valid = jnp.arange(cap) < n
        key_cols = []
        for c in range(nk):
            data = np.zeros(cap, self.core.key_types[c].np_dtype)
            data[:n] = [k[c] for k in keys]
            key_cols.append(Column(jnp.asarray(data),
                                   jnp.asarray(np.arange(cap) < n)))
        stored = []
        for j, dt in enumerate(self.core.lane_dtypes):
            arr = np.zeros(cap, np.dtype(dt))
            arr[:n] = [r[nk + j] for r in rows]
            stored.append(jnp.asarray(arr))
        self.state = self._absorb(self.state, key_cols, tuple(stored),
                                  valid, self._str_ranks())

    async def on_barrier(self, barrier: Barrier):
        packed, rank = self._probe(self.state)
        # through the async-fetch helper: the packed copy starts
        # streaming at enqueue, and the tick-path lint
        # (sync-fetch-discipline) can reason about one crossing
        n_dirty, overflow, n_live = (int(x) for x in fetch(packed))
        if overflow:
            raise RuntimeError(
                f"{self.identity}: group table overflow (capacity "
                f"{self.core.capacity}); increase table_capacity")
        lo = 0
        while lo < n_dirty:
            # no cardinality gating: a rare all-invisible flush chunk (groups
            # born and killed within one epoch) is a downstream no-op, while
            # gating costs one RTT sync per chunk
            yield self._gather(self.state, rank, jnp.int64(lo))
            lo += self.core.groups_per_chunk
        cleaned = False
        if barrier.checkpoint and self._pending_clean:
            # mark dead BEFORE the checkpoint so it persists the deletes
            # (keys must still be readable from the table), compact AFTER
            for key_pos, threshold in self._pending_clean.items():
                self.state = self._clean(self.state, key_pos,
                                         jnp.asarray(threshold))
            self._pending_clean.clear()
            cleaned = True
        if barrier.checkpoint and self.state_table is not None:
            from ..common.tracing import CAT_STORAGE, trace_span
            with trace_span(f"{self.identity}.checkpoint", CAT_STORAGE,
                            epoch=barrier.epoch.curr, tid=self.identity,
                            groups=n_live):
                self._checkpoint_to_state_table(barrier.epoch.curr)
            if (self.hbm_group_budget is not None
                    and n_live > self.hbm_group_budget):
                self._evict_cold()
                cleaned = True
        if cleaned:
            self.state = self._compact(self.state)
        self.state = self._finish(self.state)

    def _evict_cold(self) -> None:
        """Evict the coldest live groups down to 3/4 of the budget (their
        durable rows were just written by this barrier's checkpoint).
        Null-keyed groups are never evicted (the fault-in key path carries
        no null masks)."""
        keep = max(self.hbm_group_budget * 3 // 4, 1)
        mask, _n = self._evict_plan(self.state, keep)
        all_keys_valid = None
        for km in self.state.table.key_mask:
            all_keys_valid = km if all_keys_valid is None \
                else (all_keys_valid & km)
        if all_keys_valid is not None:
            mask = mask & all_keys_valid
        nm = np.asarray(mask)
        idx = np.nonzero(nm)[0]
        if not len(idx):
            return
        key_np = [np.asarray(kd)[idx] for kd in self.state.table.key_data]
        for row in zip(*key_np):
            self._evicted.add(self._pykey(row))
        self.state = self._apply_evict(self.state, jnp.asarray(nm))

    async def on_watermark(self, watermark):
        """Watermark on a group-key column: remap to the output position and
        schedule state cleaning below it; other columns' watermarks cannot
        be propagated through a grouped agg."""
        if watermark.col_idx in self.core.group_keys:
            pos = self.core.group_keys.index(watermark.col_idx)
            prev = self._pending_clean.get(pos)
            if prev is None or watermark.value > prev:
                self._pending_clean[pos] = watermark.value
            yield watermark.__class__(pos, watermark.value)

    # -- persistence ----------------------------------------------------------

    def _checkpoint_to_state_table(self, epoch: int) -> None:
        """Flush groups dirtied since the last checkpoint to the durable tier.

        Host sync is bounded by the checkpoint delta, mirroring the
        reference's incremental StateTable.commit (state_table.rs:783)."""
        st = self.state
        idx = np.nonzero(np.asarray(st.ckpt_dirty))[0]
        if len(idx):
            from ..native import codec as _native_codec
            codec = _native_codec()
            if codec is not None:
                keys_d = [np.asarray(kd) for kd in st.table.key_data]
                keys_m = [np.asarray(km) for km in st.table.key_mask]
                lanes = [np.asarray(l) for l in st.lanes]
                datas = keys_d + lanes
                ones = np.ones(lanes[0].shape, bool)
                masks = keys_m + [ones] * len(lanes)
                types = self.state_table.schema.types
                nk = len(keys_d)
                live = lanes[0][idx] > 0
                ins_idx, del_idx = idx[live], idx[~live]
                pk_t = list(types[:nk])
                puts = dict(zip(
                    codec.encode_keys(keys_d, keys_m, pk_t, ins_idx),
                    codec.encode_value_rows(datas, masks, types, ins_idx)))
                dels = codec.encode_keys(keys_d, keys_m, pk_t, del_idx)
                self.state_table.stage_encoded(puts, dels)
            else:
                keys_d = [np.asarray(kd)[idx] for kd in st.table.key_data]
                keys_m = [np.asarray(km)[idx] for km in st.table.key_mask]
                lanes = [np.asarray(l)[idx] for l in st.lanes]
                for r in range(len(idx)):
                    key_vals = [
                        keys_d[c][r].item() if keys_m[c][r] else None
                        for c in range(len(keys_d))
                    ]
                    lane_vals = [lanes[j][r].item()
                                 for j in range(len(lanes))]
                    row = tuple(key_vals) + tuple(lane_vals)
                    if lanes[0][r] > 0:
                        self.state_table.insert(row)
                    else:
                        self.state_table.delete(row)
            self.state_table.commit(epoch)
        self.state = st.replace(ckpt_dirty=jnp.zeros_like(st.ckpt_dirty))

    def _filter_shard(self, rows: list) -> list:
        """Keep rows whose group key hashes to this actor's shard — the
        same device hash the dispatcher routes live rows with, so reload
        placement always matches routing, for ANY shard count."""
        from ..common.hashing import shard_rows
        idx, n_shards = self.load_shard
        return shard_rows(self.core.key_types, rows, n_shards)[idx]

    def _load_from_state_table(self) -> None:
        """Recovery: reload committed groups into the device table."""
        rows = list(self.state_table.scan_all())
        if rows and self.load_shard is not None:
            rows = self._filter_shard(rows)
        if rows and self.load_vnodes is not None:
            # spanning actor: keep only the meta-placed vnode range —
            # post-migration stores may hold rows that moved away
            from ..common.hashing import filter_rows_vnodes
            s, e = self.load_vnodes
            rows = filter_rows_vnodes(self.core.key_types, rows, s, e)
        if (self.hbm_group_budget is not None
                and len(rows) > self.hbm_group_budget):
            # under eviction the durable tier legitimately holds more
            # groups than the device budget: load up to the budget, leave
            # the rest cold (null-keyed rows always load — the fault-in
            # key path carries no null masks)
            nk0 = len(self.core.group_keys)
            hot, cold = [], []
            for r in rows:
                key = r[:nk0]
                if len(hot) < self.hbm_group_budget or any(
                        v is None for v in key):
                    hot.append(r)
                else:
                    cold.append(r)
            for r in cold:
                self._evicted.add(self._pykey(r[:nk0]))
            rows = hot
        if not rows:
            return
        self.state = load_rows_into_state(self.core, self.state, rows)
        # prev must match what was already emitted before the failure: the
        # recovered snapshot is the new baseline
        self.state = self.state.replace(prev_lanes=self.state.lanes)


def agg_state_schema(key_fields: Sequence[Field], agg_calls: Sequence[AggCall]) -> Schema:
    """Schema of the durable agg state table: keys + raw lanes."""
    from ..common.types import FLOAT64
    lanes = [Field("row_count", INT64)]
    for i, c in enumerate(agg_calls):
        for j, dt in enumerate(c.state_dtypes()):
            lanes.append(Field(f"a{i}_l{j}", INT64 if dt == jnp.int64 else FLOAT64))
    return Schema(tuple(key_fields) + tuple(lanes))
