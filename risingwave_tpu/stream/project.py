"""Project and Filter executors (stateless).

Counterparts of the reference's ProjectExecutor / FilterExecutor
(reference: src/stream/src/executor/project.rs, executor/filter.rs). Both are
single jitted device steps; Filter keeps ops consistent for Update pairs the
same way the reference does — if a filter flips visibility across a U-/U+
pair, the pair degrades to a plain Delete/Insert (filter.rs apply logic).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from ..common.types import Field, Schema
from ..expr import Expr
from .executor import Executor, SingleInputExecutor


class ProjectExecutor(SingleInputExecutor):
    identity = "Project"

    def __init__(self, input: Executor, exprs: Sequence[Expr],
                 names: Sequence[str] = ()):
        super().__init__(input)
        self.exprs = tuple(exprs)
        names = tuple(names) or tuple(f"expr{i}" for i in range(len(exprs)))
        self.schema = Schema(tuple(Field(n, e.type) for n, e in zip(names, self.exprs)))

        def _step(chunk: StreamChunk) -> StreamChunk:
            cols = tuple(e.eval(chunk) for e in self.exprs)
            return chunk.with_columns(cols)

        self._step = jax.jit(_step)
        self._step_batch = jax.jit(jax.vmap(_step))

    async def map_chunk(self, chunk: StreamChunk):
        yield self._step(chunk)

    async def map_chunk_batch(self, batch):
        from ..common.chunk import ChunkBatch
        yield ChunkBatch(self._step_batch(batch.chunk))


class FilterExecutor(SingleInputExecutor):
    identity = "Filter"

    def __init__(self, input: Executor, predicate: Expr):
        super().__init__(input)
        self.schema = input.schema
        self.predicate = predicate

        def _step(chunk: StreamChunk) -> StreamChunk:
            cond = predicate.eval(chunk)
            keep = cond.data & cond.mask  # NULL -> filtered out (SQL WHERE)
            # Degrade broken update pairs to Insert/Delete: a U- whose U+ was
            # filtered (or vice versa) must not dangle
            # (reference: filter.rs / dispatch.rs:635-650 pairing rules).
            ops = chunk.ops
            is_ud = ops == OP_UPDATE_DELETE
            is_ui = ops == OP_UPDATE_INSERT
            partner_kept = jnp.roll(keep, -1)  # for U- rows: their U+ follows
            partner_kept_prev = jnp.roll(keep, 1)  # for U+ rows: their U- precedes
            new_ops = jnp.where(
                is_ud & ~partner_kept, OP_DELETE,
                jnp.where(is_ui & ~partner_kept_prev, OP_INSERT, ops),
            ).astype(ops.dtype)
            return chunk.replace(ops=new_ops, vis=chunk.vis & keep)

        self._step = jax.jit(_step)
        self._step_batch = jax.jit(jax.vmap(_step))

    async def map_chunk(self, chunk: StreamChunk):
        yield self._step(chunk)

    async def map_chunk_batch(self, batch):
        from ..common.chunk import ChunkBatch
        yield ChunkBatch(self._step_batch(batch.chunk))
