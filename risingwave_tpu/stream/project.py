"""Project and Filter executors (stateless).

Counterparts of the reference's ProjectExecutor / FilterExecutor
(reference: src/stream/src/executor/project.rs, executor/filter.rs). Both are
single jitted device steps; Filter keeps ops consistent for Update pairs the
same way the reference does — if a filter flips visibility across a U-/U+
pair, the pair degrades to a plain Delete/Insert (filter.rs apply logic).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column,
    StreamChunk,
)
from ..common.types import Field, Schema
from ..expr import Expr
from ..expr.expr import FunctionCall, InputRef, Literal
from .executor import Executor, SingleInputExecutor
from .message import Watermark

# Expressions through which a watermark can be derived: monotone in the
# watermark column (reference: watermark derivation over exprs,
# src/frontend/src/optimizer/property/watermark_columns.rs + stream project's
# watermark derivation). tumble_start is the load-bearing one — it carries
# source watermarks onto window-start group keys for state cleaning.
_MONOTONE_FNS = {"tumble_start"}


def derive_watermark(expr: Expr, wm: Watermark):
    """Map an input watermark through one output expression; None if the
    expression does not preserve the watermark order."""
    if isinstance(expr, InputRef):
        return wm.value if expr.index == wm.col_idx else None
    if (isinstance(expr, FunctionCall) and expr.name in _MONOTONE_FNS
            and expr.args and isinstance(expr.args[0], InputRef)
            and expr.args[0].index == wm.col_idx
            and all(isinstance(a, Literal) for a in expr.args[1:])):
        # evaluate the monotone fn on the watermark value via a 1-row chunk
        # (only the watermark column is ever read by the expression)
        cols = tuple(
            Column(jnp.full(1, wm.value if i == wm.col_idx else 0, jnp.int64),
                   jnp.ones(1, jnp.bool_))
            for i in range(wm.col_idx + 1))
        one = StreamChunk(jnp.zeros(1, jnp.int8), jnp.ones(1, jnp.bool_), cols)
        res = expr.eval(one)
        if bool(res.mask[0]):
            return res.data[0].item()
    return None


class ProjectExecutor(SingleInputExecutor):
    identity = "Project"

    def __init__(self, input: Executor, exprs: Sequence[Expr],
                 names: Sequence[str] = ()):
        super().__init__(input)
        self.exprs = tuple(exprs)
        names = tuple(names) or tuple(f"expr{i}" for i in range(len(exprs)))
        self.schema = Schema(tuple(Field(n, e.type) for n, e in zip(names, self.exprs)))

        def _step(chunk: StreamChunk) -> StreamChunk:
            cols = tuple(e.eval(chunk) for e in self.exprs)
            return chunk.with_columns(cols)

        from ..expr.expr import uses_host_callback
        if any(uses_host_callback(e) for e in self.exprs):
            # string functions hop to the host dictionary via
            # pure_callback, which some PJRT backends (axon) reject inside
            # compiled programs — run the step eagerly
            self._step = _step
            self._step_batch = None
        else:
            self._step = jax.jit(_step)
            self._step_batch = jax.jit(jax.vmap(_step))

    async def map_chunk(self, chunk: StreamChunk):
        yield self._step(chunk)

    async def map_chunk_batch(self, batch):
        if self._step_batch is None:
            async for out in super().map_chunk_batch(batch):
                yield out
            return
        from ..common.chunk import ChunkBatch
        yield ChunkBatch(self._step_batch(batch.chunk))

    async def on_watermark(self, watermark: Watermark):
        for i, e in enumerate(self.exprs):
            v = derive_watermark(e, watermark)
            if v is not None:
                yield Watermark(i, v)


class FilterExecutor(SingleInputExecutor):
    identity = "Filter"

    def __init__(self, input: Executor, predicate: Expr):
        super().__init__(input)
        self.schema = input.schema
        self.predicate = predicate

        def _step(chunk: StreamChunk) -> StreamChunk:
            cond = predicate.eval(chunk)
            keep = cond.data & cond.mask  # NULL -> filtered out (SQL WHERE)
            # Degrade broken update pairs to Insert/Delete: a U- whose U+ was
            # filtered (or vice versa) must not dangle
            # (reference: filter.rs / dispatch.rs:635-650 pairing rules).
            ops = chunk.ops
            is_ud = ops == OP_UPDATE_DELETE
            is_ui = ops == OP_UPDATE_INSERT
            partner_kept = jnp.roll(keep, -1)  # for U- rows: their U+ follows
            partner_kept_prev = jnp.roll(keep, 1)  # for U+ rows: their U- precedes
            new_ops = jnp.where(
                is_ud & ~partner_kept, OP_DELETE,
                jnp.where(is_ui & ~partner_kept_prev, OP_INSERT, ops),
            ).astype(ops.dtype)
            return chunk.replace(ops=new_ops, vis=chunk.vis & keep)

        from ..expr.expr import uses_host_callback
        if uses_host_callback(predicate):
            self._step = _step          # eager: see ProjectExecutor note
            self._step_batch = None
        else:
            self._step = jax.jit(_step)
            self._step_batch = jax.jit(jax.vmap(_step))

    async def map_chunk(self, chunk: StreamChunk):
        yield self._step(chunk)

    async def map_chunk_batch(self, batch):
        if self._step_batch is None:
            async for out in super().map_chunk_batch(batch):
                yield out
            return
        from ..common.chunk import ChunkBatch
        yield ChunkBatch(self._step_batch(batch.chunk))
