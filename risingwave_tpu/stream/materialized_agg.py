"""MaterializedAggExecutor — host-tier aggregation over materialized input
multisets.

Counterpart of the reference's ``AggStateStorage::MaterializedInput`` path
(reference: src/stream/src/executor/aggregation/agg_state.rs:65,
minput.rs): aggregates whose state cannot be a fixed set of device lanes
keep their input values materialized and recompute outputs from the
multiset. That covers

* exact DISTINCT aggregates (count/sum/avg DISTINCT — the reference's
  distinct-dedup tables, src/stream/src/executor/aggregation/distinct.rs),
* min/max over retractable inputs (a delete may remove the current
  extremum; monotone device lanes cannot retract — agg.py
  ``needs_append_only``),
* ordered/collecting aggregates: array_agg, string_agg, percentile_cont,
  mode (reference: src/expr/src/agg/{array_agg,string_agg,mode}.rs).

TPU-first placement rationale: these aggregates are inherently ragged
(per-group value multisets of unbounded, data-dependent size) — the same
reason VARCHAR contents live on the host. The hot fixed-lane aggregates
(count/sum/min/max/avg over append-only) stay on the device path
(ops/grouped_agg.py); the planner routes an agg here only when a call
*requires* materialized state (frontend/build.py).

State is one value-multiset (Counter) per (group, agg-call), persisted to a
StateTable as (group_key…, agg_idx, is_null, val_i, val_f) → count rows so
recovery rebuilds the exact multisets.
"""

from __future__ import annotations

import collections
from typing import Any, Optional, Sequence

from ..common.chunk import (
    DEFAULT_CHUNK_CAPACITY, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, StreamChunk, chunk_to_rows,
)
from ..common.types import (
    FLOAT64, GLOBAL_LIST_DICT, GLOBAL_STRING_DICT, INT64, Field, Schema,
)
from ..expr.agg import AggCall
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier
from .over_window import _emit_chunks

#: agg kinds that ALWAYS need materialized input (shared definition lives
#: on AggCall so the device executors' guards cannot drift)
MATERIALIZED_KINDS = AggCall.MATERIALIZED_KINDS


def call_needs_materialized(c: AggCall, input_append_only: bool) -> bool:
    """Does this call force the materialized-input executor?"""
    if c.lanes_unsupported:
        return True
    if c.kind in ("min", "max") and not input_append_only:
        return True
    return False


def materialized_agg_state_schema(key_fields: Sequence[Field]) -> Schema:
    """Durable multiset row: group key ⧺ (agg_idx, is_null, val_i, val_f,
    val_s, cnt). agg_idx == -1 carries the group's total row count (groups
    whose agg args are all NULL must still exist). One typed value column
    per storage class; VARCHAR values ride the _val_s column so the value
    encoding persists string CONTENT, not process-local dictionary ids
    (common/row.py process-independence contract)."""
    from ..common.types import VARCHAR
    return Schema(tuple(key_fields) + (
        Field("_agg_idx", INT64), Field("_is_null", INT64),
        Field("_val_i", INT64), Field("_val_f", FLOAT64),
        Field("_val_s", VARCHAR), Field("_cnt", INT64),
    ))


class _GroupState:
    __slots__ = ("total", "counters", "null_counts")

    def __init__(self, n_calls: int):
        self.total = 0                                  # live rows in group
        self.counters = [collections.Counter() for _ in range(n_calls)]
        self.null_counts = [0] * n_calls


class MaterializedAggExecutor(SingleInputExecutor):
    """Group-by aggregation with per-group materialized value multisets.
    ``group_keys == ()`` degrades to global (single-group) aggregation."""

    identity = "MaterializedAgg"

    def __init__(self, input: Executor, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None,
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY,
                 load_vnodes: Optional[tuple] = None):
        """``load_vnodes``: (vnode_start, vnode_end) owned by a SPANNING
        fragment actor — recovery reloads only rows in the owned range,
        so a store holding ranges a live migration moved away never
        resurrects them (meta/rescale.py, docs/scaling.md)."""
        super().__init__(input)
        self.group_keys = tuple(group_keys)
        self.load_vnodes = load_vnodes
        self.agg_calls = tuple(agg_calls)
        for c in self.agg_calls:
            if c.arg_type is not None and (c.arg_type.is_list
                                           or c.arg_type.is_struct):
                # list/struct dictionary ids are process-local; the
                # multiset value columns persist ints/floats/strings by
                # content but have no durable composite representation —
                # persisted raw ids would silently miscount DISTINCT/mode
                # after recovery
                raise ValueError(
                    f"{c.kind}() over an array column is not supported"
                    if c.arg_type.is_list else
                    f"{c.kind}() over a struct column is not supported")
        self.in_schema = input.schema
        self.state_table = state_table
        self.out_capacity = out_capacity
        key_fields = tuple(self.in_schema[i] for i in self.group_keys)
        self.schema = Schema(key_fields + tuple(
            Field(f"agg{i}", c.output_type)
            for i, c in enumerate(self.agg_calls)))
        #: per call: 'f' float, 's' string, 'i' everything else — selects
        #: which durable value column carries the multiset value
        self._arg_class = [
            "f" if (c.arg_type is not None and c.arg_type.is_float)
            else "s" if (c.arg_type is not None and c.arg_type.is_string)
            else "i"
            for c in self.agg_calls]
        self._groups: dict[tuple, _GroupState] = {}
        self._out: dict[tuple, tuple] = {}        # group -> last emitted row
        self._dirty: set = set()
        #: groups whose multiset changed since the last persisted snapshot
        self._ckpt_dirty: set = set()
        if state_table is not None:
            self._load_from_state_table()
        if not self.group_keys and () not in self._groups:
            # global aggregation always has its one group: the MV shows
            # count = 0 / NULLs before any input and after full
            # retraction (SimpleAggExecutor's first-barrier contract)
            self._groups[()] = _GroupState(len(self.agg_calls))
            self._dirty.add(())
            self._ckpt_dirty.add(())

    # -- input application ----------------------------------------------------

    async def map_chunk(self, chunk: StreamChunk):
        for op, row in chunk_to_rows(chunk, self.in_schema, with_ops=True,
                                     physical=True):
            self._apply_row(op, row)
        if False:
            yield

    def _apply_row(self, op: int, row: tuple) -> None:
        key = tuple(row[i] for i in self.group_keys)
        sign = 1 if op in (OP_INSERT, OP_UPDATE_INSERT) else -1
        g = self._groups.get(key)
        if g is None:
            if sign < 0:
                raise RuntimeError(
                    f"materialized agg: delete for unknown group {key}")
            g = self._groups[key] = _GroupState(len(self.agg_calls))
        g.total += sign
        for i, c in enumerate(self.agg_calls):
            if c.arg < 0:            # count(*): multiset not needed
                continue
            v = row[c.arg]
            if v is None:
                g.null_counts[i] += sign
                continue
            g.counters[i][v] += sign
            if g.counters[i][v] == 0:
                del g.counters[i][v]
            elif g.counters[i][v] < 0:
                raise RuntimeError(
                    "materialized agg: negative multiplicity for value "
                    f"{v!r} in group {key} (unpaired retraction)")
        if g.total < 0:
            raise RuntimeError(
                f"materialized agg: negative row count in group {key}")
        self._dirty.add(key)
        self._ckpt_dirty.add(key)

    # -- output computation ---------------------------------------------------

    def _eval_call(self, i: int, c: AggCall, g: _GroupState):
        """(physical_value | None) for call i over the group multiset."""
        counter = g.counters[i]
        if c.kind == "count":
            if c.arg < 0:
                return g.total
            if c.distinct:
                return len(counter)
            return sum(counter.values())
        if c.kind == "approx_count_distinct":
            # a call that normally lives on the device HLL lanes can be
            # routed here when ANY sibling call needs materialized input
            # (frontend/build.py sends the whole agg); the multiset is
            # already exact, and an exact distinct count is a valid
            # superset of the approximate contract
            return len(counter)
        if c.kind == "array_agg" and (counter or g.null_counts[i]):
            pass                     # NULL elements alone still aggregate
        elif not counter:
            return None              # every arg NULL (or group empty)
        if c.kind == "sum":
            if c.distinct:
                return sum(counter.keys())
            return sum(v * n for v, n in counter.items())
        if c.kind == "avg":
            if c.distinct:
                return float(sum(counter.keys())) / len(counter)
            n = sum(counter.values())
            return float(sum(v * m for v, m in counter.items())) / n
        if c.kind in ("min", "max"):
            agg_fn = min if c.kind == "min" else max
            if c.arg_type is not None and c.arg_type.is_string:
                # dictionary ids are insertion-ordered; compare contents
                return agg_fn(counter.keys(),
                              key=lambda i_: GLOBAL_STRING_DICT.lookup(i_))
            return agg_fn(counter.keys())
        if c.kind == "mode":
            # PG: the most frequent value; ties broken by smallest value
            # for determinism (PG leaves tie order unspecified)
            maxn = max(counter.values())
            cands = [v for v, n in counter.items() if n == maxn]
            if c.arg_type is not None and c.arg_type.is_string:
                return min(cands, key=lambda i_: GLOBAL_STRING_DICT.lookup(i_))
            return min(cands)
        if c.kind == "percentile_cont":
            frac = float(c.extra if c.extra is not None else 0.5)
            vals: list = []
            for v, n in sorted(counter.items()):
                vals.extend([v] * n)
            idx = frac * (len(vals) - 1)
            lo = int(idx)
            hi = min(lo + 1, len(vals) - 1)
            return vals[lo] + (vals[hi] - vals[lo]) * (idx - lo)
        if c.kind == "array_agg":
            # order unspecified in PG without ORDER BY; emit ascending by
            # PYTHON value for determinism (divergence documented); NULL
            # elements (PG keeps them) trail the sorted values
            assert c.arg_type is not None
            conv = c.arg_type.to_python
            out: list = []
            for v, n in sorted(
                    ((conv(v), n) for v, n in counter.items())):
                out.extend([v] * n)
            out.extend([None] * g.null_counts[i])
            return GLOBAL_LIST_DICT.intern(out)
        if c.kind == "string_agg":
            delim = c.extra if c.extra is not None else ""
            parts: list = []
            for v, n in sorted(
                    counter.items(),
                    key=lambda kv: GLOBAL_STRING_DICT.lookup(kv[0])):
                parts.extend([GLOBAL_STRING_DICT.lookup(v)] * n)
            return GLOBAL_STRING_DICT.intern(delim.join(parts))
        raise ValueError(f"unsupported materialized agg kind {c.kind!r}")

    def _group_row(self, key: tuple, g: _GroupState) -> tuple:
        return key + tuple(self._eval_call(i, c, g)
                           for i, c in enumerate(self.agg_calls))

    async def on_barrier(self, barrier: Barrier):
        pairs: list = []
        for key in sorted(self._dirty, key=repr):
            g = self._groups.get(key)
            old = self._out.get(key)
            if (g is None or g.total == 0) and self.group_keys:
                self._groups.pop(key, None)
                if old is not None:
                    pairs.append((OP_DELETE, old))
                    del self._out[key]
                continue
            if g is None:                     # global group never dies
                g = self._groups[key] = _GroupState(len(self.agg_calls))
            new = self._group_row(key, g)
            if old is None:
                pairs.append((OP_INSERT, new))
            elif old != new:
                pairs.append((OP_UPDATE_DELETE, old))
                pairs.append((OP_UPDATE_INSERT, new))
            self._out[key] = new
        self._dirty.clear()
        for chunk in _emit_chunks(self.schema, pairs, self.out_capacity):
            yield chunk
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint(barrier.epoch.curr)

    # -- persistence ----------------------------------------------------------

    def _state_rows(self, key: tuple, g: _GroupState) -> list:
        rows = [key + (-1, 0, 0, 0.0, 0, g.total)]
        for i, c in enumerate(self.agg_calls):
            if c.arg < 0:
                continue
            if g.null_counts[i]:
                rows.append(key + (i, 1, 0, 0.0, 0, g.null_counts[i]))
            cls = self._arg_class[i]
            for v, n in g.counters[i].items():
                if cls == "f":
                    rows.append(key + (i, 0, 0, float(v), 0, n))
                elif cls == "s":
                    rows.append(key + (i, 0, 0, 0.0, int(v), n))
                else:
                    rows.append(key + (i, 0, int(v), 0.0, 0, n))
        return rows

    def _checkpoint(self, epoch: int) -> None:
        st = self.state_table
        assert st is not None
        for key in self._ckpt_dirty:
            # multiset rows are keyed by value: stale counts must be
            # removed explicitly, so replay the group wholesale
            for row in st.scan_prefix(key, len(self.group_keys)):
                st.delete(row)
            g = self._groups.get(key)
            if g is not None and (g.total > 0 or not self.group_keys):
                for row in self._state_rows(key, g):
                    st.insert(row)
        self._ckpt_dirty.clear()
        st.commit(epoch)

    def _load_from_state_table(self) -> None:
        nk = len(self.group_keys)
        rows = list(self.state_table.scan_all())
        if rows and nk and self.load_vnodes is not None:
            from ..common.hashing import filter_rows_vnodes
            key_types = [self.in_schema[i].type for i in self.group_keys]
            s, e = self.load_vnodes
            rows = filter_rows_vnodes(key_types, rows, s, e)
        for row in rows:
            key = tuple(row[:nk])
            agg_idx, is_null, val_i, val_f, val_s, cnt = row[nk:nk + 6]
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _GroupState(len(self.agg_calls))
            if agg_idx == -1:
                g.total = cnt
            elif is_null:
                g.null_counts[agg_idx] = cnt
            else:
                cls = self._arg_class[agg_idx]
                v = val_f if cls == "f" else val_s if cls == "s" else val_i
                g.counters[agg_idx][v] = cnt
        for key, g in self._groups.items():
            self._out[key] = self._group_row(key, g)
