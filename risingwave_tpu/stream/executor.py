"""Executor protocol: async stream transformers over Messages.

Counterpart of the reference's ``Executor`` trait
(reference: src/stream/src/executor/mod.rs:170-206): every operator is an
async generator of ``Message`` (chunk / barrier / watermark). Barriers flow
through every executor and *must* be yielded after the executor has applied
all chunks of the closing epoch to its state — that ordering is what makes
the barrier a consistent cut (Chandy-Lamport, docs/checkpoint.md).

The TPU twist: an executor's per-chunk work is a jitted, functionally-pure
step over (device_state, chunk) — the async generator is only the host
control loop. Invariant-checking wrappers mirror the reference's
executor/wrapper/{schema_check,epoch_check,update_check}.rs and are enabled
in tests/sim runs.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional, Sequence

from ..common.chunk import (
    ChunkBatch, OP_UPDATE_DELETE, OP_UPDATE_INSERT, StreamChunk, chunk_to_rows,
)
from ..common.types import Schema
from .message import Barrier, Message, Watermark


class Executor:
    """Base class. ``schema`` describes the output chunks."""

    schema: Schema
    identity: str = "Executor"

    def execute(self) -> AsyncIterator[Message]:
        raise NotImplementedError


class SingleInputExecutor(Executor):
    """Common shape: transform one upstream, pass barriers/watermarks through.

    Subclasses override ``map_chunk`` (1→0..n chunks) and optionally
    ``on_barrier`` (flush state, emit pending output *before* the barrier)."""

    def __init__(self, input: Executor):
        self.input = input
        from .metrics import ExecutorStats
        self.stats = ExecutorStats()

    async def map_chunk(self, chunk: StreamChunk):
        yield chunk

    async def map_chunk_batch(self, batch: ChunkBatch):
        """Batched ingest. Default: unstack and run per-chunk (correct for
        every executor); override with a scanned/vmapped single-dispatch step
        where throughput matters."""
        for i in range(batch.num_chunks):
            async for out in self.map_chunk(batch.at(i)):
                yield out

    async def on_barrier(self, barrier: Barrier):
        if False:  # pragma: no cover - async generator shape
            yield

    async def on_watermark(self, watermark: Watermark):
        yield watermark

    async def execute(self) -> AsyncIterator[Message]:
        from .metrics import barrier_timer
        stats = self.stats
        async for msg in self.input.execute():
            if isinstance(msg, StreamChunk):
                stats.chunks_in += 1
                stats.capacity_rows_in += msg.capacity
                async for out in self.map_chunk(msg):
                    stats.chunks_out += 1
                    yield out
            elif isinstance(msg, ChunkBatch):
                stats.batches_in += 1
                stats.batch_chunks_in += msg.num_chunks
                stats.capacity_rows_in += msg.num_chunks * msg.chunk_capacity
                async for out in self.map_chunk_batch(msg):
                    stats.chunks_out += 1
                    yield out
            elif isinstance(msg, Barrier):
                with barrier_timer(stats, self.identity, msg.epoch.curr):
                    outs = [out async for out in self.on_barrier(msg)]
                for out in outs:
                    stats.chunks_out += 1
                    yield out
                yield msg
                if msg.is_stop():
                    return
            elif isinstance(msg, Watermark):
                stats.watermarks += 1
                async for out in self.on_watermark(msg):
                    yield out


# ---------------------------------------------------------------------------
# Invariant wrappers (reference: src/stream/src/executor/wrapper/)
# ---------------------------------------------------------------------------


class EpochCheckExecutor(SingleInputExecutor):
    """Barrier epochs must strictly increase (wrapper/epoch_check.rs)."""

    def __init__(self, input: Executor):
        super().__init__(input)
        self.schema = input.schema
        self.identity = input.identity
        self._last_epoch: Optional[int] = None

    async def on_barrier(self, barrier: Barrier):
        if self._last_epoch is not None and barrier.epoch.curr <= self._last_epoch:
            raise AssertionError(
                f"epoch regression: {barrier.epoch.curr} after {self._last_epoch} "
                f"at {self.identity}"
            )
        self._last_epoch = barrier.epoch.curr
        if False:
            yield


class SchemaCheckExecutor(SingleInputExecutor):
    """Every chunk's column count + physical dtypes must match the
    executor's declared schema (wrapper/schema_check.rs) — catches
    builder wiring bugs before they corrupt downstream state."""

    def __init__(self, input: Executor):
        super().__init__(input)
        self.schema = input.schema
        self.identity = input.identity

    async def map_chunk(self, chunk: StreamChunk):
        if len(chunk.columns) != len(self.schema):
            raise AssertionError(
                f"schema check at {self.identity}: chunk has "
                f"{len(chunk.columns)} columns, schema has "
                f"{len(self.schema)}")
        for i, (col, field) in enumerate(zip(chunk.columns, self.schema)):
            want = field.type.dtype
            import jax.numpy as jnp
            if jnp.dtype(col.data.dtype) != jnp.dtype(want):
                raise AssertionError(
                    f"schema check at {self.identity}: column {i} "
                    f"({field.name}) is {col.data.dtype}, schema says "
                    f"{jnp.dtype(want)}")
        yield chunk


class UpdateCheckExecutor(SingleInputExecutor):
    """UpdateDelete must be immediately followed by UpdateInsert within a
    chunk (wrapper/update_check.rs)."""

    def __init__(self, input: Executor):
        super().__init__(input)
        self.schema = input.schema
        self.identity = input.identity

    async def map_chunk(self, chunk: StreamChunk):
        rows = chunk_to_rows(chunk, self.schema, with_ops=True)
        pending_ud = False
        for op, _ in rows:
            if pending_ud and op != OP_UPDATE_INSERT:
                raise AssertionError(f"U- not followed by U+ at {self.identity}")
            pending_ud = op == OP_UPDATE_DELETE
        if pending_ud:
            raise AssertionError(f"chunk ends with dangling U- at {self.identity}")
        yield chunk


def wrap_debug(executor: Executor) -> Executor:
    """Compose the sanity wrappers (debug/sim runs)."""
    return EpochCheckExecutor(UpdateCheckExecutor(executor))


async def collect_until_barrier(stream, n_barriers: int = 1):
    """Test helper: drain messages until the n-th barrier; returns (chunks,
    barriers, watermarks)."""
    chunks: list[StreamChunk] = []
    barriers: list[Barrier] = []
    watermarks: list[Watermark] = []
    async for msg in stream:
        if isinstance(msg, StreamChunk):
            chunks.append(msg)
        elif isinstance(msg, Barrier):
            barriers.append(msg)
            if len(barriers) >= n_barriers:
                break
        else:
            watermarks.append(msg)
    return chunks, barriers, watermarks
