"""HopWindowExecutor — HOP (sliding) window expansion.

Counterpart of the reference's HopWindowExecutor
(reference: src/stream/src/executor/hop_window.rs; TUMBLE needs no executor —
it is a plain projection, which the planner lowers to Project with
``tumble_start``). Each row falls into ``n = window_size / window_slide``
hop windows; the executor emits n output chunks per input chunk — one per
hop offset, same static capacity, visibility-masked — so shapes stay static
and XLA compiles the expansion once (SURVEY.md §7 static-shape rule).

Output schema: input columns + window_start + window_end (both TIMESTAMP),
matching the reference's output layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import TIMESTAMP, Field, Schema
from .executor import Executor, SingleInputExecutor


class HopWindowExecutor(SingleInputExecutor):
    identity = "HopWindow"

    def __init__(self, input: Executor, time_col: int, window_slide: int,
                 window_size: int):
        super().__init__(input)
        if window_size % window_slide != 0:
            raise ValueError(
                f"window_size {window_size} must be a multiple of "
                f"window_slide {window_slide} (reference parity: hop_window.rs "
                "requires units == size/slide)")
        self.time_col = time_col
        self.slide = window_slide
        self.size = window_size
        self.n_windows = window_size // window_slide
        self.schema = Schema(tuple(input.schema) + (
            Field("window_start", TIMESTAMP), Field("window_end", TIMESTAMP)))

        @jax.jit
        def _expand(chunk: StreamChunk):
            col = chunk.columns[self.time_col]
            ts = col.data.astype(jnp.int64)
            # first (earliest) hop window containing ts starts at
            # tumble(ts, slide) - (n-1)*slide; the i-th candidate start is
            # tumble(ts, slide) - i*slide, valid while ts < start + size
            base = (ts // self.slide) * self.slide
            outs = []
            for i in range(self.n_windows):
                ws = base - (self.n_windows - 1 - i) * self.slide
                we = ws + self.size
                valid = col.mask & (ts < we) & (ts >= ws)
                cols = chunk.columns + (
                    Column(ws, valid), Column(we, valid))
                outs.append(chunk.replace(
                    vis=chunk.vis & valid, columns=cols))
            return tuple(outs)

        self._expand = _expand

    async def map_chunk(self, chunk: StreamChunk):
        for out in self._expand(chunk):
            if bool(jnp.any(out.vis)):
                yield out
