"""SimpleAgg / StatelessSimpleAgg — global (single-group) aggregation.

Counterparts of the reference's SimpleAggExecutor and
StatelessSimpleAggExecutor (reference: src/stream/src/executor/simple_agg.rs,
src/stream/src/executor/stateless_simple_agg.rs). SimpleAgg keeps one group's
lanes as device scalars and emits its first row on the first barrier (the MV
of ``SELECT count(*) …`` shows 0 before any input — reference
simple_agg.rs's AggGroup with prev_outputs=None). StatelessSimpleAgg is the
shuffle-free local phase of 2-phase aggregation: one partial-delta row per
chunk, always op Insert (downstream global agg combines via signed sums).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import (
    OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, Column, StreamChunk,
)
from ..common.types import Field, Schema
from ..expr.agg import AggCall
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


@struct.dataclass
class SimpleAggState:
    lanes: tuple[jax.Array, ...]       # scalars; lane 0 = row count
    prev_lanes: tuple[jax.Array, ...]
    dirty: jax.Array                   # bool scalar
    ever_emitted: jax.Array            # bool scalar


class _AggLanes:
    """Shared lane layout/update logic for the two global-agg executors."""

    def __init__(self, agg_calls: Sequence[AggCall]):
        for c in agg_calls:
            if c.lanes_unsupported:
                raise ValueError(
                    f"{c.kind}{'(distinct)' if c.distinct else ''} needs "
                    "materialized-input state (stream/materialized_agg.py)")
        self.agg_calls = tuple(agg_calls)
        self.lane_dtypes = [jnp.int64]
        self.call_lane_ofs = []
        for c in self.agg_calls:
            self.call_lane_ofs.append(len(self.lane_dtypes))
            self.lane_dtypes.extend(c.state_dtypes())

    def init_lanes(self) -> tuple[jax.Array, ...]:
        lanes = [jnp.zeros((), jnp.int64)]
        for c in self.agg_calls:
            for v, dt in zip(c.init_lanes(), c.state_dtypes()):
                lanes.append(jnp.asarray(v, dt))
        return tuple(lanes)

    def chunk_deltas(self, chunk: StreamChunk,
                     str_ranks=None) -> tuple[jax.Array, ...]:
        """Per-chunk reduction of contributions → one delta per lane.

        String MIN/MAX deltas stay in packed rank|id space — merge()
        unpacks after combining with the stored lane, within the same
        evaluation (same rank-table version)."""
        signs = chunk.signs()
        deltas = [jnp.sum(signs).astype(jnp.int64)]
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            if call.arg >= 0:
                col = chunk.columns[call.arg]
                value, vmask = col.data, col.mask & chunk.vis
            else:
                value = jnp.zeros_like(signs)
                vmask = chunk.vis
            for contrib, op in zip(
                    call.contributions(value, vmask, signs, str_ranks),
                    call.reduce_ops()):
                if op == "add":
                    deltas.append(jnp.sum(contrib))
                elif op == "min":
                    deltas.append(jnp.min(contrib))
                else:
                    deltas.append(jnp.max(contrib))
        return tuple(deltas)

    def merge(self, lanes, deltas, str_ranks=None) -> tuple[jax.Array, ...]:
        out = [lanes[0] + deltas[0]]
        i = 1
        for call in self.agg_calls:
            for op in call.reduce_ops():
                if op == "add":
                    out.append(lanes[i] + deltas[i])
                elif op == "min":
                    out.append(call.unpack_lane(jnp.minimum(
                        call.pack_lane(lanes[i], str_ranks), deltas[i])))
                else:
                    out.append(call.unpack_lane(jnp.maximum(
                        call.pack_lane(lanes[i], str_ranks), deltas[i])))
                i += 1
        return tuple(out)

    def outputs(self, lanes) -> list[tuple[jax.Array, jax.Array]]:
        live = lanes[0] > 0
        outs = []
        for call, ofs in zip(self.agg_calls, self.call_lane_ofs):
            call_lanes = [lanes[ofs + j] for j in range(call.num_lanes)]
            data, mask = call.output(call_lanes, live)
            outs.append((data.astype(call.output_type.dtype), mask))
        return outs

    def out_schema(self) -> Schema:
        return Schema(tuple(
            Field(f"agg{i}", c.output_type) for i, c in enumerate(self.agg_calls)
        ))


def simple_agg_state_schema(agg_calls: Sequence[AggCall]) -> Schema:
    """Schema of the durable simple-agg state row: id pk, raw lanes, flag.
    The single source of truth for the arity the checkpoint row carries —
    value encoding is schema-driven, so a short hand-built schema silently
    truncates state."""
    from ..common.types import FLOAT64, INT64
    lanes = [Field("id", INT64)]
    for i, dt in enumerate(_AggLanes(agg_calls).lane_dtypes):
        lanes.append(Field(f"l{i}", INT64 if dt == jnp.int64 else FLOAT64))
    lanes.append(Field("flag", INT64))
    return Schema(tuple(lanes))


class SimpleAggExecutor(SingleInputExecutor):
    """Global aggregation: output is always exactly one logical row."""

    identity = "SimpleAgg"

    def __init__(self, input: Executor, agg_calls: Sequence[AggCall],
                 state_table: Optional[StateTable] = None):
        super().__init__(input)
        self.lanes_def = _AggLanes(agg_calls)
        self.agg_calls = self.lanes_def.agg_calls
        self.schema = self.lanes_def.out_schema()
        self.state_table = state_table
        self.state = SimpleAggState(
            lanes=self.lanes_def.init_lanes(),
            prev_lanes=self.lanes_def.init_lanes(),
            dirty=jnp.zeros((), jnp.bool_),
            ever_emitted=jnp.zeros((), jnp.bool_),
        )
        self._apply = jax.jit(self._apply_impl)
        self._flush = jax.jit(self._flush_impl)
        if state_table is not None:
            self._load_from_state_table()

    def _apply_impl(self, state: SimpleAggState, chunk: StreamChunk,
                    str_ranks=None):
        deltas = self.lanes_def.chunk_deltas(chunk, str_ranks)
        any_row = chunk.cardinality() > 0
        return state.replace(
            lanes=self.lanes_def.merge(state.lanes, deltas, str_ranks),
            dirty=state.dirty | any_row,
        )

    def _flush_impl(self, state: SimpleAggState):
        """Returns (new_state, chunk-of-2-rows): row 0 = U- of prev values
        (vis only if previously emitted), row 1 = U+/Insert of current."""
        emit = state.dirty | ~state.ever_emitted
        prev_outs = self.lanes_def.outputs(state.prev_lanes)
        cur_outs = self.lanes_def.outputs(state.lanes)
        ops = jnp.array([OP_UPDATE_DELETE, OP_UPDATE_INSERT], jnp.int8)
        ops = jnp.where(
            state.ever_emitted, ops,
            jnp.array([OP_UPDATE_DELETE, OP_INSERT], jnp.int8))
        vis = jnp.stack([state.ever_emitted & emit, emit])
        cols = tuple(
            Column(jnp.stack([pd, cd]), jnp.stack([pm, cm]))
            for (pd, pm), (cd, cm) in zip(prev_outs, cur_outs)
        )
        chunk = StreamChunk(ops, vis, cols)
        new_state = state.replace(
            prev_lanes=state.lanes,
            dirty=jnp.zeros((), jnp.bool_),
            ever_emitted=state.ever_emitted | emit,
        )
        return new_state, chunk

    async def map_chunk(self, chunk: StreamChunk):
        str_ranks = None
        if any(c.is_string_minmax for c in self.agg_calls):
            from ..common.types import GLOBAL_STRING_DICT
            str_ranks = GLOBAL_STRING_DICT.device_ranks()
        self.state = self._apply(self.state, chunk, str_ranks)
        if False:
            yield

    async def on_barrier(self, barrier: Barrier):
        self.state, chunk = self._flush(self.state)
        if bool(jnp.any(chunk.vis)):
            yield chunk
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint(barrier.epoch.curr)

    # -- persistence ----------------------------------------------------------

    def _checkpoint(self, epoch: int) -> None:
        row = tuple(l.item() for l in self.state.lanes) + (
            bool(self.state.ever_emitted),)
        self.state_table.insert((0,) + row)
        self.state_table.commit(epoch)

    def _load_from_state_table(self) -> None:
        rows = list(self.state_table.scan_all())
        if not rows:
            return
        row = rows[0]
        lanes = tuple(
            jnp.asarray(v, dt) for v, dt in zip(row[1:], self.lanes_def.lane_dtypes)
        )
        self.state = self.state.replace(
            lanes=lanes, prev_lanes=lanes,
            ever_emitted=jnp.asarray(bool(row[1 + len(lanes)]), jnp.bool_),
        )


class StatelessSimpleAggExecutor(SingleInputExecutor):
    """Local (pre-shuffle) agg phase: one partial-delta Insert row per chunk
    (reference: stateless_simple_agg.rs — StatelessSimpleAgg has no state and
    emits chunk-local partials; only sum/count shapes are retraction-safe)."""

    identity = "StatelessSimpleAgg"

    def __init__(self, input: Executor, agg_calls: Sequence[AggCall]):
        super().__init__(input)
        for c in agg_calls:
            if c.needs_append_only or c.kind == "avg":
                raise ValueError(
                    f"stateless agg cannot emit {c.kind} partials; the "
                    "planner must split it (avg -> sum+count)")
        self.lanes_def = _AggLanes(agg_calls)
        self.agg_calls = self.lanes_def.agg_calls
        self.schema = self.lanes_def.out_schema()
        self._step = jax.jit(self._step_impl)

    def _step_impl(self, chunk: StreamChunk):
        """Emit RAW partial deltas (count deltas may be negative, sums are
        signed) — the downstream global agg combines them; applying the SQL
        output projection here would lose retraction information."""
        deltas = self.lanes_def.chunk_deltas(chunk)
        outs = []
        for call, ofs in zip(self.agg_calls, self.lanes_def.call_lane_ofs):
            data = deltas[ofs].astype(call.output_type.dtype)
            if call.arg >= 0:
                col = chunk.columns[call.arg]
                mask = jnp.any(col.mask & chunk.vis)
            else:
                mask = jnp.ones((), jnp.bool_)
            outs.append((data, mask))
        any_row = chunk.cardinality() > 0
        ops = jnp.zeros(1, jnp.int8)
        vis = jnp.stack([any_row])
        cols = tuple(Column(d[None], m[None]) for d, m in outs)
        return StreamChunk(ops, vis, cols)

    async def map_chunk(self, chunk: StreamChunk):
        out = self._step(chunk)
        if bool(jnp.any(out.vis)):
            yield out
