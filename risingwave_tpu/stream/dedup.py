"""AppendOnlyDedupExecutor — drop duplicate pks from an append-only stream.

Counterpart of the reference's AppendOnlyDedupExecutor
(reference: src/stream/src/executor/dedup/append_only_dedup.rs). The seen-key
set is a device hash table; a whole chunk dedups in one step — the scatter-min
claim in ht_lookup_or_insert already makes the FIRST row of each new key the
winner (`is_new`), which is exactly SQL's keep-first semantics.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import OP_INSERT, StreamChunk, physical_chunk
from ..ops.hash_table import ht_lookup_or_insert, ht_new
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


class AppendOnlyDedupExecutor(SingleInputExecutor):
    identity = "AppendOnlyDedup"

    def __init__(self, input: Executor, pk_indices: Sequence[int],
                 state_table: Optional[StateTable] = None,
                 table_capacity: int = 1 << 16):
        super().__init__(input)
        self.schema = input.schema
        self.pk_indices = tuple(pk_indices)
        self.capacity = table_capacity
        self.state_table = state_table
        pk_types = [input.schema[i].type for i in self.pk_indices]
        self.table = ht_new(pk_types, table_capacity)
        self.ckpt_dirty = jnp.zeros(table_capacity, jnp.bool_)
        self.overflow = jnp.zeros((), jnp.bool_)
        self.saw_delete = jnp.zeros((), jnp.bool_)

        @jax.jit
        def _step(table, ckpt_dirty, chunk: StreamChunk):
            keys = [chunk.columns[i] for i in self.pk_indices]
            table, slots, is_new, ovf = ht_lookup_or_insert(
                table, keys, chunk.vis)
            mark = jnp.where(is_new, slots, self.capacity)
            ckpt_dirty = ckpt_dirty.at[mark].set(True, mode="drop")
            bad = jnp.any(chunk.vis & (chunk.ops != OP_INSERT))
            return table, ckpt_dirty, chunk.mask_vis(is_new), ovf, bad

        self._step = _step
        if state_table is not None:
            self._load_from_state_table()

    async def map_chunk(self, chunk: StreamChunk):
        self.table, self.ckpt_dirty, out, ovf, bad = self._step(
            self.table, self.ckpt_dirty, chunk)
        self.overflow = self.overflow | ovf
        self.saw_delete = self.saw_delete | bad
        if bool(jnp.any(out.vis)):
            yield out

    async def on_barrier(self, barrier: Barrier):
        if bool(self.overflow):
            raise RuntimeError(
                f"{self.identity}: key table overflow (capacity "
                f"{self.capacity})")
        if bool(self.saw_delete):
            raise RuntimeError(
                f"{self.identity}: non-insert op on append-only input")
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint(barrier.epoch.curr)
        if False:
            yield

    # -- persistence (durable row = pk values only) ---------------------------

    def _checkpoint(self, epoch: int) -> None:
        idx = np.nonzero(np.asarray(self.ckpt_dirty))[0]
        if len(idx):
            datas = [np.asarray(d)[idx] for d in self.table.key_data]
            masks = [np.asarray(m)[idx] for m in self.table.key_mask]
            for r in range(len(idx)):
                self.state_table.insert(tuple(
                    datas[c][r].item() if masks[c][r] else None
                    for c in range(len(datas))))
            self.state_table.commit(epoch)
        self.ckpt_dirty = jnp.zeros_like(self.ckpt_dirty)

    def _load_from_state_table(self) -> None:
        pk_schema = type(self.schema)(tuple(
            self.schema[i] for i in self.pk_indices))
        rows = list(self.state_table.scan_all())
        bs = 1024
        ident = list(range(len(self.pk_indices)))
        for i in range(0, len(rows), bs):
            chunk = physical_chunk(pk_schema, rows[i:i + bs], bs)
            keys = [chunk.columns[j] for j in ident]
            self.table, _, _, ovf = ht_lookup_or_insert(
                self.table, keys, chunk.vis)
            if bool(ovf):
                raise RuntimeError("dedup table overflow during recovery")
        self.ckpt_dirty = jnp.zeros_like(self.ckpt_dirty)
