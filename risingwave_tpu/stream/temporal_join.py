"""TemporalJoinExecutor: probe-time lookup against a materialized table.

Counterpart of the reference's TemporalJoin / Lookup executors
(reference: src/stream/src/executor/temporal_join.rs:352, executor/
lookup.rs — ``FOR SYSTEM_TIME AS OF PROCTIME()``). Unlike the symmetric
hash join, the stream side keeps NO join state and table-side updates
produce NO retractions: each probe row is enriched with the table's rows
*as of processing time* and the output is append-only with respect to the
table side. This is the cheap pattern for enrichment joins (orders ⋈
current price) where replaying history on a dimension change is unwanted.

The table side is read straight from its StateTable (the session drives
table jobs and the probe job in the same epoch loop; probe rows of epoch
N see the table as of the epoch's processing order — process-time
semantics, exactly as loose as the reference's). The probe side must be
APPEND-ONLY: a delete's enrichment would be recomputed from the table's
current rows and could fail to cancel what was originally emitted.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..common.chunk import (
    DEFAULT_CHUNK_CAPACITY, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
    OP_UPDATE_INSERT, StreamChunk, chunk_to_rows, make_chunk,
)
from ..common.types import Field, Schema
from ..expr.expr import Expr
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor


class TemporalJoinExecutor(SingleInputExecutor):
    identity = "TemporalJoin"

    def __init__(self, input: Executor, right_table: StateTable,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 outer: bool = False, condition: Optional[Expr] = None,
                 out_capacity: int = DEFAULT_CHUNK_CAPACITY):
        super().__init__(input)
        self.right_table = right_table
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.outer = outer
        self.condition = condition
        self.out_capacity = out_capacity
        self.in_schema = input.schema
        self.schema = Schema(tuple(input.schema)
                             + tuple(right_table.schema))
        # fast path: probing by the table's full pk is a point get;
        # otherwise a (rare) prefix/full scan per probe key
        self._point_lookup = (self.right_keys
                              == tuple(right_table.pk_indices))

    def _matches(self, key_vals, index) -> list:
        if any(v is None for v in key_vals):
            return []
        if self._point_lookup:
            row = self.right_table.get_row(key_vals)
            return [row] if row is not None else []
        return index.get(tuple(key_vals), [])

    def _build_index(self) -> dict:
        """Non-pk probe keys: one table pass per chunk, not per row."""
        index: dict = {}
        for r in self.right_table.scan_all():
            index.setdefault(
                tuple(r[i] for i in self.right_keys), []).append(r)
        return index

    async def map_chunk(self, chunk: StreamChunk):
        index = None if self._point_lookup else self._build_index()
        out_rows, out_ops = [], []
        nright = len(self.right_table.schema)
        for op, row in chunk_to_rows(chunk, self.in_schema, with_ops=True,
                                     physical=True):
            # append-only probe contract (the reference requires it too):
            # a DELETE's enrichment would be recomputed from the table's
            # CURRENT rows, which may differ from what was emitted at
            # insert time — the retraction would not cancel the original
            if op != OP_INSERT:
                raise AssertionError(
                    "temporal join requires an append-only probe side "
                    "(got a delete/update); join a snapshot instead")
            keys = [row[i] for i in self.left_keys]
            matches = self._matches(keys, index)
            if not matches and self.outer:
                out_rows.append(tuple(row) + (None,) * nright)
                out_ops.append(op)
            for m in matches:
                out_rows.append(tuple(row) + tuple(m))
                out_ops.append(op)
        i = 0
        while i < len(out_rows):
            take_r = out_rows[i:i + self.out_capacity]
            take_o = out_ops[i:i + self.out_capacity]
            i += len(take_r)
            chunk_out = make_chunk(
                self.schema, take_r, ops=take_o,
                capacity=max(self.out_capacity, len(take_r)),
                physical=True)
            if self.condition is not None:
                cond = self.condition.eval(chunk_out)
                import jax.numpy as jnp
                keep = cond.data & cond.mask
                chunk_out = chunk_out.mask_vis(keep)
            yield chunk_out
