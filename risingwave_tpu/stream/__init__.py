from .message import (  # noqa: F401
    Barrier, EpochPair, Message, Mutation, MutationKind, Watermark, is_chunk,
)
from .executor import (  # noqa: F401
    EpochCheckExecutor, Executor, SingleInputExecutor, UpdateCheckExecutor,
    collect_until_barrier, wrap_debug,
)
from .source import MockSource, ScheduledSource  # noqa: F401
from .project import FilterExecutor, ProjectExecutor  # noqa: F401
from .hash_agg import HashAggExecutor, agg_state_schema  # noqa: F401
from .materialize import MaterializeExecutor  # noqa: F401
from .hash_join import HashJoinExecutor  # noqa: F401
from .barrier_align import barrier_align  # noqa: F401
