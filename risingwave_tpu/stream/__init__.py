from .message import (  # noqa: F401
    Barrier, EpochPair, Message, Mutation, MutationKind, Watermark, is_chunk,
)
from .executor import (  # noqa: F401
    EpochCheckExecutor, Executor, SchemaCheckExecutor, SingleInputExecutor,
    UpdateCheckExecutor,
    collect_until_barrier, wrap_debug,
)
from .source import MockSource, ScheduledSource  # noqa: F401
from .project import FilterExecutor, ProjectExecutor  # noqa: F401
from .hash_agg import HashAggExecutor, agg_state_schema  # noqa: F401
from .materialize import MaterializeExecutor  # noqa: F401
from .hash_join import HashJoinExecutor  # noqa: F401
from .barrier_align import barrier_align  # noqa: F401
from .simple_agg import (  # noqa: F401
    SimpleAggExecutor, StatelessSimpleAggExecutor,
)
from .top_n import TopNExecutor  # noqa: F401
from .dynamic_filter import DynamicFilterExecutor  # noqa: F401
from .barrier_align import align_streams  # noqa: F401
from .hop_window import HopWindowExecutor  # noqa: F401
from .union import UnionExecutor, ValuesExecutor  # noqa: F401
from .dedup import AppendOnlyDedupExecutor  # noqa: F401
from .row_id_gen import RowIdGenExecutor  # noqa: F401
from .expand import ExpandExecutor  # noqa: F401
from .eowc import (  # noqa: F401
    NowExecutor, SortExecutor, WatermarkFilterExecutor,
)
