"""TopN executor family: plain / group, ±append-only, WITH TIES.

Counterpart of the reference's four TopN executors
(reference: src/stream/src/executor/top_n/{top_n_plain,group_top_n,
top_n_appendonly,group_top_n_appendonly}.rs over TopNCache
top_n/top_n_cache.rs:43). One implementation covers the whole family here:
the device row set (ops/row_set.py) absorbs chunks with last-writer-wins
upserts, and each barrier recomputes the rank window by a full device sort
(ops/topn.py) and emits the membership/value diff. Append-only inputs need
no special path (deletes simply never arrive); the flag only gates the
sanity check. GroupTopN = TopN with a group-key hash table assigning a gid
per row; ranks are computed per-gid segment in the same sort.

Output schema = input schema (the reference emits the full row; ordering of
emitted chunks is not significant downstream).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..common.chunk import DEFAULT_CHUNK_CAPACITY, StreamChunk
from ..ops.hash_table import DeviceHashTable, ht_lookup_or_insert, ht_new
from ..common.chunk import physical_chunk
from ..ops.row_set import (
    RowSetState, rs_apply_chunk, rs_changed, rs_checkpoint, rs_finish_flush,
    rs_gather_delta, rs_new,
)
from ..ops.topn import (
    OrderSpec, _key_sentinels, key0_dtype, topn_candidate_flush,
    topn_in_set, topn_refill,
)
from ..storage.state_table import StateTable
from .executor import Executor, SingleInputExecutor
from .message import Barrier


@struct.dataclass
class TopNState:
    rows: RowSetState
    group_table: DeviceHashTable   # group key -> gid (own slot index)
    gid: jax.Array                 # int32[cap]: group slot per row
    cand: jax.Array                # bool[cap]: incremental candidate slots
    t1: jax.Array                  # scalar: forget threshold (leading key)


class TopNExecutor(SingleInputExecutor):
    """``order``: OrderSpec list; window = [offset, offset+limit).

    ``group_by``: input column indices (empty = plain TopN).
    ``pk_indices``: stream pk of the input — row identity under updates."""

    identity = "TopN"

    def __init__(
        self,
        input: Executor,
        order: Sequence[OrderSpec],
        offset: int,
        limit: int,
        pk_indices: Sequence[int],
        group_by: Sequence[int] = (),
        with_ties: bool = False,
        append_only: bool = False,
        state_table: Optional[StateTable] = None,
        table_capacity: int = 1 << 16,
        out_capacity: int = DEFAULT_CHUNK_CAPACITY,
    ):
        super().__init__(input)
        if with_ties and offset != 0:
            raise ValueError("WITH TIES requires OFFSET 0 (reference parity)")
        self.schema = input.schema
        # pk columns as final tiebreak: emitted membership must be a
        # deterministic function of row *values*, not hash-slot order —
        # recovery re-derives the emitted set from reloaded rows and any
        # slot-dependent tie choice would diverge from what downstream holds
        # (the reference orders its TopN state table by (order key, pk))
        import dataclasses as _dc
        order = list(order)
        self.n_user_keys = len(order)
        ordered_cols = {o.col for o in order}
        order += [OrderSpec(i) for i in pk_indices if i not in ordered_cols]
        # VARCHAR order columns sort by dictionary *rank*, not raw id
        # (ids are insertion-ordered — reference: memcmp_encoding.rs)
        order = [_dc.replace(o, is_string=input.schema[o.col].type.is_string)
                 for o in order]
        self.order = tuple(order)
        self._has_str_order = any(o.is_string for o in self.order)
        self._rank_ver = -1
        self.offset, self.limit = offset, limit
        self.pk_indices = tuple(pk_indices)
        self.group_by = tuple(group_by)
        self.with_ties = with_ties
        self.append_only = append_only
        self.capacity = table_capacity
        self.out_capacity = out_capacity
        self.state_table = state_table
        if group_by:
            self.identity = "GroupTopN"

        pk_types = [input.schema[i].type for i in self.pk_indices]
        col_types = [f.type for f in input.schema]
        rows = rs_new(pk_types, col_types, table_capacity)
        group_types = [input.schema[i].type for i in self.group_by]

        # incremental fast path (plain TopN): sort only a candidate subset
        # per barrier (reference: 3-segment TopNCache, top_n_cache.rs:43);
        # groups/ties fall back to the full-sort flush
        win = offset + limit
        cand_cap = 1
        while cand_cap < max(2 * win + 128, 512):
            cand_cap *= 2
        self.cand_cap = cand_cap
        self.cand_keep = max(win, cand_cap // 2)
        self.use_incremental = (not group_by and not with_ties
                                and cand_cap < table_capacity)
        big0, _ = _key_sentinels(key0_dtype(rows, self.order[0]))

        # group table sized like the row table: worst case every row is its
        # own group; gid values are group-table slot indices
        self.state = TopNState(
            rows=rows,
            group_table=ht_new(group_types, table_capacity),
            gid=jnp.zeros(table_capacity, jnp.int32),
            cand=jnp.zeros(table_capacity, jnp.bool_),
            t1=big0,
        )
        self._dirty = False
        self.n_fast_flushes = 0      # observability: incremental flushes…
        self.n_refills = 0           # …vs full-sort refills
        self._apply = jax.jit(self._apply_impl)

        def _apply_batch_impl(state: TopNState, batched_chunk):
            def body(st, ch):
                return self._apply_impl(st, ch), None

            state, _ = jax.lax.scan(body, state, batched_chunk)
            return state

        # whole-ChunkBatch ingest in ONE dispatch (lax.scan keeps the
        # epoch loop on device; the default unstack-and-loop pays one
        # dispatch per chunk) — same amortization as hash_agg's
        self._apply_batch = jax.jit(_apply_batch_impl)
        self._compute_flush = jax.jit(self._compute_flush_impl)
        self._flush_fast = jax.jit(self._flush_fast_impl)
        self._flush_refill = jax.jit(self._flush_refill_impl)
        self._gather = jax.jit(rs_gather_delta, static_argnames=("out_capacity",))
        self._finish = jax.jit(rs_finish_flush)
        if state_table is not None:
            self._load_from_state_table()

    # -- pure steps -----------------------------------------------------------

    def _apply_impl(self, state: TopNState, chunk: StreamChunk) -> TopNState:
        rows, slots, applied = rs_apply_chunk(state.rows, chunk, self.pk_indices)
        idx = jnp.where(applied, slots, self.capacity)
        cand = state.cand.at[idx].set(True, mode="drop")
        if not self.group_by:
            return state.replace(rows=rows, cand=cand)
        gcols = [chunk.columns[i] for i in self.group_by]
        gtable, gslots, _, govf = ht_lookup_or_insert(
            state.group_table, gcols, applied)
        gid = state.gid.at[idx].set(gslots, mode="drop")
        rows = rows.replace(overflow=rows.overflow | govf)
        return state.replace(rows=rows, group_table=gtable, gid=gid,
                             cand=cand)

    def _stats(self, state: TopNState, changed, bad):
        """All host-fetched scalars in ONE array → one tunnel round trip
        (dispatch latency dominates on remote chips)."""
        return jnp.stack([
            jnp.sum(changed),
            bad.astype(jnp.int64),
            state.rows.overflow.astype(jnp.int64),
            state.rows.saw_delete.astype(jnp.int64),
        ])

    def _compute_flush_impl(self, state: TopNState, str_ranks=None):
        in_set = topn_in_set(
            state.rows, state.gid, self.order, self.offset, self.limit,
            self.with_ties, n_tie_keys=self.n_user_keys,
            str_ranks=str_ranks)
        changed = rs_changed(state.rows, in_set)
        return in_set, changed, self._stats(
            state, changed, jnp.zeros((), jnp.bool_))

    def _flush_fast_impl(self, state: TopNState, str_ranks=None):
        in_set, new_cand, new_t1, bad = topn_candidate_flush(
            state.rows, self.order, self.offset, self.limit,
            state.cand, self.cand_cap, self.cand_keep, state.t1,
            str_ranks=str_ranks)
        changed = rs_changed(state.rows, in_set)
        return in_set, changed, new_cand, new_t1, self._stats(
            state, changed, bad)

    def _flush_refill_impl(self, state: TopNState, str_ranks=None):
        in_set, cand, t1 = topn_refill(
            state.rows, state.gid, self.order, self.offset, self.limit,
            self.cand_keep, str_ranks=str_ranks)
        changed = rs_changed(state.rows, in_set)
        return in_set, changed, cand, t1, self._stats(
            state, changed, jnp.zeros((), jnp.bool_))

    def _cur_ranks(self):
        """(device rank table | None, dictionary version). Fetched fresh per
        flush — the table grows as strings are interned."""
        if not self._has_str_order:
            return None, self._rank_ver
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks(), GLOBAL_STRING_DICT.version

    # -- host control ---------------------------------------------------------

    async def map_chunk(self, chunk: StreamChunk):
        self.state = self._apply(self.state, chunk)
        self._dirty = True
        if False:
            yield

    async def map_chunk_batch(self, batch):
        self.state = self._apply_batch(self.state, batch.chunk)
        self._dirty = True
        if False:
            yield

    async def on_barrier(self, barrier: Barrier):
        if not self._dirty:
            # idle barrier: membership cannot have changed — skip the sort
            # entirely (barrier cost independent of stored row count)
            if barrier.checkpoint and self.state_table is not None:
                self._checkpoint(barrier.epoch.curr)
            return
        self._dirty = False
        import numpy as np
        str_ranks, rank_ver = self._cur_ranks()
        if self.use_incremental:
            # a dictionary grown since the last flush may have re-ranked
            # keys under the stored t1 threshold / candidate set — the fast
            # path's invariants no longer hold, recompute from the full set
            bad = self._has_str_order and rank_ver != self._rank_ver
            if not bad:
                in_set, changed, cand, t1, stats = self._flush_fast(
                    self.state, str_ranks)
                n_changed, bad, ovf, sawdel = (
                    int(x) for x in np.asarray(stats))
            if bad:
                # candidate set over/underflowed or the window reached the
                # forgotten region: full-sort refill
                (in_set, changed, cand, t1,
                 stats) = self._flush_refill(self.state, str_ranks)
                n_changed, _, ovf, sawdel = (
                    int(x) for x in np.asarray(stats))
                self.n_refills += 1
            else:
                self.n_fast_flushes += 1
            self.state = self.state.replace(cand=cand, t1=t1)
        else:
            in_set, changed, stats = self._compute_flush(self.state, str_ranks)
            n_changed, _, ovf, sawdel = (int(x) for x in np.asarray(stats))
        self._rank_ver = rank_ver
        if ovf:
            raise RuntimeError(
                f"{self.identity}: row table overflow (capacity "
                f"{self.capacity}); increase table_capacity")
        if self.append_only and sawdel:
            raise RuntimeError(
                f"{self.identity}: delete arrived on declared append-only "
                "input")
        lo, n = 0, n_changed
        while lo < n:
            chunk = self._gather(self.state.rows, in_set, changed,
                                 jnp.int64(lo), out_capacity=self.out_capacity)
            yield chunk
            lo += self.out_capacity // 2
        if barrier.checkpoint and self.state_table is not None:
            self._checkpoint(barrier.epoch.curr)
        self.state = self.state.replace(rows=self._finish(self.state.rows, in_set))

    # -- persistence ----------------------------------------------------------
    # The durable row is the full input row; membership is recomputed on
    # recovery (reference persists the full managed state the same way and
    # rebuilds TopNCache from the state table on startup).

    def _checkpoint(self, epoch: int) -> None:
        rows = rs_checkpoint(self.state.rows, self.state_table, epoch)
        self.state = self.state.replace(rows=rows)

    def _load_from_state_table(self) -> None:
        rows = list(self.state_table.scan_all())
        if not rows:
            return
        bs = 1024
        for i in range(0, len(rows), bs):
            chunk = physical_chunk(self.schema, rows[i:i + bs], bs)
            self.state = self._apply(self.state, chunk)
        # recovered rows were already emitted before the failure: rebuild the
        # emitted snapshot so the first post-recovery flush emits no spurious
        # inserts; the reloaded slots are not checkpoint-dirty (they ARE the
        # checkpoint)
        # overflow during reload must surface immediately — idle barriers
        # skip the (sync-costing) check until the next data chunk
        if bool(self.state.rows.overflow):
            raise RuntimeError(
                f"{self.identity}: row table overflow while reloading "
                f"checkpoint (capacity {self.capacity})")
        str_ranks, rank_ver = self._cur_ranks()
        if self.use_incremental:
            in_set, _, cand, t1, _ = self._flush_refill(self.state, str_ranks)
            self.state = self.state.replace(cand=cand, t1=t1)
        else:
            in_set, _, _ = self._compute_flush(self.state, str_ranks)
        self._rank_ver = rank_ver
        self._dirty = False
        rows_st = self._finish(self.state.rows, in_set)
        import jax.numpy as _jnp
        rows_st = rows_st.replace(ckpt_dirty=_jnp.zeros_like(rows_st.ckpt_dirty))
        self.state = self.state.replace(rows=rows_st)

