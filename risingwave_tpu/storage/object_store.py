"""ObjectStore: the blob layer under the durable checkpoint tier.

Counterpart of the reference's ``ObjectStore`` trait
(reference: src/object_store/src/object/mod.rs:93-136 —
upload/read/delete/list over S3/OpenDAL/in-mem backends). The checkpoint
log (storage/checkpoint.py) is parameterized by this interface, so the
durable tier is one backend swap away from an object-storage service; the
implementations here are local-FS (fsync + atomic-rename discipline) and
in-memory (tests/sim).

Only whole-object operations: segments are written once and read whole —
the streaming/range reads the reference needs for LSM blocks do not arise
(device state is merged in HBM; a segment is one compact delta).

Fault tolerance (the boundary discipline): every durable-tier consumer
opens its store through ``open_object_store``/``wrap_object_store``, which
layer ``RetryingObjectStore`` (common/retry.py policy — whole-object ops
are idempotent, so a blind re-put/re-get is always safe) and, for tests
and the sim, a seeded ``FaultInjectingObjectStore`` with transient-rate,
permanent-path, and torn-write modes. Raw backend construction outside
this module is lint-rejected by scripts/check.sh.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, List, Optional, Sequence


class ObjectStore:
    """put/get/list/delete + atomic_put (read-modify-write safe publish)."""

    def put(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def atomic_put(self, path: str, data: bytes) -> None:
        """Readers see the old object or the new one, never a torn mix
        (manifest publication; local FS: tmp file + rename)."""
        raise NotImplementedError

    def get(self, path: str) -> Optional[bytes]:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        return self.get(path) is not None


class LocalFsObjectStore(ObjectStore):
    """Objects are files under ``root``; durability via fsync, atomicity
    via tmp + os.replace (the discipline the checkpoint log relied on
    before this layer was factored out)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _p(self, path: str) -> str:
        return os.path.join(self.root, path)

    def put(self, path: str, data: bytes) -> None:
        full = self._p(path)
        os.makedirs(os.path.dirname(full), exist_ok=True) \
            if os.path.dirname(path) else None
        with open(full, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def atomic_put(self, path: str, data: bytes) -> None:
        full = self._p(path)
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, full)

    def get(self, path: str) -> Optional[bytes]:
        try:
            with open(self._p(path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            for fn in files:
                p = fn if rel == "." else os.path.join(rel, fn)
                if p.startswith(prefix) and not p.endswith(".tmp"):
                    out.append(p)
        return sorted(out)

    def delete(self, path: str) -> None:
        try:
            os.remove(self._p(path))
        except OSError:
            pass

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))


class MemObjectStore(ObjectStore):
    """In-memory backend (the reference's InMemObjectStore) — tests and
    the deterministic sim. Thread-safe: the background compactor reads
    concurrently with barrier-path appends."""

    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[path] = bytes(data)

    atomic_put = put    # dict assignment is already atomic

    def get(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(path)

    def list(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(p for p in self._objects if p.startswith(prefix))

    def delete(self, path: str) -> None:
        with self._lock:
            self._objects.pop(path, None)


# -- fault-tolerance layers ---------------------------------------------------


class TransientObjectStoreError(OSError):
    """A fault a retry may absorb (throttling, flaky network, torn put)."""


class PermanentObjectStoreError(RuntimeError):
    """A fault retrying cannot fix (permissions, bad bucket): surfaces
    immediately through the retry layer."""


class FaultInjectingObjectStore(ObjectStore):
    """Seeded chaos wrapper for tests and the sim (the in-tree analogue of
    the reference's storage failpoints + madsim IO faults). Modes:

    * ``transient_rate`` — each op fails with TransientObjectStoreError
      with this probability BEFORE touching the backend,
    * ``torn_write_rate`` — a ``put`` writes a truncated prefix, then
      fails (the mid-write crash shape the manifest discipline must
      survive; never applied to ``atomic_put``, whose contract is
      no-torn-state),
    * ``permanent_paths`` — path prefixes that always fail permanently.

    Thread-safe: the seeded RNG is shared by the barrier path and the
    background compactor."""

    def __init__(self, inner: ObjectStore, seed: int = 0,
                 transient_rate: float = 0.0,
                 torn_write_rate: float = 0.0,
                 permanent_paths: Sequence[str] = ()):
        self.inner = inner
        self.transient_rate = float(transient_rate)
        self.torn_write_rate = float(torn_write_rate)
        self.permanent_paths = tuple(permanent_paths)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.faults_injected = 0
        self.torn_writes = 0

    def _roll(self, rate: float) -> bool:
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.faults_injected += 1
            return hit

    def _maybe_fault(self, op: str, path: str) -> None:
        for p in self.permanent_paths:
            if path.startswith(p):
                raise PermanentObjectStoreError(
                    f"injected permanent fault: {op} {path!r}")
        if self._roll(self.transient_rate):
            raise TransientObjectStoreError(
                f"injected transient fault: {op} {path!r}")

    def put(self, path: str, data: bytes) -> None:
        self._maybe_fault("put", path)
        if self._roll(self.torn_write_rate):
            with self._lock:
                self.torn_writes += 1
            self.inner.put(path, data[: max(1, len(data) // 2)])
            raise TransientObjectStoreError(
                f"injected torn write: put {path!r}")
        self.inner.put(path, data)

    def atomic_put(self, path: str, data: bytes) -> None:
        # atomic_put may fail but never tear (that is its contract)
        self._maybe_fault("atomic_put", path)
        self.inner.atomic_put(path, data)

    def get(self, path: str) -> Optional[bytes]:
        self._maybe_fault("get", path)
        return self.inner.get(path)

    def list(self, prefix: str = "") -> List[str]:
        self._maybe_fault("list", prefix)
        return self.inner.list(prefix)

    def delete(self, path: str) -> None:
        self._maybe_fault("delete", path)
        self.inner.delete(path)

    def exists(self, path: str) -> bool:
        self._maybe_fault("exists", path)
        return self.inner.exists(path)


#: default policy for object-store IO; callers override via rw_config
#: fault.* knobs (common/config.py, the single source of the default
#: numbers) threaded through open_object_store
def default_io_retry_policy():
    from ..common.config import FaultConfig
    return FaultConfig().io_retry_policy()


class RetryingObjectStore(ObjectStore):
    """Retry/backoff layer over any backend. Safe by construction: every
    op is whole-object and idempotent (a re-put rewrites the same bytes;
    a torn first put is fully overwritten by the retry), so the wrapper
    retries blindly on retryable errors and surfaces
    PermanentObjectStoreError / RetryError past the budget. Per-op
    counters land in the global retry registry under
    ``object_store.<op>`` sites."""

    def __init__(self, inner: ObjectStore, policy=None,
                 site_prefix: str = "object_store"):
        self.inner = inner
        self.policy = policy or default_io_retry_policy()
        self._prefix = site_prefix

    def _run(self, op: str, fn, *args):
        return self.policy.run(f"{self._prefix}.{op}", fn, *args)

    def put(self, path: str, data: bytes) -> None:
        self._run("put", self.inner.put, path, data)

    def atomic_put(self, path: str, data: bytes) -> None:
        self._run("atomic_put", self.inner.atomic_put, path, data)

    def get(self, path: str) -> Optional[bytes]:
        return self._run("get", self.inner.get, path)

    def list(self, prefix: str = "") -> List[str]:
        return self._run("list", self.inner.list, prefix)

    def delete(self, path: str) -> None:
        self._run("delete", self.inner.delete, path)

    def exists(self, path: str) -> bool:
        return self._run("exists", self.inner.exists, path)


def wrap_object_store(store: ObjectStore, policy=None) -> ObjectStore:
    """Canonical retry wrapping: idempotent (an already-retrying store is
    returned as-is) so every durable-tier entry point can call it
    unconditionally."""
    if isinstance(store, RetryingObjectStore):
        return store
    return RetryingObjectStore(store, policy)


def open_object_store(data_dir: str, retry_policy=None,
                      fault_transient_rate: float = 0.0,
                      fault_seed: int = 0,
                      fault_torn_write_rate: float = 0.0) -> ObjectStore:
    """THE way the durable tier opens a local-FS-backed store: backend →
    (optional seeded fault injection, tests/sim) → retry layer. Raw
    ``LocalFsObjectStore(...)`` construction outside this module is a
    lint error (scripts/check.sh) — it would bypass the retry boundary."""
    store: ObjectStore = LocalFsObjectStore(data_dir)
    if fault_transient_rate > 0.0 or fault_torn_write_rate > 0.0:
        store = FaultInjectingObjectStore(
            store, seed=fault_seed, transient_rate=fault_transient_rate,
            torn_write_rate=fault_torn_write_rate)
    return wrap_object_store(store, retry_policy)
