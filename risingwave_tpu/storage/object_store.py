"""ObjectStore: the blob layer under the durable checkpoint tier.

Counterpart of the reference's ``ObjectStore`` trait
(reference: src/object_store/src/object/mod.rs:93-136 —
upload/read/delete/list over S3/OpenDAL/in-mem backends). The checkpoint
log (storage/checkpoint.py) is parameterized by this interface, so the
durable tier is one backend swap away from an object-storage service; the
implementations here are local-FS (fsync + atomic-rename discipline) and
in-memory (tests/sim).

Only whole-object operations: segments are written once and read whole —
the streaming/range reads the reference needs for LSM blocks do not arise
(device state is merged in HBM; a segment is one compact delta).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional


class ObjectStore:
    """put/get/list/delete + atomic_put (read-modify-write safe publish)."""

    def put(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def atomic_put(self, path: str, data: bytes) -> None:
        """Readers see the old object or the new one, never a torn mix
        (manifest publication; local FS: tmp file + rename)."""
        raise NotImplementedError

    def get(self, path: str) -> Optional[bytes]:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        return self.get(path) is not None


class LocalFsObjectStore(ObjectStore):
    """Objects are files under ``root``; durability via fsync, atomicity
    via tmp + os.replace (the discipline the checkpoint log relied on
    before this layer was factored out)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _p(self, path: str) -> str:
        return os.path.join(self.root, path)

    def put(self, path: str, data: bytes) -> None:
        full = self._p(path)
        os.makedirs(os.path.dirname(full), exist_ok=True) \
            if os.path.dirname(path) else None
        with open(full, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def atomic_put(self, path: str, data: bytes) -> None:
        full = self._p(path)
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, full)

    def get(self, path: str) -> Optional[bytes]:
        try:
            with open(self._p(path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            for fn in files:
                p = fn if rel == "." else os.path.join(rel, fn)
                if p.startswith(prefix) and not p.endswith(".tmp"):
                    out.append(p)
        return sorted(out)

    def delete(self, path: str) -> None:
        try:
            os.remove(self._p(path))
        except OSError:
            pass

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))


class MemObjectStore(ObjectStore):
    """In-memory backend (the reference's InMemObjectStore) — tests and
    the deterministic sim. Thread-safe: the background compactor reads
    concurrently with barrier-path appends."""

    def __init__(self) -> None:
        self._objects: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[path] = bytes(data)

    atomic_put = put    # dict assignment is already atomic

    def get(self, path: str) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(path)

    def list(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(p for p in self._objects if p.startswith(prefix))

    def delete(self, path: str) -> None:
        with self._lock:
            self._objects.pop(path, None)
