from .state_store import MemoryStateStore  # noqa: F401
from .state_table import StateTable  # noqa: F401
