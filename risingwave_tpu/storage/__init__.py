from .state_store import MemoryStateStore  # noqa: F401
from .state_table import StateTable  # noqa: F401
from .sstable import Sstable, SstBuilder  # noqa: F401
from .hummock import (  # noqa: F401
    CompactTask, HummockStateStore, HummockVersion, PinnedSnapshot,
)
