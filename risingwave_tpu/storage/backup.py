"""Meta-snapshot backup / restore of a durable data directory.

Counterpart of the reference's backup tooling (reference:
src/meta/src/backup_restore/backup_manager.rs — meta snapshot =
cluster metadata + the Hummock version manifest, written to the backup
object store; src/storage/backup/ meta_snapshot.rs format;
restore.rs rebuilds a fresh meta store from a snapshot). Here a snapshot
captures the SAME two tiers:

* the checkpoint manifest + every segment it references (the durable
  state version — orphan segments from torn publishes are deliberately
  excluded, exactly like unreferenced SSTs),
* the meta tier (``meta/meta.jsonl`` — catalog, DDL log, system params).

All data-file reads and writes go through the retried object-store layer
(storage/object_store.py): the backup of a flaky volume retries with
backoff instead of dying on the first EIO, exactly like the checkpoint
path it snapshots.

The snapshot is self-describing (``backup.json`` with id, epoch and the
captured file list) and restore refuses to overwrite a non-empty target,
mirroring the reference's restore precondition that the new cluster must
be uninitialized (backup_restore/restore.rs).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from .object_store import open_object_store

_BACKUP_META = "backup.json"


class BackupError(RuntimeError):
    pass


def _write_descriptor(dest: str, desc: dict) -> None:
    tmp = os.path.join(dest, _BACKUP_META + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(desc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dest, _BACKUP_META))


def _copy_meta_tier(src_store, dest_store, files: list) -> None:
    raw = src_store.get("meta/meta.jsonl")
    if raw is not None:
        dest_store.put("meta/meta.jsonl", raw)
        files.append("meta/meta.jsonl")


def create_backup(data_dir: str, dest: str,
                  backup_id: Optional[str] = None) -> dict:
    """Snapshot ``data_dir`` into ``dest`` (created; must not already hold
    a backup). Returns the backup descriptor. Detects the durable tier:
    segment log (manifest.json) or Hummock-lite (hummock/version.json)."""
    if os.path.exists(os.path.join(data_dir, "hummock", "version.json")):
        return _create_backup_hummock(data_dir, dest, backup_id)
    src = open_object_store(data_dir)
    manifest_raw = src.get("manifest.json")
    if manifest_raw is None:
        raise BackupError(f"{data_dir!r} has no checkpoint manifest")
    manifest = json.loads(manifest_raw)
    os.makedirs(dest, exist_ok=True)
    if os.path.exists(os.path.join(dest, _BACKUP_META)):
        raise BackupError(f"{dest!r} already contains a backup")
    out = open_object_store(dest)

    files = []
    # 1. the manifest itself (fixed bytes: the version being captured)
    out.put("manifest.json", manifest_raw)
    files.append("manifest.json")
    # 2. every segment the manifest references — and nothing else
    for seg in manifest.get("segments", []):
        data = src.get(seg)
        if data is None:
            raise BackupError(
                f"manifest references missing segment {seg!r}")
        out.put(seg, data)
        files.append(seg)
    # 3. the meta tier (catalog / DDL log / params)
    _copy_meta_tier(src, out, files)

    desc = {
        "backup_id": backup_id or f"backup-{int(time.time())}",
        "committed_epoch": manifest.get("committed_epoch"),
        "files": files,
        "source_dir": os.path.abspath(data_dir),
    }
    _write_descriptor(dest, desc)
    return desc


def _create_backup_hummock(data_dir: str, dest: str,
                           backup_id: Optional[str]) -> dict:
    """Hummock-tier snapshot: the version manifest + every SST it
    references + the meta tier. In-process callers pin the version
    instead (Session.pin_version); a CROSS-process backup cannot hold a
    pin, so it leans on the tier's immutability discipline — the
    manifest swap is atomic and runs are immutable — and simply re-reads
    the manifest if a referenced SST was vacuumed mid-copy (the same
    retry rule as recovery's fold)."""
    os.makedirs(dest, exist_ok=True)
    if os.path.exists(os.path.join(dest, _BACKUP_META)):
        raise BackupError(f"{dest!r} already contains a backup")
    src = open_object_store(data_dir)
    out = open_object_store(dest)
    for attempt in range(8):
        version_raw = src.get("hummock/version.json")
        if version_raw is None:
            raise BackupError(f"{data_dir!r} has no hummock version")
        version = json.loads(version_raw)
        runs = list(version.get("l0", [])) + list(version.get("l1", []))
        # copy ONE SST at a time (never the whole store in memory); if a
        # referenced run vanished mid-copy (vacuumed by a live
        # compactor), re-read the manifest and start over — SSTs already
        # copied are simply overwritten or orphaned in the backup dir
        files = ["hummock/version.json"]
        out.put("hummock/version.json", version_raw)
        vanished = False
        for rel in runs:
            data = src.get(rel)
            if data is None:
                vanished = True
                break
            out.put(rel, data)
            files.append(rel)
        if not vanished:
            break
        if attempt == 7:
            raise BackupError(
                "version kept referencing vanished SSTs (live "
                "compactor racing the backup?)")
    _copy_meta_tier(src, out, files)
    desc = {
        "backup_id": backup_id or f"backup-{int(time.time())}",
        "committed_epoch": version.get("committed_epoch"),
        "version_id": version.get("vid"),
        "tier": "hummock",
        "files": files,
        "source_dir": os.path.abspath(data_dir),
    }
    _write_descriptor(dest, desc)
    return desc


def restore_backup(backup_dir: str, data_dir: str) -> dict:
    """Materialize a backup into a FRESH data dir; a recovered Session
    over it resumes at the snapshot's committed epoch."""
    desc_path = os.path.join(backup_dir, _BACKUP_META)
    if not os.path.exists(desc_path):
        raise BackupError(f"{backup_dir!r} is not a backup (no "
                          f"{_BACKUP_META})")
    with open(desc_path, "r", encoding="utf-8") as f:
        desc = json.load(f)
    if os.path.exists(data_dir) and os.listdir(data_dir):
        raise BackupError(
            f"restore target {data_dir!r} is not empty (refusing to "
            "overwrite a live data dir)")
    src = open_object_store(backup_dir)
    out = open_object_store(data_dir)
    for rel in desc["files"]:
        data = src.get(rel)
        if data is None:
            raise BackupError(f"backup is missing file {rel!r}")
        out.put(rel, data)
    return desc


def list_backup(backup_dir: str) -> dict:
    desc_path = os.path.join(backup_dir, _BACKUP_META)
    if not os.path.exists(desc_path):
        raise BackupError(f"{backup_dir!r} is not a backup")
    with open(desc_path, "r", encoding="utf-8") as f:
        return json.load(f)
