"""Meta-snapshot backup / restore of a durable data directory.

Counterpart of the reference's backup tooling (reference:
src/meta/src/backup_restore/backup_manager.rs — meta snapshot =
cluster metadata + the Hummock version manifest, written to the backup
object store; src/storage/backup/ meta_snapshot.rs format;
restore.rs rebuilds a fresh meta store from a snapshot). Here a snapshot
captures the SAME two tiers:

* the checkpoint manifest + every segment it references (the durable
  state version — orphan segments from torn publishes are deliberately
  excluded, exactly like unreferenced SSTs),
* the meta tier (``meta/meta.jsonl`` — catalog, DDL log, system params).

The snapshot is self-describing (``backup.json`` with id, epoch and the
captured file list) and restore refuses to overwrite a non-empty target,
mirroring the reference's restore precondition that the new cluster must
be uninitialized (backup_restore/restore.rs).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Optional

_BACKUP_META = "backup.json"


class BackupError(RuntimeError):
    pass


def create_backup(data_dir: str, dest: str,
                  backup_id: Optional[str] = None) -> dict:
    """Snapshot ``data_dir`` into ``dest`` (created; must not already hold
    a backup). Returns the backup descriptor. Detects the durable tier:
    segment log (manifest.json) or Hummock-lite (hummock/version.json)."""
    if os.path.exists(os.path.join(data_dir, "hummock", "version.json")):
        return _create_backup_hummock(data_dir, dest, backup_id)
    manifest_path = os.path.join(data_dir, "manifest.json")
    if not os.path.exists(manifest_path):
        raise BackupError(f"{data_dir!r} has no checkpoint manifest")
    with open(manifest_path, "rb") as f:
        manifest_raw = f.read()
    manifest = json.loads(manifest_raw)
    os.makedirs(dest, exist_ok=True)
    if os.path.exists(os.path.join(dest, _BACKUP_META)):
        raise BackupError(f"{dest!r} already contains a backup")

    files = []
    # 1. the manifest itself (fixed bytes: the version being captured)
    with open(os.path.join(dest, "manifest.json"), "wb") as f:
        f.write(manifest_raw)
    files.append("manifest.json")
    # 2. every segment the manifest references — and nothing else
    for seg in manifest.get("segments", []):
        src = os.path.join(data_dir, seg)
        if not os.path.exists(src):
            raise BackupError(
                f"manifest references missing segment {seg!r}")
        shutil.copy2(src, os.path.join(dest, seg))
        files.append(seg)
    # 3. the meta tier (catalog / DDL log / params)
    meta_src = os.path.join(data_dir, "meta", "meta.jsonl")
    if os.path.exists(meta_src):
        os.makedirs(os.path.join(dest, "meta"), exist_ok=True)
        shutil.copy2(meta_src, os.path.join(dest, "meta", "meta.jsonl"))
        files.append("meta/meta.jsonl")

    desc = {
        "backup_id": backup_id or f"backup-{int(time.time())}",
        "committed_epoch": manifest.get("committed_epoch"),
        "files": files,
        "source_dir": os.path.abspath(data_dir),
    }
    tmp = os.path.join(dest, _BACKUP_META + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(desc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dest, _BACKUP_META))
    return desc


def _create_backup_hummock(data_dir: str, dest: str,
                           backup_id: Optional[str]) -> dict:
    """Hummock-tier snapshot: the version manifest + every SST it
    references + the meta tier. In-process callers pin the version
    instead (Session.pin_version); a CROSS-process backup cannot hold a
    pin, so it leans on the tier's immutability discipline — the
    manifest swap is atomic and runs are immutable — and simply re-reads
    the manifest if a referenced SST was vacuumed mid-copy (the same
    retry rule as recovery's fold)."""
    os.makedirs(dest, exist_ok=True)
    if os.path.exists(os.path.join(dest, _BACKUP_META)):
        raise BackupError(f"{dest!r} already contains a backup")
    version_path = os.path.join(data_dir, "hummock", "version.json")
    for attempt in range(8):
        with open(version_path, "rb") as f:
            version_raw = f.read()
        version = json.loads(version_raw)
        runs = list(version.get("l0", [])) + list(version.get("l1", []))
        try:
            staged = []
            for rel in runs:
                src = os.path.join(data_dir, rel)
                if not os.path.exists(src):
                    raise FileNotFoundError(rel)
                staged.append(rel)
            files = []
            os.makedirs(os.path.join(dest, "hummock"), exist_ok=True)
            with open(os.path.join(dest, "hummock", "version.json"),
                      "wb") as f:
                f.write(version_raw)
            files.append("hummock/version.json")
            for rel in staged:
                dst = os.path.join(dest, rel)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                shutil.copy2(os.path.join(data_dir, rel), dst)
                files.append(rel)
            break
        except FileNotFoundError:
            if attempt == 7:
                raise BackupError(
                    "version kept referencing vanished SSTs (live "
                    "compactor racing the backup?)")
    meta_src = os.path.join(data_dir, "meta", "meta.jsonl")
    if os.path.exists(meta_src):
        os.makedirs(os.path.join(dest, "meta"), exist_ok=True)
        shutil.copy2(meta_src, os.path.join(dest, "meta", "meta.jsonl"))
        files.append("meta/meta.jsonl")
    desc = {
        "backup_id": backup_id or f"backup-{int(time.time())}",
        "committed_epoch": version.get("committed_epoch"),
        "version_id": version.get("vid"),
        "tier": "hummock",
        "files": files,
        "source_dir": os.path.abspath(data_dir),
    }
    tmp = os.path.join(dest, _BACKUP_META + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(desc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dest, _BACKUP_META))
    return desc


def restore_backup(backup_dir: str, data_dir: str) -> dict:
    """Materialize a backup into a FRESH data dir; a recovered Session
    over it resumes at the snapshot's committed epoch."""
    desc_path = os.path.join(backup_dir, _BACKUP_META)
    if not os.path.exists(desc_path):
        raise BackupError(f"{backup_dir!r} is not a backup (no "
                          f"{_BACKUP_META})")
    with open(desc_path, "r", encoding="utf-8") as f:
        desc = json.load(f)
    if os.path.exists(data_dir) and os.listdir(data_dir):
        raise BackupError(
            f"restore target {data_dir!r} is not empty (refusing to "
            "overwrite a live data dir)")
    os.makedirs(data_dir, exist_ok=True)
    for rel in desc["files"]:
        src = os.path.join(backup_dir, rel)
        if not os.path.exists(src):
            raise BackupError(f"backup is missing file {rel!r}")
        dst = os.path.join(data_dir, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(src, dst)
    return desc


def list_backup(backup_dir: str) -> dict:
    desc_path = os.path.join(backup_dir, _BACKUP_META)
    if not os.path.exists(desc_path):
        raise BackupError(f"{backup_dir!r} is not a backup")
    with open(desc_path, "r", encoding="utf-8") as f:
        return json.load(f)
