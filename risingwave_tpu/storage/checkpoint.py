"""Durable checkpoint log: epoch-delta segments + manifest on an
ObjectStore (local FS by default — storage/object_store.py).

The durable tier under MemoryStateStore — the role Hummock's SST upload +
version manifest plays in the reference (reference:
src/storage/src/hummock/sstable/builder.rs:87 SST build,
src/meta/src/hummock/manager/ commit_epoch version bump, docs/checkpoint.md:
26-44 "commit epoch makes sealed state durable"). Deliberately NOT an LSM:
executor state is already merged in device HBM, so each checkpoint writes
one compact *delta segment* (the rows dirtied since the previous checkpoint,
already deduplicated per key) and recovery is a linear replay of segments —
compaction pressure, which Hummock exists to manage, does not arise until
segment counts grow, at which point segments fold into one. Folding runs on
a BACKGROUND thread, off the barrier path (reference: standalone compactor,
src/storage/compactor/src/server.rs:57): the fold reads a snapshot of the
segment list, writes the folded segment, then swaps the manifest under the
lock — barrier-path appends interleave freely because they only append.

Write discipline (crash-safe at every point):
  1. put the segment object (fsync'd by the FS backend),
  2. publish the manifest via atomic_put (tmp + atomic rename).
A crash between 1 and 2 leaves an orphan segment the manifest never
references — ignored on recovery.

Values inside segments use the process-independent value encoding
(common/row.py: strings as bytes, not dictionary ids), so a fresh process
recovers cleanly.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Optional

from .object_store import ObjectStore, open_object_store, wrap_object_store
from .state_store import MemoryStateStore

_MANIFEST = "manifest.json"

#: Plan/lowering format generation. State-table ids are assigned by a
#: deterministic walk of the OPTIMIZED plan, so a data_dir written by a
#: build with a different frontend pipeline may lay out state tables
#: differently (reference: Hummock's version/format compatibility gates,
#: src/meta/src/hummock/manager/versioning.rs). Bump when the planner/
#: optimizer changes the shape of built plans; recovery warns on mismatch.
#: v3: join state-table pks are join-key-prefixed (frontend/build.py
#: join_state_pk) — v2 join rows are keyed under the old stream-pk layout.
PLAN_FORMAT_VERSION = 3


class CheckpointLog:
    def __init__(self, data_dir: Optional[str] = None,
                 object_store: Optional[ObjectStore] = None,
                 compact_after: Optional[int] = None,
                 retry_policy=None):
        if object_store is None:
            if data_dir is None:
                raise ValueError("need data_dir or object_store")
            object_store = open_object_store(data_dir, retry_policy)
        self.dir = data_dir
        # every IO below the manifest/segment discipline goes through the
        # retry layer (idempotent whole-object ops; common/retry.py)
        self.store = wrap_object_store(object_store, retry_policy)
        if compact_after is not None:
            self.COMPACT_AFTER = compact_after
        # serializes manifest read-modify-write cycles between the barrier
        # path and the background compactor
        self._mlock = threading.RLock()
        # one fold at a time: an explicit compact() call must not overlap
        # the background thread's (overlapping folds would double-delete
        # and race the folded-segment sequence number)
        self._fold_lock = threading.Lock()
        self._compact_thread: Optional[threading.Thread] = None
        self._compact_seq = 0
        self._format_warned = False

    # -- manifest -------------------------------------------------------------

    def exists(self) -> bool:
        return self.store.exists(_MANIFEST)

    def _read_manifest(self) -> dict:
        raw = self.store.get(_MANIFEST)
        if raw is None:
            return {"committed_epoch": 0, "segments": [], "ddl": [],
                    "dropped_tables": [], "prepared": {},
                    "plan_format": PLAN_FORMAT_VERSION}
        m = json.loads(raw)
        m.setdefault("dropped_tables", [])
        m.setdefault("prepared", {})
        stored = m.setdefault("plan_format", 1)
        if stored != PLAN_FORMAT_VERSION and not self._format_warned:
            self._format_warned = True
            import warnings
            warnings.warn(
                f"data dir was written by plan-format {stored}, this "
                f"build is {PLAN_FORMAT_VERSION}: state-table layout may "
                "not match the replayed DDL's rebuilt plans — if recovery "
                "misbehaves, rebuild the MVs from sources (DROP/CREATE)")
        return m

    def _write_manifest(self, manifest: dict) -> None:
        from ..common.failpoint import fail_point
        fail_point("checkpoint.manifest.write")
        payload = json.dumps(manifest).encode()
        try:
            fail_point("checkpoint.manifest.rename")
        except BaseException:
            # torn publish: the tmp object exists, the manifest does not
            # change — recovery ignores *.tmp (the pre-refactor on-disk
            # shape of a crash between tmp write and rename)
            self.store.put(_MANIFEST + ".tmp2", payload)
            raise
        self.store.atomic_put(_MANIFEST, payload)

    # -- segments -------------------------------------------------------------

    @staticmethod
    def _encode_segment(
            deltas: dict[int, dict[bytes, Optional[bytes]]]) -> bytes:
        parts = [struct.pack("<I", len(deltas))]
        for table_id, buf in sorted(deltas.items()):
            parts.append(struct.pack("<II", table_id, len(buf)))
            for k, v in sorted(buf.items()):
                parts.append(struct.pack("<H", len(k)))
                parts.append(k)
                if v is None:
                    parts.append(b"\x00")
                else:
                    parts.append(b"\x01")
                    parts.append(struct.pack("<I", len(v)))
                    parts.append(v)
        return b"".join(parts)

    def _write_segment(self, name: str,
                       deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
        from ..common.failpoint import fail_point
        fail_point("checkpoint.segment.write")
        payload = self._encode_segment(deltas)
        try:
            # simulates a torn segment (crash mid-write): a truncated
            # object lands on disk. Safe because the manifest that would
            # reference this segment is only written after the segment
            # completes — recovery never reads an unreferenced object.
            fail_point("checkpoint.segment.write.partial")
        except BaseException:
            self.store.put(name, payload[:4])
            raise
        self.store.put(name, payload)

    def _read_segment(self, name: str) -> dict[int, dict[bytes, Optional[bytes]]]:
        data = self.store.get(name)
        if data is None:
            raise FileNotFoundError(name)
        return self._decode_segment(data)

    @staticmethod
    def _decode_segment(data: bytes) -> dict[int, dict[bytes, Optional[bytes]]]:
        pos = 0
        (n_tables,) = struct.unpack_from("<I", data, pos)
        pos += 4
        out: dict[int, dict[bytes, Optional[bytes]]] = {}
        for _ in range(n_tables):
            table_id, n = struct.unpack_from("<II", data, pos)
            pos += 8
            buf: dict[bytes, Optional[bytes]] = {}
            for _ in range(n):
                (klen,) = struct.unpack_from("<H", data, pos)
                pos += 2
                k = data[pos:pos + klen]
                pos += klen
                live = data[pos]
                pos += 1
                if live:
                    (vlen,) = struct.unpack_from("<I", data, pos)
                    pos += 4
                    buf[k] = data[pos:pos + vlen]
                    pos += vlen
                else:
                    buf[k] = None
            out[table_id] = buf
        return out

    # -- public surface -------------------------------------------------------

    # folding threshold: bounds segment-count growth AND the O(segments)
    # manifest rewrite per commit
    COMPACT_AFTER = 64

    def append_epoch(self, epoch: int,
                     deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
        from ..common.failpoint import fail_point
        fail_point("checkpoint.commit")
        if deltas:
            name = f"epoch_{epoch:012d}.seg"
            self._write_segment(name, deltas)
        with self._mlock:
            manifest = self._read_manifest()
            if deltas:
                manifest["segments"].append(name)
            # empty delta: bump the committed epoch only (idle FLUSH ticks
            # must not grow the segment list)
            manifest["committed_epoch"] = epoch
            self._write_manifest(manifest)
            n_segments = len(manifest["segments"])
        if n_segments > self.COMPACT_AFTER:
            self._spawn_compact()

    # -- two-phase epochs (spanning jobs) -------------------------------------
    # A job whose fragment graph spans worker processes needs the cluster
    # checkpoint cut to be CONSISTENT across several independent stores.
    # Phase 1 (barrier ack) therefore makes the epoch's deltas DURABLE
    # without committing them: the segment object is written and recorded
    # in the manifest's ``prepared`` map. Phase 2 (the session's commit
    # frame) promotes it into the committed chain. A process killed
    # between ack and commit can then be ROLLED FORWARD at recovery to
    # whatever epoch the rest of the cluster committed — without this,
    # one participant recovering a checkpoint behind its peers forks the
    # job's history (reference: Hummock solves the same problem by giving
    # the META node one atomic version for the whole cluster;
    # src/meta/src/hummock/manager/ commit_epoch).

    def prepare_epoch(self, epoch: int,
                      deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
        """Phase 1: durably stage an epoch's deltas without committing."""
        from ..common.failpoint import fail_point
        fail_point("checkpoint.prepare")
        name = None
        if deltas:
            name = f"epoch_{epoch:012d}.prepared.seg"
            self._write_segment(name, deltas)
        with self._mlock:
            manifest = self._read_manifest()
            manifest["prepared"][str(epoch)] = name
            self._write_manifest(manifest)

    def prepared_epochs(self) -> list[int]:
        with self._mlock:
            return sorted(int(e) for e in self._read_manifest()["prepared"])

    def recovery_info(self) -> tuple[int, list[int]]:
        """(committed epoch, prepared epochs) — what this store durably
        holds, for the session's recovery negotiation."""
        with self._mlock:
            m = self._read_manifest()
        return (int(m["committed_epoch"]),
                sorted(int(e) for e in m["prepared"]))

    def settle_prepared(self, decided_epoch: int,
                        discard_beyond: bool = True) -> None:
        """Roll prepared epochs ≤ ``decided_epoch`` forward into the
        committed chain. With ``discard_beyond`` (the RECOVERY path),
        prepared epochs beyond it are DELETED — the cluster never
        decided them, and committing them would replay rows the rest of
        the graph does not have. The normal phase-2 path passes False:
        with pipelined checkpoints a LATER epoch may already be durably
        prepared when this epoch's commit frame arrives, and it must
        survive for its own commit."""
        from ..common.failpoint import fail_point
        fail_point("checkpoint.settle")
        victims: list[str] = []
        with self._mlock:
            manifest = self._read_manifest()
            prepared = manifest["prepared"]
            if not prepared:
                return
            for e in sorted(int(x) for x in prepared):
                name = prepared[str(e)]
                if e <= decided_epoch:
                    prepared.pop(str(e))
                    if name is not None:
                        manifest["segments"].append(name)
                    manifest["committed_epoch"] = max(
                        manifest["committed_epoch"], e)
                elif discard_beyond:
                    prepared.pop(str(e))
                    if name is not None:
                        victims.append(name)
            self._write_manifest(manifest)
        for name in victims:
            self.store.delete(name)

    def log_ddl(self, sql: str) -> None:
        with self._mlock:
            manifest = self._read_manifest()
            manifest["ddl"].append(sql)
            self._write_manifest(manifest)

    def drop_table(self, table_id: int) -> None:
        """Tombstone a table id: recovery and compaction skip its rows
        (the durable analogue of dropping the object's state)."""
        with self._mlock:
            manifest = self._read_manifest()
            if table_id not in manifest["dropped_tables"]:
                manifest["dropped_tables"].append(table_id)
                self._write_manifest(manifest)

    def ddl(self) -> list[str]:
        with self._mlock:
            return list(self._read_manifest().get("ddl", []))

    def _fold(self, segments: list, dropped: set) -> dict:
        tables: dict[int, dict[bytes, bytes]] = {}
        for name in segments:
            for table_id, buf in self._read_segment(name).items():
                if table_id in dropped:
                    continue
                tbl = tables.setdefault(table_id, {})
                for k, v in buf.items():
                    if v is None:
                        tbl.pop(k, None)
                    else:
                        tbl[k] = v
        return tables

    def load_tables(self) -> tuple[int, dict[int, dict[bytes, bytes]]]:
        """Replay all manifest-referenced segments in commit order.

        A concurrent compactor (this process's or another reader-turned-
        writer on the same directory) may delete a base segment between our
        manifest read and the segment read. Segments are immutable and the
        manifest swap is atomic, so re-reading the manifest and replaying
        converges — retry instead of surfacing FileNotFoundError."""
        for attempt in range(8):
            with self._mlock:
                manifest = self._read_manifest()
            try:
                tables = self._fold(manifest["segments"],
                                    set(manifest["dropped_tables"]))
                return manifest["committed_epoch"], tables
            except FileNotFoundError:
                if attempt == 7:   # still racing: surface the real error
                    raise
        raise AssertionError("unreachable")

    # -- compaction (background, off the barrier path) ------------------------
    # (reference: the standalone compactor worker; compaction tasks run
    #  concurrently with checkpoints, src/storage/compactor/src/server.rs:57)

    def _spawn_compact(self) -> None:
        t = self._compact_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._compact_guarded, daemon=True,
                             name="checkpoint-compactor")
        self._compact_thread = t
        t.start()

    def _compact_guarded(self) -> None:
        try:
            self.compact()
        except Exception as e:   # never fatal: old segments remain valid,
            import sys           # but a persistent failure must be visible
            sys.stderr.write(
                f"checkpoint compaction failed (segments keep "
                f"accumulating until it succeeds): {e!r}\n")

    def wait_compaction(self) -> None:
        """Join any in-flight background fold (tests / orderly shutdown)."""
        t = self._compact_thread
        if t is not None and t.is_alive():
            t.join()

    def compact(self) -> None:
        """Fold segments into one (the stand-in for LSM compaction);
        dropped tables' rows are discarded in the fold.

        Safe concurrently with ``append_epoch``: the fold works on a
        SNAPSHOT of the segment list (segments are immutable and appends
        only add), and the manifest swap under the lock keeps any segments
        appended meanwhile."""
        with self._fold_lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        # Like load_tables, the fold can race a CROSS-process compactor
        # deleting base segments after our manifest read — re-read and
        # retry; segments are immutable so a retry converges.
        for attempt in range(8):
            with self._mlock:
                manifest = self._read_manifest()
                base = list(manifest["segments"])
                dropped = set(manifest["dropped_tables"])
                epoch = manifest["committed_epoch"]
            if len(base) <= 1:
                return
            try:
                tables = self._fold(base, dropped)
                break
            except FileNotFoundError:
                if attempt == 7:
                    raise
        # _compact_seq is process-local and resets on restart, and a plain
        # exists-probe would be check-then-write racy across processes: a
        # per-process random token makes the folded name unique, so no fold
        # (post-restart or concurrent) can overwrite a live segment.
        self._compact_seq += 1
        import uuid
        name = (f"epoch_{epoch:012d}.c{self._compact_seq}"
                f"-{uuid.uuid4().hex[:8]}.compacted.seg")
        self._write_segment(name, {t: dict(b) for t, b in tables.items()})
        with self._mlock:
            manifest = self._read_manifest()
            base_set = set(base)
            manifest["segments"] = [name] + [
                s for s in manifest["segments"] if s not in base_set]
            self._write_manifest(manifest)
        for n in base:
            if n != name:
                self.store.delete(n)


# -- vnode-migration handoff segments (elastic scaling plane) ----------------
# A live rescale (meta/rescale.py, docs/scaling.md) moves only the vnode
# ranges whose owner changes. The SOURCE worker writes each moving
# range's committed rows as ONE handoff segment on shared storage (the
# same wire format as checkpoint segments) and the migration protocol
# hands the DESTINATION a *reference* — the path — instead of shipping
# rows through the session or replaying sources (reference: scale.rs:657
# moving Hummock SST references between parallel units).


def write_handoff(path: str,
                  deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
    """Durably write one handoff segment (fsync before rename so a ref
    never names a torn object)."""
    payload = CheckpointLog._encode_segment(deltas)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_handoff(path: str) -> dict[int, dict[bytes, Optional[bytes]]]:
    with open(path, "rb") as f:
        return CheckpointLog._decode_segment(f.read())


class DurableStateStore(MemoryStateStore):
    """MemoryStateStore whose epoch commits are persisted through a
    CheckpointLog; a fresh instance over the same directory recovers the
    committed state (reference: StateStoreImpl selecting the Hummock backend,
    src/storage/src/store_impl.rs:49-64)."""

    def __init__(self, data_dir: Optional[str] = None,
                 object_store: Optional[ObjectStore] = None,
                 compact_after: Optional[int] = None,
                 retry_policy=None,
                 recover_at: Optional[int] = None):
        super().__init__()
        self.log = CheckpointLog(data_dir, object_store=object_store,
                                 compact_after=compact_after,
                                 retry_policy=retry_policy)
        self._prepared_epochs: set[int] = set()
        # off-critical-path checkpoint encode (pipelined tick): at most
        # ONE deferred commit in flight; ordering is preserved by
        # joining before starting the next (segments are replayed in
        # manifest order, so a later epoch's segment must never land
        # without its predecessor)
        self._commit_thread: Optional[threading.Thread] = None
        self._commit_error: Optional[BaseException] = None
        if self.log.exists():
            if recover_at is not None:
                # spanning-job recovery: the session names the epoch the
                # CLUSTER decided; prepared-but-uncommitted epochs up to
                # it roll forward, later ones are discarded — every
                # participant recovers the same cut
                self.log.settle_prepared(recover_at)
            epoch, tables = self.log.load_tables()
            self._committed = tables
            self.committed_epoch = epoch

    def _pending_deltas(self, epoch: int) -> dict:
        deltas: dict[int, dict[bytes, Optional[bytes]]] = {}
        for e in sorted(k for k in self._pending if k <= epoch):
            for table_id, buf in self._pending[e].items():
                deltas.setdefault(table_id, {}).update(buf)
        return deltas

    def prepare(self, epoch: int) -> None:
        """Phase 1 of the cluster checkpoint: durably stage pending
        deltas ≤ ``epoch`` (the in-memory view is untouched; ``commit``
        later applies and publishes them)."""
        if epoch <= self.committed_epoch or epoch in self._prepared_epochs:
            return
        self.join_commits()          # manifest ops stay strictly ordered
        from ..common.barrier_ledger import timed_stage
        from ..common.tracing import CAT_STORAGE, trace_span
        deltas = self._pending_deltas(epoch)
        with trace_span("DurableStateStore.prepare", CAT_STORAGE,
                        epoch=epoch, tid="storage", tables=len(deltas)), \
                timed_stage(epoch, "storage_prepare"):
            self.log.prepare_epoch(epoch, deltas)
        self._prepared_epochs.add(epoch)

    def commit_async(self, epoch: int) -> None:
        """Commit ``epoch`` with the delta serialization + segment/
        manifest IO on a worker thread (the pipelined tick's
        off-critical-path checkpoint encode). The in-memory commit
        applies HERE, synchronously — readers see the epoch at once —
        while durability lands in the background and is joined at the
        next commit, at ``join_commits()`` (the session calls it before
        any 2PC phase-2 frame and on FLUSH/close), or at the next
        synchronous commit. A crash before the join recovers at the
        previous checkpoint and replays deterministically — the same
        window as crashing just before a synchronous commit. 2PC
        participants (prepared epochs) stay fully synchronous: their
        durability IS the phase-1 ack."""
        if epoch <= self.committed_epoch:
            return
        self.join_commits()          # strict segment ordering + errors
        if any(e <= epoch for e in self._prepared_epochs):
            self.commit(epoch)
            return
        deltas = self._pending_deltas(epoch)
        MemoryStateStore.commit(self, epoch)
        from ..common.tracing import CAT_STORAGE, trace_span

        def _encode_and_publish() -> None:
            from ..common.barrier_ledger import timed_stage
            try:
                with trace_span("DurableStateStore.commit_async",
                                CAT_STORAGE, epoch=epoch, tid="storage",
                                tables=len(deltas)), \
                        timed_stage(epoch, "storage_commit"):
                    self.log.append_epoch(epoch, deltas)
            except BaseException as e:  # noqa: BLE001 - surfaced at join
                self._commit_error = e

        t = threading.Thread(target=_encode_and_publish, daemon=True,
                             name="checkpoint-encode")
        self._commit_thread = t
        t.start()

    def join_commits(self) -> None:
        t = self._commit_thread
        if t is not None and t.is_alive():
            t.join()
        self._commit_thread = None
        err = self._commit_error
        if err is not None:
            self._commit_error = None
            raise RuntimeError(
                "deferred checkpoint encode failed; the epoch is "
                "committed in memory but NOT durable") from err

    def commit(self, epoch: int) -> None:
        if epoch <= self.committed_epoch:
            return
        self.join_commits()
        from ..common.barrier_ledger import timed_stage
        from ..common.tracing import CAT_STORAGE, trace_span
        prepared = {e for e in self._prepared_epochs if e <= epoch}
        if prepared:
            # phase 2: promote the durably staged segment(s); epochs
            # prepared BEYOND this commit (pipelined checkpoints) keep
            # their staged segments for their own commit frames
            with trace_span("DurableStateStore.settle", CAT_STORAGE,
                            epoch=epoch, tid="storage",
                            prepared=len(prepared)), \
                    timed_stage(epoch, "storage_settle"):
                self.log.settle_prepared(epoch, discard_beyond=False)
            self._prepared_epochs -= prepared
        else:
            deltas = self._pending_deltas(epoch)
            with trace_span("DurableStateStore.commit", CAT_STORAGE,
                            epoch=epoch, tid="storage",
                            tables=len(deltas)), \
                    timed_stage(epoch, "storage_commit"):
                self.log.append_epoch(epoch, deltas)
        super().commit(epoch)

    def import_tables(self, deltas: dict[int, dict[bytes, bytes]],
                      epoch: int) -> int:
        """Apply a migration handoff straight into the COMMITTED tier
        (memory + a durable segment): the rows were committed at
        ``epoch`` by their previous owner, so they enter this store as
        already-committed state, not as a pending epoch a later barrier
        must settle. Returns the number of rows imported."""
        deltas = {tid: dict(rows) for tid, rows in deltas.items() if rows}
        if not deltas:
            return 0
        self.join_commits()
        n = 0
        for tid, rows in deltas.items():
            tbl = self._committed.setdefault(tid, {})
            self._keys_dirty.add(tid)
            for k, v in rows.items():
                tbl[k] = v
            n += len(rows)
        self.log.append_epoch(max(epoch, self.committed_epoch), deltas)
        self.committed_epoch = max(self.committed_epoch, epoch)
        return n

    def drop_table(self, table_id: int) -> None:
        self.join_commits()
        super().drop_table(table_id)
        self.log.drop_table(table_id)
