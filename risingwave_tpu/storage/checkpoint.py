"""Durable checkpoint log: epoch-delta segments + manifest on local disk.

The durable tier under MemoryStateStore — the role Hummock's SST upload +
version manifest plays in the reference (reference:
src/storage/src/hummock/sstable/builder.rs:87 SST build,
src/meta/src/hummock/manager/ commit_epoch version bump, docs/checkpoint.md:
26-44 "commit epoch makes sealed state durable"). Deliberately NOT an LSM:
executor state is already merged in device HBM, so each checkpoint writes
one compact *delta segment* (the rows dirtied since the previous checkpoint,
already deduplicated per key) and recovery is a linear replay of segments —
compaction pressure, which Hummock exists to manage, does not arise until
segment counts grow, at which point ``compact()`` folds them into one.

Write discipline (crash-safe at every point):
  1. append the segment file (fsync'd),
  2. rewrite the manifest via tmp-file + atomic rename (fsync'd).
A crash between 1 and 2 leaves an orphan segment the manifest never
references — ignored on recovery.

Values inside segments use the process-independent value encoding
(common/row.py: strings as bytes, not dictionary ids), so a fresh process
recovers cleanly.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Optional

from .state_store import MemoryStateStore

_MANIFEST = "manifest.json"


class CheckpointLog:
    def __init__(self, data_dir: str):
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)

    # -- manifest -------------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def exists(self) -> bool:
        return os.path.exists(self._manifest_path())

    def _read_manifest(self) -> dict:
        if not self.exists():
            return {"committed_epoch": 0, "segments": [], "ddl": [],
                    "dropped_tables": []}
        with open(self._manifest_path()) as f:
            m = json.load(f)
        m.setdefault("dropped_tables", [])
        return m

    def _write_manifest(self, manifest: dict) -> None:
        from ..common.failpoint import fail_point
        tmp = self._manifest_path() + ".tmp"
        fail_point("checkpoint.manifest.write")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        fail_point("checkpoint.manifest.rename")
        os.replace(tmp, self._manifest_path())

    # -- segments -------------------------------------------------------------

    def _write_segment(self, name: str,
                       deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
        from ..common.failpoint import fail_point
        fail_point("checkpoint.segment.write")
        path = os.path.join(self.dir, name)
        with open(path, "wb") as f:
            f.write(struct.pack("<I", len(deltas)))
            f.flush()
            # fires AFTER bytes hit the file: simulates a torn segment
            # (crash mid-write). Safe because the manifest that would
            # reference this segment is only written after the segment
            # completes — recovery never reads an unreferenced file.
            fail_point("checkpoint.segment.write.partial")
            for table_id, buf in sorted(deltas.items()):
                f.write(struct.pack("<II", table_id, len(buf)))
                for k, v in sorted(buf.items()):
                    f.write(struct.pack("<H", len(k)))
                    f.write(k)
                    if v is None:
                        f.write(b"\x00")
                    else:
                        f.write(b"\x01")
                        f.write(struct.pack("<I", len(v)))
                        f.write(v)
            f.flush()
            os.fsync(f.fileno())

    def _read_segment(self, name: str) -> dict[int, dict[bytes, Optional[bytes]]]:
        with open(os.path.join(self.dir, name), "rb") as f:
            data = f.read()
        pos = 0
        (n_tables,) = struct.unpack_from("<I", data, pos)
        pos += 4
        out: dict[int, dict[bytes, Optional[bytes]]] = {}
        for _ in range(n_tables):
            table_id, n = struct.unpack_from("<II", data, pos)
            pos += 8
            buf: dict[bytes, Optional[bytes]] = {}
            for _ in range(n):
                (klen,) = struct.unpack_from("<H", data, pos)
                pos += 2
                k = data[pos:pos + klen]
                pos += klen
                live = data[pos]
                pos += 1
                if live:
                    (vlen,) = struct.unpack_from("<I", data, pos)
                    pos += 4
                    buf[k] = data[pos:pos + vlen]
                    pos += vlen
                else:
                    buf[k] = None
            out[table_id] = buf
        return out

    # -- public surface -------------------------------------------------------

    # folding threshold: bounds segment-count growth AND the O(segments)
    # manifest rewrite per commit
    COMPACT_AFTER = 64

    def append_epoch(self, epoch: int,
                     deltas: dict[int, dict[bytes, Optional[bytes]]]) -> None:
        manifest = self._read_manifest()
        if deltas:
            name = f"epoch_{epoch:012d}.seg"
            self._write_segment(name, deltas)
            manifest["segments"].append(name)
        # empty delta: bump the committed epoch only (idle FLUSH ticks must
        # not grow the segment list)
        manifest["committed_epoch"] = epoch
        self._write_manifest(manifest)
        if len(manifest["segments"]) > self.COMPACT_AFTER:
            self.compact()

    def log_ddl(self, sql: str) -> None:
        manifest = self._read_manifest()
        manifest["ddl"].append(sql)
        self._write_manifest(manifest)

    def drop_table(self, table_id: int) -> None:
        """Tombstone a table id: recovery and compaction skip its rows
        (the durable analogue of dropping the object's state)."""
        manifest = self._read_manifest()
        if table_id not in manifest["dropped_tables"]:
            manifest["dropped_tables"].append(table_id)
            self._write_manifest(manifest)

    def ddl(self) -> list[str]:
        return list(self._read_manifest().get("ddl", []))

    def load_tables(self) -> tuple[int, dict[int, dict[bytes, bytes]]]:
        """Replay all manifest-referenced segments in commit order."""
        manifest = self._read_manifest()
        dropped = set(manifest["dropped_tables"])
        tables: dict[int, dict[bytes, bytes]] = {}
        for name in manifest["segments"]:
            for table_id, buf in self._read_segment(name).items():
                if table_id in dropped:
                    continue
                tbl = tables.setdefault(table_id, {})
                for k, v in buf.items():
                    if v is None:
                        tbl.pop(k, None)
                    else:
                        tbl[k] = v
        return manifest["committed_epoch"], tables

    def compact(self) -> None:
        """Fold all segments into one (the stand-in for LSM compaction);
        dropped tables' rows are discarded in the fold."""
        manifest = self._read_manifest()
        if len(manifest["segments"]) <= 1:
            return
        epoch, tables = self.load_tables()   # already filters dropped ids
        name = f"epoch_{epoch:012d}.compacted.seg"
        self._write_segment(name, {t: dict(b) for t, b in tables.items()})
        old = manifest["segments"]
        manifest["segments"] = [name]
        self._write_manifest(manifest)
        for n in old:
            if n != name:
                try:
                    os.remove(os.path.join(self.dir, n))
                except OSError:
                    pass


class DurableStateStore(MemoryStateStore):
    """MemoryStateStore whose epoch commits are persisted through a
    CheckpointLog; a fresh instance over the same directory recovers the
    committed state (reference: StateStoreImpl selecting the Hummock backend,
    src/storage/src/store_impl.rs:49-64)."""

    def __init__(self, data_dir: str):
        super().__init__()
        self.log = CheckpointLog(data_dir)
        if self.log.exists():
            epoch, tables = self.log.load_tables()
            self._committed = tables
            self.committed_epoch = epoch

    def commit(self, epoch: int) -> None:
        if epoch <= self.committed_epoch:
            return
        deltas: dict[int, dict[bytes, Optional[bytes]]] = {}
        for e in sorted(k for k in self._pending if k <= epoch):
            for table_id, buf in self._pending[e].items():
                deltas.setdefault(table_id, {}).update(buf)
        self.log.append_epoch(epoch, deltas)
        super().commit(epoch)

    def drop_table(self, table_id: int) -> None:
        super().drop_table(table_id)
        self.log.drop_table(table_id)
