"""StateTable — the relational view over the state store.

Counterpart of the reference's ``StateTable``
(reference: src/stream/src/common/table/state_table.rs:62,520,667-686,783):
pk-addressed row storage with buffered writes that become visible at
``commit(epoch)``. In the TPU design executors keep *hot* state on device and
use the StateTable as the durable tier: they write dirty deltas here on
barriers, and reload on recovery (`scan_all` → device bulk-insert).

Rows are stored as physical-value tuples; pk columns are memcomparable-
encoded so iteration order == pk order.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence

from ..common.row import decode_value_row, encode_key, encode_value_row
from ..common.types import Schema
from .state_store import MemoryStateStore


class StateTable:
    def __init__(
        self,
        store: MemoryStateStore,
        table_id: int,
        schema: Schema,
        pk_indices: Sequence[int],
    ) -> None:
        self.store = store
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        self._pk_types = tuple(schema[i].type for i in self.pk_indices)
        self._puts: dict[bytes, tuple] = {}
        self._puts_enc: dict[bytes, bytes] = {}   # pre-encoded (native path)
        self._dels: set[bytes] = set()

    # -- key helpers ----------------------------------------------------------

    def key_of(self, row: Sequence[Any]) -> bytes:
        return encode_key([row[i] for i in self.pk_indices], self._pk_types)

    # -- buffered writes (MemTable semantics) ---------------------------------

    def insert(self, row: Sequence[Any]) -> None:
        k = self.key_of(row)
        self._dels.discard(k)
        self._puts_enc.pop(k, None)
        self._puts[k] = tuple(row)

    def delete(self, row: Sequence[Any]) -> None:
        k = self.key_of(row)
        self._puts.pop(k, None)
        self._puts_enc.pop(k, None)
        self._dels.add(k)

    def stage_encoded(self, puts: dict, dels: Sequence[bytes]) -> None:
        """Batch-staged rows already in durable form — the native
        checkpoint fast path (native/rowcodec.cpp): keys are memcomparable
        bytes, values are value-encoded bytes. Semantically identical to
        insert()/delete() row by row."""
        for k in dels:
            self._puts.pop(k, None)
            self._puts_enc.pop(k, None)
            self._dels.add(k)
        for k, v in puts.items():
            self._dels.discard(k)
            self._puts.pop(k, None)
            self._puts_enc[k] = v

    def update(self, old_row: Sequence[Any], new_row: Sequence[Any]) -> None:
        ko, kn = self.key_of(old_row), self.key_of(new_row)
        if ko != kn:
            self.delete(old_row)
        self.insert(new_row)

    def commit(self, epoch: int) -> None:
        """Hand the buffered epoch delta to the store (visible after the
        store-level commit of this epoch). Rows cross the table/store
        boundary as value-encoded bytes — the store is an opaque KV tier,
        and the durable backend persists process-independent bytes
        (reference: value encoding at the table layer, state_table.rs:62)."""
        if self._puts or self._puts_enc or self._dels:
            encoded = {
                k: encode_value_row(v, self.schema.types)
                for k, v in self._puts.items()
            }
            encoded.update(self._puts_enc)
            self.store.ingest(self.table_id, epoch, encoded, self._dels)
            self._puts, self._puts_enc, self._dels = {}, {}, set()

    def is_dirty(self) -> bool:
        return bool(self._puts or self._puts_enc or self._dels)

    # -- reads (committed + own uncommitted buffer) ---------------------------

    def get_row(self, pk_values: Sequence[Any]) -> Optional[tuple]:
        k = encode_key(list(pk_values), self._pk_types)
        if k in self._dels:
            return None
        if k in self._puts:
            return self._puts[k]
        if k in self._puts_enc:
            return decode_value_row(self._puts_enc[k], self.schema.types)
        v = self.store.get(self.table_id, k)
        return None if v is None else decode_value_row(v, self.schema.types)

    def scan_all(self) -> Iterator[tuple]:
        """Committed rows merged with the uncommitted buffer, pk order."""
        merged: dict[bytes, Optional[Any]] = {
            k: decode_value_row(v, self.schema.types)
            for k, v in self.store.iter_table(self.table_id)
        }
        for k in self._dels:
            merged.pop(k, None)
        merged.update({
            k: decode_value_row(v, self.schema.types)
            for k, v in self._puts_enc.items()})
        merged.update(self._puts)
        for k in sorted(merged):
            v = merged[k]
            if v is not None:
                yield v

    def scan_after(self, after_key: Optional[bytes],
                   limit: int) -> tuple[list[tuple], Optional[bytes]]:
        """Up to ``limit`` rows with encoded pk > ``after_key``, in key
        order, plus the last key read (the resumable backfill cursor —
        reference: snapshot-read chunks, executor/backfill.rs:48-69).
        Reads the CURRENT merged view, so each call observes updates
        committed since the last one — exactly the per-epoch re-read the
        reference's backfill relies on for exactly-once.

        Cost per call: O(log n) bisect into the store's cached sorted
        committed keys + O(batch + staged) merge walk — a backfill over a
        large table never re-sorts the whole table per batch."""
        import bisect
        committed = self.store.committed_view(self.table_id)
        skeys = self.store.sorted_committed_keys(self.table_id)
        # staged overlay (pending epochs + this instance's buffer): small
        # between checkpoints; None = delete
        overlay: dict[bytes, Optional[Any]] = {}
        for e in sorted(self.store._pending):
            overlay.update(self.store._pending[e].get(self.table_id, {}))
        overlay.update(self._puts_enc)
        overlay.update(self._puts)
        raw = set(self._puts)
        for k in self._dels:
            overlay[k] = None
        okeys = sorted(k for k in overlay
                       if after_key is None or k > after_key)
        i = (bisect.bisect_right(skeys, after_key)
             if after_key is not None else 0)
        j = 0
        out: list[tuple] = []
        last = after_key
        while len(out) < limit and (i < len(skeys) or j < len(okeys)):
            ck = skeys[i] if i < len(skeys) else None
            ok = okeys[j] if j < len(okeys) else None
            if ok is None or (ck is not None and ck < ok):
                k, v = ck, committed[ck]
                is_raw = False
                i += 1
            else:
                if ck == ok:
                    i += 1                 # overlay shadows committed
                k, v = ok, overlay[ok]
                is_raw = k in raw
                j += 1
            last = k
            if v is None:
                continue
            out.append(v if is_raw
                       else decode_value_row(v, self.schema.types))
        return out, last

    def scan_prefix(self, prefix_values: Sequence[Any], n_cols: int) -> Iterator[tuple]:
        """Rows whose encoded pk starts with the first ``n_cols`` pk
        columns' encoding, in key order. O(log n) bisect into the store's
        sorted committed keys + the (small) staged overlay — the join
        cold-tier fault-in path calls this per faulted key."""
        import bisect
        prefix = encode_key(list(prefix_values), self._pk_types[:n_cols])
        committed = self.store.committed_view(self.table_id)
        skeys = self.store.sorted_committed_keys(self.table_id)
        merged: dict[bytes, Optional[Any]] = {}
        i = bisect.bisect_left(skeys, prefix)
        while i < len(skeys) and skeys[i].startswith(prefix):
            merged[skeys[i]] = decode_value_row(
                committed[skeys[i]], self.schema.types)
            i += 1
        for e in sorted(self.store._pending):
            for k, v in self.store._pending[e].get(self.table_id, {}).items():
                if k.startswith(prefix):
                    merged[k] = (None if v is None
                                 else decode_value_row(v, self.schema.types))
        for k, v in self._puts_enc.items():
            if k.startswith(prefix):
                merged[k] = decode_value_row(v, self.schema.types)
        for k, v in self._puts.items():
            if k.startswith(prefix):
                merged[k] = v
        for k in self._dels:
            if k.startswith(prefix):
                merged[k] = None
        for k in sorted(merged):
            v = merged[k]
            if v is not None:
                yield v

    def __len__(self) -> int:
        n = self.store.table_len(self.table_id)
        new_puts = sum(
            1 for k in (*self._puts, *self._puts_enc)
            if self.store.get(self.table_id, k) is None)
        dead = sum(1 for k in self._dels if self.store.get(self.table_id, k) is not None)
        return n + new_puts - dead
