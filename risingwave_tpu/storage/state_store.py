"""Epoch-versioned state store (host tier).

Counterpart of the reference's ``StateStore`` trait family
(reference: src/storage/src/store.rs:87-110,163-180,215,264) with the
Memory backend (src/storage/src/memory.rs) as the first implementation. In
the TPU design the store is the *truth tier under the device state*: executor
state lives in HBM and is flushed here on checkpoint barriers; recovery
reloads it (SURVEY.md §7 "JoinHashMap / AggGroup LRU over Hummock" row).

Semantics kept from the reference:
  * writes are buffered per epoch and become visible atomically at
    ``commit(epoch)`` (MemTable → shared-buffer semantics),
  * reads see the latest committed epoch,
  * ``checkpoint(epoch)`` materialises a named durable snapshot; the
    checkpoint manager persists it (storage/checkpoint.py).
"""

from __future__ import annotations

import copy
from typing import Any, Iterator, Optional


class MemoryStateStore:
    """Process-local multi-table KV store with epoch commit.

    Keys are ``(table_id, key_bytes)``; values are opaque bytes (StateTable
    value-encodes rows at this boundary). Not thread-safe; the
    single-process runtime drives it from one event loop, matching the
    per-CN LocalStateStore usage. ``DurableStateStore``
    (storage/checkpoint.py) persists commits to disk.
    """

    def __init__(self) -> None:
        self._committed: dict[int, dict[bytes, tuple]] = {}
        self._pending: dict[int, dict[int, dict[bytes, Optional[tuple]]]] = {}
        self.committed_epoch: int = 0
        # per-table sorted committed-key cache (range scans / backfill):
        # rebuilt lazily after a commit touches the table
        self._sorted_keys: dict[int, list] = {}
        self._keys_dirty: set[int] = set()

    # -- write path -----------------------------------------------------------

    def ingest(self, table_id: int, epoch: int,
               puts: dict[bytes, tuple], deletes: set[bytes]) -> None:
        buf = self._pending.setdefault(epoch, {}).setdefault(table_id, {})
        for k in deletes:
            buf[k] = None
        buf.update(puts)

    def commit(self, epoch: int) -> None:
        """Atomically apply all writes buffered for epochs ≤ ``epoch``.

        A checkpoint epoch commits every earlier non-checkpoint epoch's
        buffer too, in epoch order — mirroring the reference where
        non-checkpoint barriers stage state that the next checkpoint's
        ``commit_epoch`` makes durable (docs/checkpoint.md:26-44).

        Idempotent per epoch: every executor of an epoch may trigger the
        commit; the first wins (the reference's HummockManager.commit_epoch
        is likewise a single logical commit per epoch)."""
        if epoch <= self.committed_epoch:
            return
        for e in sorted(k for k in self._pending if k <= epoch):
            for table_id, buf in self._pending.pop(e).items():
                tbl = self._committed.setdefault(table_id, {})
                self._keys_dirty.add(table_id)
                for k, v in buf.items():
                    if v is None:
                        tbl.pop(k, None)
                    else:
                        tbl[k] = v
        self.committed_epoch = epoch

    # -- async commit surface (pipelined tick, docs/performance.md) -----------
    # The memory tier commits are dict merges — nothing to offload — so
    # the base implementations are synchronous aliases. DurableStateStore
    # overrides them to hand the committed-delta serialization + segment
    # write to a worker thread (storage/checkpoint.py); every backend
    # answers the same two calls so the session's commit path stays
    # tier-agnostic.

    def commit_async(self, epoch: int) -> None:
        self.commit(epoch)

    def join_commits(self) -> None:
        """Barrier for any deferred commit work (no-op in memory)."""

    # -- read path ------------------------------------------------------------

    def _merged_view(self, table_id: int) -> dict:
        """Read-your-writes view: committed state overlaid with every staged
        (sealed-but-uncommitted) epoch in order — the reference's shared
        buffer makes sealed epochs readable before the checkpoint commits
        them (docs/checkpoint.md:36-44, state visibility vs durability)."""
        view = dict(self._committed.get(table_id, {}))
        for e in sorted(self._pending):
            for k, v in self._pending[e].get(table_id, {}).items():
                if v is None:
                    view.pop(k, None)
                else:
                    view[k] = v
        return view

    def get(self, table_id: int, key: bytes) -> Optional[tuple]:
        for e in sorted(self._pending, reverse=True):
            buf = self._pending[e].get(table_id, {})
            if key in buf:
                return buf[key]
        return self._committed.get(table_id, {}).get(key)

    def iter_table(self, table_id: int) -> Iterator[tuple[bytes, tuple]]:
        yield from sorted(self._merged_view(table_id).items())

    def committed_view(self, table_id: int) -> dict:
        """The committed (checkpointed) rows of a table — the backfill
        range-scan base (staged overlays are applied by the caller)."""
        return self._committed.get(table_id, {})

    def sorted_committed_keys(self, table_id: int) -> list:
        """Sorted committed keys, cached per table and rebuilt only after
        a commit touched the table — keeps range scans O(log n + batch)
        instead of O(n log n) per call."""
        if table_id in self._keys_dirty or table_id not in self._sorted_keys:
            self._sorted_keys[table_id] = sorted(
                self._committed.get(table_id, {}))
            self._keys_dirty.discard(table_id)
        return self._sorted_keys[table_id]

    def iter_prefix(self, table_id: int, prefix: bytes) -> Iterator[tuple[bytes, tuple]]:
        for k, v in self.iter_table(table_id):
            if k.startswith(prefix):
                yield k, v

    def table_len(self, table_id: int) -> int:
        return len(self._merged_view(table_id))


    def drop_table(self, table_id: int) -> None:
        """Free a dropped object's state (committed + pending)."""
        self._committed.pop(table_id, None)
        self._sorted_keys.pop(table_id, None)
        self._keys_dirty.discard(table_id)
        for buf in self._pending.values():
            buf.pop(table_id, None)

    def discard_pending_tables(self, table_ids) -> None:
        """Drop staged-uncommitted buffers for ``table_ids`` only.

        The scoped-recovery primitive (reference: reset_compute_nodes
        clearing the shared buffer, recovery.rs:140): a dead job may have
        staged a torn subset of its tables for an epoch whose checkpoint it
        never completed — those buffers must not ride a later epoch's
        commit. Committed state is untouched."""
        ids = set(table_ids)
        for buf in self._pending.values():
            for tid in ids:
                buf.pop(tid, None)

    # -- snapshot (checkpoint/restore hooks) ----------------------------------

    def snapshot(self) -> dict:
        return {
            "committed_epoch": self.committed_epoch,
            "tables": copy.deepcopy(self._committed),
        }

    def restore(self, snap: dict) -> None:
        self.committed_epoch = snap["committed_epoch"]
        self._committed = copy.deepcopy(snap["tables"])
        self._pending.clear()
        self._sorted_keys.clear()
        self._keys_dirty.clear()
