"""SSTable: sorted immutable runs for the Hummock-lite state tier.

Counterpart of the reference's Hummock SST (reference:
src/storage/src/hummock/sstable/builder.rs:87 block-structured build,
sstable/bloom.rs bloom filter, sstable/mod.rs block index + footer).
Entries are ``(table_id, key) -> value | tombstone`` in strict composite
order; a block index (first composite key + offset per block) gives
point reads one block scan, and a bloom filter over composite keys makes
"not here" answers cheap across a deep L0 stack.

Whole objects move through the ``ObjectStore`` abstraction
(storage/object_store.py) — LocalFs and Mem both work, so the tier is
one backend swap away from cloud object storage, exactly the property
the checkpoint log already has.

Layout (little-endian):

    [entry...]                     concatenated data blocks
    meta JSON (utf-8)              block index, bloom, stats
    <I meta_len> <8s magic>        footer

    entry := <I table_id> <H klen> key <B live> [<I vlen> value]

Binary keys/bloom bits cross into the JSON meta as base64 — the same
debuggable-over-compact tradeoff the wire frames make (rpc/wire.py).
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
from typing import Iterable, Iterator, List, Optional, Tuple

_MAGIC = b"RWSST\x01\x00\x00"
_FOOTER = struct.Struct("<I8s")
_ENTRY_HEAD = struct.Struct("<IH")

Entry = Tuple[int, bytes, Optional[bytes]]      # (table_id, key, value|None)


# -- bloom filter -------------------------------------------------------------

class BloomFilter:
    """Split-hash bloom over composite keys (reference: sstable/bloom.rs).
    k probes are carved out of one blake2b digest; false positives cost a
    wasted block scan, never a wrong answer."""

    K = 7

    def __init__(self, bits: bytearray, k: int = K):
        self.bits = bits
        self.k = k

    @classmethod
    def with_capacity(cls, n_keys: int) -> "BloomFilter":
        # ~10 bits/key ≈ 1% false positives at k=7
        m = max(64, n_keys * 10)
        m = (m + 7) // 8 * 8
        return cls(bytearray(m // 8))

    def _probes(self, table_id: int, key: bytes) -> Iterator[int]:
        h = hashlib.blake2b(struct.pack("<I", table_id) + key,
                            digest_size=4 * self.k).digest()
        m = len(self.bits) * 8
        for i in range(self.k):
            yield struct.unpack_from("<I", h, 4 * i)[0] % m

    def add(self, table_id: int, key: bytes) -> None:
        for p in self._probes(table_id, key):
            self.bits[p // 8] |= 1 << (p % 8)

    def may_contain(self, table_id: int, key: bytes) -> bool:
        return all(self.bits[p // 8] & (1 << (p % 8))
                   for p in self._probes(table_id, key))

    def to_b64(self) -> str:
        return base64.b64encode(bytes(self.bits)).decode()

    @classmethod
    def from_b64(cls, s: str, k: int) -> "BloomFilter":
        return cls(bytearray(base64.b64decode(s)), k)


# -- builder ------------------------------------------------------------------

def _pack_entry(table_id: int, key: bytes, value: Optional[bytes]) -> bytes:
    head = _ENTRY_HEAD.pack(table_id, len(key)) + key
    if value is None:
        return head + b"\x00"
    return head + b"\x01" + struct.pack("<I", len(value)) + value


class SstBuilder:
    """Streaming builder: feed strictly increasing ``(table_id, key)``
    entries, get immutable bytes. Tombstones (value=None) are kept — a
    run must shadow older runs' rows until bottom-level compaction."""

    def __init__(self, block_target_bytes: int = 4096):
        self.block_target = block_target_bytes
        self._parts: List[bytes] = []
        self._size = 0
        self._block_start = 0
        self._block_first: Optional[Tuple[int, str]] = None
        self._index: List[dict] = []     # {table, key(b64), off, len}
        self._keys: List[Tuple[int, bytes]] = []
        self._last: Optional[Tuple[int, bytes]] = None
        self.n_entries = 0
        self.n_tombstones = 0
        self._tables: set = set()

    def add(self, table_id: int, key: bytes, value: Optional[bytes]) -> None:
        ck = (table_id, key)
        if self._last is not None and ck <= self._last:
            raise ValueError(
                f"SST entries must be strictly increasing: {ck!r} after "
                f"{self._last!r}")
        self._last = ck
        if self._block_first is None:
            self._block_first = (table_id,
                                 base64.b64encode(key).decode())
            self._block_start = self._size
        rec = _pack_entry(table_id, key, value)
        self._parts.append(rec)
        self._size += len(rec)
        self._keys.append(ck)
        self.n_entries += 1
        if value is None:
            self.n_tombstones += 1
        self._tables.add(table_id)
        if self._size - self._block_start >= self.block_target:
            self._seal_block()

    def _seal_block(self) -> None:
        if self._block_first is None:
            return
        self._index.append({
            "table": self._block_first[0], "key": self._block_first[1],
            "off": self._block_start,
            "len": self._size - self._block_start,
        })
        self._block_first = None

    def finish(self) -> bytes:
        self._seal_block()
        bloom = BloomFilter.with_capacity(self.n_entries)
        for t, k in self._keys:
            bloom.add(t, k)
        first = self._keys[0] if self._keys else None
        last = self._keys[-1] if self._keys else None
        meta = {
            "n_entries": self.n_entries,
            "n_tombstones": self.n_tombstones,
            "tables": sorted(self._tables),
            "first": ([first[0], base64.b64encode(first[1]).decode()]
                      if first else None),
            "last": ([last[0], base64.b64encode(last[1]).decode()]
                     if last else None),
            "index": self._index,
            "bloom": bloom.to_b64(),
            "bloom_k": bloom.k,
        }
        meta_b = json.dumps(meta).encode()
        return (b"".join(self._parts) + meta_b
                + _FOOTER.pack(len(meta_b), _MAGIC))


def build_sst(entries: Iterable[Entry],
              block_target_bytes: int = 4096) -> bytes:
    """One-shot build from an iterable already in composite-key order."""
    b = SstBuilder(block_target_bytes)
    for table_id, key, value in entries:
        b.add(table_id, key, value)
    return b.finish()


# -- reader -------------------------------------------------------------------

class CorruptSst(ValueError):
    pass


class Sstable:
    """Immutable reader over one SST's bytes. ``lookup`` answers
    (found, value|None-for-tombstone); iteration yields raw entries in
    composite order (the compactor's merge input)."""

    def __init__(self, data: bytes, name: str = "<sst>"):
        self.name = name
        self._data = data
        if len(data) < _FOOTER.size:
            raise CorruptSst(f"{name}: truncated footer")
        meta_len, magic = _FOOTER.unpack_from(data, len(data) - _FOOTER.size)
        if magic != _MAGIC:
            raise CorruptSst(f"{name}: bad magic {magic!r}")
        meta_end = len(data) - _FOOTER.size
        if meta_len > meta_end:
            raise CorruptSst(f"{name}: meta overruns object")
        self.meta = json.loads(data[meta_end - meta_len:meta_end])
        self._data_end = meta_end - meta_len
        self._index: List[Tuple[Tuple[int, bytes], int, int]] = [
            ((e["table"], base64.b64decode(e["key"])), e["off"], e["len"])
            for e in self.meta["index"]
        ]
        # bisect target for point reads (avoids rebuilding per lookup)
        self._firsts = [e[0] for e in self._index]
        self.bloom = BloomFilter.from_b64(self.meta["bloom"],
                                          self.meta.get("bloom_k",
                                                        BloomFilter.K))

    # range/meta accessors ----------------------------------------------------

    @property
    def n_entries(self) -> int:
        return self.meta["n_entries"]

    @property
    def table_ids(self) -> List[int]:
        return list(self.meta["tables"])

    def key_range(self) -> Optional[Tuple[Tuple[int, bytes],
                                          Tuple[int, bytes]]]:
        f, l = self.meta["first"], self.meta["last"]
        if f is None:
            return None
        return ((f[0], base64.b64decode(f[1])),
                (l[0], base64.b64decode(l[1])))

    # reads -------------------------------------------------------------------

    def _parse_block(self, off: int, length: int) -> Iterator[Entry]:
        data = self._data
        pos, end = off, off + length
        if end > self._data_end:
            raise CorruptSst(f"{self.name}: block overruns data area")
        while pos < end:
            table_id, klen = _ENTRY_HEAD.unpack_from(data, pos)
            pos += _ENTRY_HEAD.size
            key = data[pos:pos + klen]
            pos += klen
            live = data[pos]
            pos += 1
            if live:
                (vlen,) = struct.unpack_from("<I", data, pos)
                pos += 4
                yield table_id, key, data[pos:pos + vlen]
                pos += vlen
            else:
                yield table_id, key, None

    def may_contain(self, table_id: int, key: bytes) -> bool:
        return self.bloom.may_contain(table_id, key)

    def lookup(self, table_id: int,
               key: bytes) -> Tuple[bool, Optional[bytes]]:
        """(found, value). found=True with value=None is a tombstone —
        the caller must STOP searching older runs."""
        if not self._index or not self.may_contain(table_id, key):
            return False, None
        import bisect
        ck = (table_id, key)
        i = bisect.bisect_right(self._firsts, ck) - 1
        if i < 0:
            return False, None
        _, off, length = self._index[i]
        for t, k, v in self._parse_block(off, length):
            if (t, k) == ck:
                return True, v
            if (t, k) > ck:
                break
        return False, None

    def iter_entries(self) -> Iterator[Entry]:
        for _, off, length in self._index:
            yield from self._parse_block(off, length)

    def __len__(self) -> int:
        return self.n_entries


def load_sst(store, name: str) -> Sstable:
    """Fetch + parse one SST through the ObjectStore abstraction."""
    data = store.get(name)
    if data is None:
        raise FileNotFoundError(name)
    return Sstable(data, name)


def merge_iter(runs: List[Sstable]) -> Iterator[Entry]:
    """k-way merge of runs ordered NEWEST FIRST: for duplicate composite
    keys the newest run wins (the compactor core; reference:
    hummock/compactor/ merge iterators). Tombstones pass through — the
    caller decides whether the output level may drop them."""
    import heapq
    iters = [iter(r.iter_entries()) for r in runs]
    heap: List[Tuple[Tuple[int, bytes], int, Optional[bytes]]] = []
    for rank, it in enumerate(iters):
        e = next(it, None)
        if e is not None:
            heapq.heappush(heap, ((e[0], e[1]), rank, e[2]))
    last: Optional[Tuple[int, bytes]] = None
    while heap:
        ck, rank, value = heapq.heappop(heap)
        e = next(iters[rank], None)
        if e is not None:
            heapq.heappush(heap, ((e[0], e[1]), rank, e[2]))
        if ck == last:
            continue                    # older run's row: shadowed
        last = ck
        yield ck[0], ck[1], value
